"""Offline run report: `python -m ape_x_dqn_tpu.obs.report run.jsonl`.

Summarizes one run's metrics JSONL — the single self-contained
artifact every driver writes — into the questions that matter for an
Ape-X run (SURVEY.md §5, ISSUE 2):

- stage-time breakdown: where host wall-clock went, from the
  `span/<name>` aggregates Obs.publish folds into the stream;
- staleness: sampled-transition-age and actor-parameter-lag
  percentiles from the `hist/<name>` snapshots (the failure mode
  Horgan et al. 2018 §4 and Kapturowski et al. 2019 both name);
- throughput: frames/s, grad-steps/s, totals;
- stall events: every attributed watchdog record.

Stdlib-only on purpose: the report must run anywhere the JSONL can be
copied, with no jax (or even numpy) available.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any

# The canonical instrument table: one row per metric name the runtime
# can emit, keyed by JSONL name with the registry's kind prefix
# (hist/ gauge/ ctr/). apexlint's obs-names checker cross-references
# this table against every emission site in the package, both ways —
# an emitted name missing here, or a row no code emits, is a lint
# failure — so the report can never silently drop a signal a PR adds.
# "warn" rows carry the healthy-range rule printed next to the value
# (and documented in PERF.md "Observability"): Ape-X tolerates replay
# staleness by design, but tails beyond these suggest the learner is
# overrunning ingest (age) or the publish path is wedged (lag).
INSTRUMENTS = {
    "sample_age_steps": {
        "kind": "hist",
        "warn": ("p99", 200_000,
                 "p99 sampled age beyond ~capacity suggests the "
                 "learner free-runs over stale replay")},
    "param_lag_steps": {
        "kind": "hist",
        "warn": ("p99", 1_000,
                 "p99 actor param lag should stay within a few "
                 "publish_every periods")},
    "td_abs": {"kind": "hist"},
    "server_batch_items": {"kind": "hist"},
    "infer_latency_ms": {
        "kind": "hist",
        "warn": ("p99", 100.0,
                 "p99 inference latency beyond ~100ms means actors "
                 "wait on the server more than they step envs — the "
                 "queue is backing up or a compile stole the window")},
    "ingest_staging_occupancy": {"kind": "gauge"},
    "ingest_coalesce_width": {"kind": "gauge"},
    "ingest_decode_ms": {"kind": "gauge"},
    "wire_compression_ratio": {"kind": "gauge"},
    "replay_occupancy": {"kind": "gauge"},
    "server_queue_depth": {
        "kind": "gauge",
        "warn": ("value", 64,
                 "a queue deeper than max_batch at publish time means "
                 "dynamic batching is saturated — requests wait whole "
                 "extra batch rounds")},
    "stall_errors": {"kind": "ctr"},
    "replay_adds": {"kind": "ctr"},
    # fleet telemetry plane (obs/fleet.py)
    "telemetry_frames": {"kind": "ctr"},
    "peer_disconnects": {"kind": "ctr"},
    "fleet_peers": {"kind": "gauge"},
    # elastic fleet runtime (PR 7): supervised recovery + chaos lane
    "supervisor_restarts": {"kind": "ctr"},
    "actor_quarantines": {"kind": "ctr"},
    "peer_stall_events": {"kind": "ctr"},
    "param_pull_errors": {"kind": "ctr"},
    "wire_decode_errors": {"kind": "ctr"},
    # continuous perf plane (obs/profiling.py, ISSUE 8): live roofline
    # gauges per stage (EWMA ms/dispatch + cost-analysis MFU and HBM
    # bandwidth fractions; the compiler FLOP count under-reports convs
    # on this backend, so mfu_* are lower bounds — see PERF.md)
    "mfu_sample_k": {"kind": "gauge"},
    "hbm_bw_frac_sample_k": {"kind": "gauge"},
    "device_ms_sample_k": {"kind": "gauge"},
    "mfu_learn_k": {"kind": "gauge"},
    "hbm_bw_frac_learn_k": {"kind": "gauge"},
    "device_ms_learn_k": {"kind": "gauge"},
    "mfu_train": {"kind": "gauge"},
    "hbm_bw_frac_train": {"kind": "gauge"},
    "device_ms_train": {"kind": "gauge"},
    # dist learner's fused dispatch (ISSUE 9): same roofline math,
    # own names so mesh runs never alias single-chip train history
    "mfu_train_dist": {"kind": "gauge"},
    "hbm_bw_frac_train_dist": {"kind": "gauge"},
    "device_ms_train_dist": {"kind": "gauge"},
    # dp-scaling plane (bench.py --multichip + dist driver runs):
    # "value_min" warn rows flag values BELOW the bound (efficiency
    # and fill are healthy when high, unlike every gauge above)
    "dp_scaling_efficiency": {
        "kind": "gauge",
        "warn": ("value_min", 0.5,
                 "scaling efficiency below ~0.5 means over half of "
                 "each added chip is lost to collectives/dispatch "
                 "overhead — on shared-host virtual devices that is "
                 "expected contention, on real chips it is a regression "
                 "(PERF.md 'Multi-chip scaling')")},
    "replay_shard_fill_min": {"kind": "gauge"},
    "replay_shard_fill_max": {"kind": "gauge"},
    "hbm_bw_frac_ingest": {"kind": "gauge"},
    "device_ms_ingest": {"kind": "gauge"},
    "ingest_ship_ms": {"kind": "gauge"},
    # compile telemetry: per-publish compile deltas + the monotonic
    # per-process executable count whose growth precedes the known XLA
    # teardown SIGSEGV (tests/run_chunked.sh exists because of it)
    "jit_compiles": {"kind": "ctr"},
    "jit_compile_ms": {"kind": "ctr"},
    "compile_cache_entries": {
        "kind": "gauge",
        "warn": ("value", 2000,
                 "a long-lived process past ~2000 backend compiles is "
                 "in the XLA accumulation regime that has segfaulted "
                 "CPU clients at teardown — split the workload "
                 "(run_chunked.sh) or hunt the shape churn")},
    # perf-regression engine: EWMA throughput baselines + warn-only
    # degradation events (each event is an attributed JSONL record)
    "perf_degradations": {"kind": "ctr"},
    "ewma_grad_steps_per_s": {"kind": "gauge"},
    "ewma_env_fps": {"kind": "gauge"},
    "ewma_ingest_rows_per_s": {"kind": "gauge"},
    # learning-health plane (obs/learning.py, ISSUE 10): in-graph
    # diagnostics computed inside the learner jits, host-read only at
    # existing sync points. The four warn rows mirror LearnMonitor's
    # absolute rules exactly (Q_MAX_LIMIT / UPDATE_RATIO_MIN /
    # ESS_FRAC_MIN / TOP_FRAC_MAX) so the offline report flags the same
    # lines the online engine fires on. Per-tenant duplicates ride
    # dynamic `learn/<env_id>/<name>` keys (regrouped by summarize(),
    # invisible to lint by design — same policy as peer/ keys).
    "learn_td_abs_p50": {"kind": "gauge"},
    "learn_td_abs_p90": {"kind": "gauge"},
    "learn_td_abs_p99": {"kind": "gauge"},
    "learn_td_signed_mean": {"kind": "gauge"},
    "learn_q_mean": {"kind": "gauge"},
    "learn_q_max": {
        "kind": "gauge",
        "warn": ("value", 1_000.0,
                 "|q_max| beyond ~1e3 in clipped-reward units is Q "
                 "divergence — check lr, target sync cadence, and the "
                 "overestimation gap trend")},
    "learn_target_q_mean": {"kind": "gauge"},
    "learn_q_gap": {"kind": "gauge"},
    "learn_grad_norm": {"kind": "gauge"},
    "learn_update_ratio": {
        "kind": "gauge",
        "warn": ("value_min", 1e-9,
                 "||update||/||params|| below ~1e-9 means the optimizer "
                 "is effectively frozen — dead gradients or a crushed "
                 "lr schedule")},
    "learn_is_ess_frac": {
        "kind": "gauge",
        "warn": ("value_min", 0.05,
                 "IS effective sample size below 5% of the batch means "
                 "a handful of transitions dominate every update — "
                 "beta/alpha pathology")},
    "learn_priority_top_frac": {
        "kind": "gauge",
        "warn": ("value", 0.5,
                 "one transition holding over half the priority mass "
                 "means the sampler has collapsed onto a single "
                 "outlier")},
    "learn_sample_age_p50": {"kind": "gauge"},
    "learn_sample_age_p90": {"kind": "gauge"},
    "learn_prio_staleness_frac": {"kind": "gauge"},
    "learn_shard_td_mean_min": {"kind": "gauge"},
    "learn_shard_td_mean_max": {"kind": "gauge"},
    "learn_loss": {"kind": "hist"},
    "learning_degradations": {"kind": "ctr"},
    # tiered cold replay (replay/cold_store.py, ISSUE 11): host-RAM
    # compressed segments behind the device ring. cold_bytes /
    # cold_segments track resident footprint; the ratio's floor is 1.0
    # by construction (per-leaf never-inflate guard in
    # packing.cold_pack + the store's explicit clamp), so a reading
    # below it means the clamp was bypassed — a codec regression, not
    # a workload property.
    "cold_segments": {"kind": "gauge"},
    "cold_bytes": {"kind": "gauge"},
    "cold_compression_ratio": {
        "kind": "gauge",
        "warn": ("value_min", 1.0,
                 "cold compression ratio below 1.0 should be "
                 "impossible (never-inflate guard stores raw leaves) — "
                 "a reading here means the cold codec is inflating "
                 "data and its guard is broken")},
    "cold_evictions": {"kind": "ctr"},
    "cold_recalls": {"kind": "ctr"},
    # cold-door outcomes (ISSUE 16): every ring eviction either stores,
    # displaces a lighter resident segment, or drops at the door. Drops
    # persistently outrunning displacements means the door is rejecting
    # mass the store has no room to absorb — the thrashing signal the
    # disk rung exists to absorb (check_violations has a bespoke row).
    "cold_dropped": {"kind": "ctr"},
    "cold_displaced": {"kind": "ctr"},
    # disk spill rung (replay/disk_store.py, ISSUE 16): append-only
    # segment files below the host-RAM cold store. Spills ride an async
    # writeback queue off the ingest thread (queue_full counts offers
    # the full queue refused — never waited on); promotions re-enter
    # the RAM store during the idle refill tick. cold_disk_errors is
    # lost-segment IO failures (writeback append / promote read).
    "cold_disk_spills": {"kind": "ctr"},
    "cold_disk_promotions": {"kind": "ctr"},
    "cold_disk_queue_full": {"kind": "ctr"},
    "cold_disk_errors": {"kind": "ctr"},
    "cold_disk_segments": {"kind": "gauge"},
    "cold_disk_transitions": {"kind": "gauge"},
    "cold_disk_bytes": {"kind": "gauge"},
    # multi-tenant serving tier (parallel/inference_server.py, ISSUE
    # 13): admission-controller accounting closes by construction —
    # serve_offered == serve_admitted + serve_shed at quiescence (shed
    # includes deadline expiries; serve_expired counts those
    # separately). Per-tenant duplicates ride dynamic
    # `serve/<tenant>/<stat>` gauge keys (regrouped by summarize(),
    # invisible to lint by design — same policy as learn/ and peer/
    # keys); the per-tenant p99_ms rows are checked against
    # infer_latency_ms's healthy bound in check_violations.
    "serve_offered": {"kind": "ctr"},
    "serve_admitted": {"kind": "ctr"},
    "serve_shed": {"kind": "ctr"},
    "serve_expired": {"kind": "ctr"},
    "serve_tenants": {"kind": "gauge"},
    "serve_backpressure": {"kind": "gauge"},
    "serve_queue_items": {
        "kind": "gauge",
        "warn": ("value", 256,
                 "admission-queue depth beyond queue_slo_items means "
                 "offered load exceeds serving capacity — the "
                 "controller is shedding lower classes and "
                 "backpressuring the transport")},
    # fleet remediation plane (runtime/remediation.py, ISSUE 14): the
    # policy engine that closes the monitor→actuator loop. Outcome
    # counters partition every decision: applied (actuator ran) /
    # observed (dry-run mode) / suppressed (budget) / failed (actuator
    # raised). remediation_mode encodes the configured mode (1=observe,
    # 2=enforce; absent/0 = off). budget_headroom is the live token
    # count of the global actions/min bucket — below 1.0 the engine
    # cannot afford a single non-safety action, which is a health
    # violation only in enforce mode (check_violations gates on the
    # mode gauge).
    "remediation_actions": {"kind": "ctr"},
    "remediation_observed": {"kind": "ctr"},
    "remediation_suppressed": {"kind": "ctr"},
    "remediation_failed": {"kind": "ctr"},
    "remediation_budget_headroom": {
        "kind": "gauge",
        "warn": ("value_min", 1.0,
                 "action-budget headroom below one token means the "
                 "remediation engine is rate-limited out of acting — "
                 "faults are firing faster than "
                 "remediation.budget_per_min allows responses")},
    "remediation_mode": {"kind": "gauge"},
    # forensics plane (obs/blackbox.py + obs/postmortem.py, ISSUE 17):
    # flight-recorder activity counters. Healthy ranges are bespoke
    # rows in check_violations (ctr warns don't fit the single-value
    # rule shapes): a terminal stall/quarantine with no dump on disk
    # fails the check naming the missing peer, and a ring-drop
    # fraction above 1/2 (blackbox_dropped vs blackbox_records) warns
    # that most of the forensic window was overwritten before any dump.
    "blackbox_records": {"kind": "ctr"},
    "blackbox_dumps": {"kind": "ctr"},
    "blackbox_dropped": {"kind": "ctr"},
    "postmortem_bundles": {"kind": "ctr"},
    # shared-memory same-host transport (ISSUE 18): doorbells are
    # slot deliveries on the zero-copy ring; torn slots (crc/seq
    # mismatch — writer died mid-pack or wild write) are counted and
    # freed, NEVER delivered; fallbacks are batches a granted
    # connection still shipped over TCP (ring full / oversize batch).
    # A nonzero torn rate or a fallback-dominated mix means the ring
    # is mis-sized for the batch shape — see README "Shared-memory
    # same-host transport".
    "shm_doorbells": {"kind": "ctr"},
    "shm_torn_slots": {"kind": "ctr"},
    "shm_fallbacks": {"kind": "ctr"},
    "shm_slots_inflight": {"kind": "gauge"},
    # param-plane codec (comm/param_codec.py, ISSUE 19): weight
    # broadcast over TCP as quantized deltas against each subscriber's
    # acked version. bytes_out is the actual wire spend; the ratio is
    # raw-equivalent/wire (cumulative); resyncs count full-blob
    # fallbacks (missed version, epoch bump, window overrun); queue
    # drops count per-subscriber latest-wins supersedes — a steady
    # stream on one peer is a slow subscriber riding resyncs, not a
    # broadcast stall (README "Parameter-plane codec").
    "param_bytes_out": {"kind": "ctr"},
    "param_resyncs": {"kind": "ctr"},
    "param_push_queue_drops": {"kind": "ctr"},
    "param_compression_ratio": {
        "kind": "gauge",
        "warn": ("value_min", 1.0,
                 "param compression ratio below 1.0 should be "
                 "impossible (the codec never-inflates: every delta "
                 "segment and full blob is capped at the raw "
                 "versioned-blob cost) — a reading here means the "
                 "per-leaf or blob-level guard is broken")},
}

# healthy ranges, derived view kept under its historical name (the
# formatting path and PERF.md both refer to HEALTHY)
HEALTHY = {name: row["warn"] for name, row in INSTRUMENTS.items()
           if "warn" in row}


def load_records(path: str) -> list[dict]:
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail line of a killed run
    return records


def summarize(records: list[dict]) -> dict[str, Any]:
    """Fold a record stream into one summary dict. Scalar/snapshot keys
    are last-write-wins (each Obs.publish record carries cumulative
    state); stall events accumulate."""
    latest: dict[str, Any] = {}
    stalls: list[dict] = []
    disconnects: list[dict] = []
    perf_events: list[dict] = []
    learn_events: list[dict] = []
    remediation_events: list[dict] = []
    quarantines: list[dict] = []
    peer_stalls: list[dict] = []
    blackbox_dumps: list[dict] = []
    for rec in records:
        for k, v in rec.items():
            if v is not None:
                latest[k] = v
        if rec.get("learning_degradation") is not None:
            learn_events.append({"step": rec.get("step"),
                                 "rule": rec["learning_degradation"],
                                 "tenant": rec.get("learn_tenant"),
                                 "value": rec.get("learn_value"),
                                 "baseline": rec.get("learn_baseline")})
        if rec.get("stall_component") is not None:
            stalls.append({"step": rec.get("step"),
                           "component": rec["stall_component"],
                           "staleness_s": rec.get("stall_staleness_s"),
                           "note": rec.get("stall_note")})
        if rec.get("peer_disconnect") is not None:
            disconnects.append({"step": rec.get("step"),
                                "peer": rec["peer_disconnect"]})
        if rec.get("actor_quarantined") is not None:
            quarantines.append({"step": rec.get("step"),
                                "component":
                                    f"actor-{rec['actor_quarantined']}",
                                "staleness_s":
                                    rec.get("stall_staleness_s")})
        if rec.get("peer_stall") is not None:
            peer_stalls.append({"step": rec.get("step"),
                                "component": rec["peer_stall"],
                                "staleness_s":
                                    rec.get("stall_staleness_s")})
        if rec.get("blackbox_dump") is not None:
            blackbox_dumps.append({"step": rec.get("step"),
                                   "path": rec["blackbox_dump"],
                                   "reason": rec.get("blackbox_reason"),
                                   "peer": rec.get("blackbox_peer"),
                                   "component":
                                       rec.get("blackbox_component"),
                                   "recorded":
                                       rec.get("blackbox_ring_recorded"),
                                   "dropped":
                                       rec.get("blackbox_ring_dropped")})
        if rec.get("perf_degradation") is not None:
            perf_events.append({"step": rec.get("step"),
                                "name": rec["perf_degradation"],
                                "peer": rec.get("perf_peer"),
                                "value": rec.get("perf_value"),
                                "baseline": rec.get("perf_baseline"),
                                "frac": rec.get("perf_frac")})
        if rec.get("remediation") is not None:
            remediation_events.append({
                "step": rec.get("step"),
                "rule": rec["remediation"],
                "target": rec.get("remediation_target"),
                "action": rec.get("remediation_action"),
                "outcome": rec.get("remediation_outcome"),
                "value": rec.get("remediation_value"),
                "baseline": rec.get("remediation_baseline")})
    # fleet telemetry: `peer/<id>/<kind>/<name>` keys the aggregator
    # merges into the stream (obs/fleet.py) regroup into one dict per
    # peer — {"seq": n, "ctr": {...}, "gauge": {...}, "hist": {...},
    # "span": {...}, "hb": {...}}
    peers: dict[str, dict[str, Any]] = {}
    for k, v in latest.items():
        if not k.startswith("peer/"):
            continue
        parts = k.split("/", 3)
        if len(parts) == 3:  # peer/<id>/seq
            peers.setdefault(parts[1], {})[parts[2]] = v
        elif len(parts) == 4:
            peers.setdefault(parts[1], {}).setdefault(
                parts[2], {})[parts[3]] = v
    # multichip scaling lane: `multichip/dp<N>/<stat>` keys the bench
    # lane (bench.py --multichip) appends to the JSONL — one group per
    # dp point, same raw-key pattern as the fleet peer frames
    multichip: dict[int, dict[str, Any]] = {}
    for k, v in latest.items():
        if not k.startswith("multichip/dp"):
            continue
        parts = k.split("/", 2)
        if len(parts) != 3:
            continue
        try:
            dp = int(parts[1][2:])
        except ValueError:
            continue
        multichip.setdefault(dp, {})[parts[2]] = v
    spans = {k[len("span/"):]: v for k, v in latest.items()
             if k.startswith("span/") and isinstance(v, dict)}
    hists = {k[len("hist/"):]: v for k, v in latest.items()
             if k.startswith("hist/") and isinstance(v, dict)}
    gauges = {k[len("gauge/"):]: v for k, v in latest.items()
              if k.startswith("gauge/")}
    # per-tenant learning health: `gauge/learn/<env_id>/<name>` keys
    # (obs/learning.publish_learn) regroup into one dict per env family
    # — 57-game suite = 57 attributable tenants
    tenants: dict[str, dict[str, Any]] = {}
    for k, v in gauges.items():
        if not k.startswith("learn/"):
            continue
        parts = k.split("/", 2)
        if len(parts) == 3:
            tenants.setdefault(parts[1], {})[parts[2]] = v
    # per-tenant serving stats: `gauge/serve/<policy_id>/<stat>` keys
    # (parallel/inference_server._maybe_publish_stats) regroup into one
    # dict per tenant — the serving tier's equivalent of learn/ keys
    serving: dict[str, dict[str, Any]] = {}
    for k, v in gauges.items():
        if not k.startswith("serve/"):
            continue
        parts = k.split("/", 2)
        if len(parts) == 3:
            serving.setdefault(parts[1], {})[parts[2]] = v
    ctrs = {k[len("ctr/"):]: v for k, v in latest.items()
            if k.startswith("ctr/")}
    hbm = {k[len("hbm/"):]: v for k, v in latest.items()
           if k.startswith("hbm/")}
    header_keys = ("run_name", "version", "sample_chunk",
                   "sample_prefetch", "replay_kind", "replay_storage",
                   "replay_capacity", "batch_size", "train_chunk",
                   "dp", "tp")
    return {
        "header": {k: latest[k] for k in header_keys if k in latest},
        "throughput": {
            "final_step": latest.get("step", 0),
            "frames": latest.get("frames"),
            "frames_per_s": latest.get("frames_per_s"),
            "grad_steps_per_s": latest.get("grad_steps_per_s"),
            "loss": latest.get("loss"),
            "avg_return": latest.get("avg_return"),
        },
        "spans": spans,
        "hists": hists,
        "gauges": gauges,
        "ctrs": ctrs,
        "hbm": hbm,
        "peers": peers,
        "multichip": multichip,
        "tenants": tenants,
        "serving": serving,
        "virtual_devices": latest.get("virtual_devices"),
        "disconnects": disconnects,
        "stalls": stalls,
        "perf_events": perf_events,
        "learn_events": learn_events,
        "remediation_events": remediation_events,
        "quarantines": quarantines,
        "peer_stalls": peer_stalls,
        "blackbox_dumps": blackbox_dumps,
    }


def _fmt_spans(spans: dict[str, dict]) -> list[str]:
    lines = ["stage-time breakdown (host spans):",
             f"  {'stage':<28} {'count':>8} {'total_s':>9} "
             f"{'mean_ms':>9} {'max_ms':>9} {'share':>7}"]
    grand = sum(s.get("total_s", 0.0) for s in spans.values()) or 1.0
    order = sorted(spans.items(),
                   key=lambda kv: -kv[1].get("total_s", 0.0))
    for name, s in order:
        count = int(s.get("count", 0))
        total = float(s.get("total_s", 0.0))
        mean_ms = total / count * 1e3 if count else 0.0
        tag = " (fused)" if total == 0.0 and count else ""
        lines.append(
            f"  {name:<28} {count:>8} {total:>9.3f} {mean_ms:>9.3f} "
            f"{float(s.get('max_s', 0.0)) * 1e3:>9.3f} "
            f"{total / grand:>6.1%}{tag}")
    return lines


def _fmt_hist(name: str, h: dict) -> list[str]:
    count = int(h.get("count", 0))
    if not count:
        return [f"  {name:<22} (empty)"]
    mean = h.get("sum", 0.0) / count
    line = (f"  {name:<22} n={count:<9} mean={mean:<10.2f} "
            f"p50={_n(h.get('p50')):<8} p90={_n(h.get('p90')):<8} "
            f"p99={_n(h.get('p99')):<8} max={_n(h.get('max'))}")
    out = [line]
    if name in HEALTHY:
        pct, bound, why = HEALTHY[name]
        v = h.get(pct)
        if v is not None and v > bound:
            out.append(f"    ⚠ {pct}={_n(v)} exceeds healthy ~{bound}: "
                       f"{why}")
    return out


def _fmt_ingest(summary: dict[str, Any]) -> list[str]:
    """Ingest-pipeline health from the staging gauges (runtime/ingest.py
    zero-copy stager; PERF.md 'Ingest pipeline'). Gauges are last-write
    point samples, so read them as 'state at the final publish'."""
    gauges = summary.get("gauges", {})
    occ = gauges.get("ingest_staging_occupancy")
    width = gauges.get("ingest_coalesce_width")
    if occ is None and width is None:
        return []
    lines = ["ingest staging (zero-copy pipeline gauges):"]
    if occ is not None:
        lines.append(f"  staging occupancy      {float(occ):.1%} of the "
                     f"active buffer (point sample)")
    if width is not None:
        lines.append(f"  last coalesce width    {_n(width)} blocks/add "
                     f"dispatch (1 = idle-drain, >1 = full-buffer "
                     f"add_many)")
    ratio = gauges.get("wire_compression_ratio")
    if ratio is not None:
        lines.append(f"  wire compression       {float(ratio):.2f}x "
                     f"raw/wire (delta-deflate codec; healthy ≥2x on "
                     f"frame traffic, 1.0 = raw peer)")
        if float(ratio) < 1.5:
            lines.append("    ⚠ wire ratio <1.5x: peer negotiated raw "
                         "(old build / comm.wire_codec=raw) or traffic "
                         "is float-dominated — the ingest link runs "
                         "uncompressed")
    dec = gauges.get("ingest_decode_ms")
    if dec is not None:
        lines.append(f"  last put decode        {float(dec):.2f} ms "
                     f"(inflate + delta-undo + staging copy; healthy "
                     f"<10ms per message — beyond that decode eats the "
                     f"ingest thread's budget)")
    # ingest-bound flags: a persistently full staging buffer means
    # device adds can't keep up with actor arrivals; a replay.add span
    # eating a large share of host wall-clock means adds steal the
    # learner's dispatch window
    if occ is not None and float(occ) >= 0.5:
        lines.append("    ⚠ staging buffer ≥50% full at last publish: "
                     "ingest-bound — device adds lag actor arrivals "
                     "(raise replay.ingest_coalesce or check the h2d "
                     "link)")
    spans = summary.get("spans", {})
    add = spans.get("replay.add")
    if add:
        grand = sum(s.get("total_s", 0.0) for s in spans.values()) or 1.0
        share = float(add.get("total_s", 0.0)) / grand
        if share >= 0.25:
            lines.append(f"    ⚠ replay.add is {share:.0%} of host "
                         f"wall-clock: adds contend with the train "
                         f"dispatch loop — ingest-bound")
    return lines


def _fmt_slo(summary: dict[str, Any]) -> list[str]:
    """Live serving-SLO view: inference latency percentiles and every
    gauge with a healthy-range rule, each flagged when outside it."""
    hists = summary.get("hists", {})
    gauges = summary.get("gauges", {})
    lat = hists.get("infer_latency_ms")
    # learn_* warn rows render (and flag) in the learning-health
    # section, remediation_* rows in the remediation section — keep
    # the SLO block serving-scoped
    gauge_rows = [(name, gauges[name]) for name, row in INSTRUMENTS.items()
                  if row["kind"] == "gauge" and "warn" in row
                  and name in gauges
                  and not name.startswith(("learn_", "remediation_"))]
    if not lat and not gauge_rows:
        return []
    lines = ["serving SLOs:"]
    if lat and int(lat.get("count", 0)):
        lines.append(
            f"  infer latency (ms)     p50={_n(lat.get('p50'))} "
            f"p99={_n(lat.get('p99'))} max={_n(lat.get('max'))} "
            f"over n={int(lat['count'])} requests "
            f"(healthy p99 < {HEALTHY['infer_latency_ms'][1]})")
    for name, v in gauge_rows:
        kind, bound, why = HEALTHY[name]
        # "value_min" rows (e.g. dp_scaling_efficiency) are healthy
        # when HIGH: flag below the bound instead of above it
        low_side = kind == "value_min"
        flag = float(v) < bound if low_side else float(v) > bound
        rel = "≥" if low_side else "≤"
        lines.append(f"  {name:<22} {_n(v)} "
                     f"(healthy {rel} {_n(float(bound))})")
        if flag:
            verb = "falls below" if low_side else "exceeds"
            lines.append(f"    ⚠ value={_n(v)} {verb} healthy "
                         f"~{bound}: {why}")
    return lines


# stage -> (mfu gauge, bw gauge, ewma-ms gauge, host span carrying the
# stage's total wall time). The span totals give the honest device-time
# SHARE (every window is block_until_ready-bracketed by contract);
# the gauges give the per-dispatch roofline position.
_ROOFLINE_STAGES = (
    ("sample_k", "mfu_sample_k", "hbm_bw_frac_sample_k",
     "device_ms_sample_k", "replay.sample"),
    ("learn_k", "mfu_learn_k", "hbm_bw_frac_learn_k",
     "device_ms_learn_k", "learner.learn"),
    ("train", "mfu_train", "hbm_bw_frac_train",
     "device_ms_train", "learner.train"),
    ("train_dist", "mfu_train_dist", "hbm_bw_frac_train_dist",
     "device_ms_train_dist", "learner.train"),
    ("ingest", None, "hbm_bw_frac_ingest",
     "device_ms_ingest", "replay.add"),
)


def _fmt_roofline(summary: dict[str, Any]) -> list[str]:
    """Live roofline (obs/profiling.py): per-stage EWMA dispatch time,
    device-time share, and MFU / HBM-bandwidth fractions against the
    detected chip peaks — the continuous version of PERF.md's one-off
    roofline study. mfu_* are LOWER bounds (compiler FLOP counts omit
    most conv FLOPs on this backend)."""
    gauges = summary.get("gauges", {})
    spans = summary.get("spans", {})
    rows = []
    for stage, mfu_k, bw_k, ms_k, span_name in _ROOFLINE_STAGES:
        if ms_k not in gauges and (mfu_k is None
                                   or mfu_k not in gauges):
            continue
        rows.append((stage,
                     gauges.get(mfu_k) if mfu_k else None,
                     gauges.get(bw_k), gauges.get(ms_k),
                     float(spans.get(span_name, {}).get("total_s", 0.0))))
    if not rows:
        return []
    # single-process runs carry no host spans; their stages share one
    # dispatch cadence, so the EWMA-ms weights give the same share
    if not any(r[4] for r in rows):
        rows = [(st, mfu, bw, ms, float(ms or 0.0))
                for st, mfu, bw, ms, _ in rows]
    grand = sum(r[4] for r in rows) or 1.0
    lines = ["roofline (live gauges; mfu is a lower bound — see "
             "PERF.md):",
             f"  {'stage':<12} {'dev_ms(ewma)':>13} {'time_share':>11} "
             f"{'mfu':>8} {'hbm_bw':>8}"]
    for stage, mfu, bw, ms, total_s in rows:
        ms_s = f"{float(ms):.3f}" if ms is not None else "-"
        mfu_s = f"{float(mfu):.2%}" if mfu is not None else "-"
        bw_s = f"{float(bw):.2%}" if bw is not None else "-"
        lines.append(f"  {stage:<12} {ms_s:>13} "
                     f"{total_s / grand:>10.1%} {mfu_s:>8} {bw_s:>8}")
    ctrs = summary.get("ctrs", {})
    n = ctrs.get("jit_compiles")
    if n is not None:
        ms = ctrs.get("jit_compile_ms", 0.0)
        entries = gauges.get("compile_cache_entries")
        lines.append(
            f"  compile telemetry: {_n(n)} backend compiles, "
            f"{float(ms):.0f} ms total, process cache entries="
            f"{_n(entries)}")
    return lines


def _fmt_multichip(summary: dict[str, Any]) -> list[str]:
    """dp-scaling curve from the multichip bench lane (bench.py
    --multichip): one row per dp point with throughput, efficiency vs
    dp=1, per-shard fill bounds, and the dist-dispatch roofline gauges.
    Efficiency on virtual devices (one shared host) is a correctness/
    overhead signal, not a speedup claim — see PERF.md."""
    points = summary.get("multichip", {})
    if not points:
        return []
    virt = summary.get("virtual_devices")
    tag = ("virtual devices — shared host, efficiency is an overhead "
           "signal" if virt else "real chips")
    lines = [f"multichip scaling ({tag}):",
             f"  {'dp':>4} {'grad_steps/s':>13} {'efficiency':>11} "
             f"{'shard_fill':>13} {'mfu':>8} {'dev_ms':>9} "
             f"{'ingest_rows/s':>14}"]
    for dp in sorted(points):
        p = points[dp]
        eff = p.get("efficiency")
        fmin, fmax = p.get("shard_fill_min"), p.get("shard_fill_max")
        fill = (f"{float(fmin):.2f}..{float(fmax):.2f}"
                if fmin is not None and fmax is not None else "-")
        mfu = p.get("mfu_train_dist")
        ms = p.get("device_ms_train_dist")
        lines.append(
            f"  {dp:>4} {_n(p.get('grad_steps_per_s')):>13} "
            f"{(f'{float(eff):.2f}x' if eff is not None else '-'):>11} "
            f"{fill:>13} "
            f"{(f'{float(mfu):.2%}' if mfu else '-'):>8} "
            f"{(f'{float(ms):.2f}' if ms is not None else '-'):>9} "
            f"{_n(p.get('ingest_rows_per_s')):>14}")
        if eff is not None and float(eff) < HEALTHY[
                "dp_scaling_efficiency"][1] and dp > 1:
            lines.append(f"    ⚠ dp={dp} efficiency {float(eff):.2f} "
                         f"below healthy ~"
                         f"{HEALTHY['dp_scaling_efficiency'][1]}: "
                         f"{HEALTHY['dp_scaling_efficiency'][2]}")
    return lines


# the four learn_* gauges with warn rows, i.e. the lines LearnMonitor's
# absolute rules fire on — flagged here with the same bounds
_LEARN_WARN_ROWS = ("learn_q_max", "learn_update_ratio",
                    "learn_is_ess_frac", "learn_priority_top_frac")


def _fmt_learning(summary: dict[str, Any]) -> list[str]:
    """Learning-health section (obs/learning.py): the in-graph training
    diagnostics at the last publish, healthy-range flags mirroring the
    LearnMonitor rules, and the per-tenant (per-env-family) view."""
    gauges = summary.get("gauges", {})
    if not any(k.startswith("learn_") for k in gauges):
        return []

    def g(name: str) -> str:
        v = gauges.get(name)
        return _n(float(v)) if v is not None else "-"

    lines = [
        "learning health (in-graph diagnostics, last publish):",
        f"  td |error|            p50={g('learn_td_abs_p50')} "
        f"p90={g('learn_td_abs_p90')} p99={g('learn_td_abs_p99')} "
        f"signed_mean={g('learn_td_signed_mean')}",
        f"  Q values              mean={g('learn_q_mean')} "
        f"max={g('learn_q_max')} target_mean={g('learn_target_q_mean')} "
        f"overestimation_gap={g('learn_q_gap')}",
        f"  optimizer             grad_norm={g('learn_grad_norm')} "
        f"update_ratio={g('learn_update_ratio')}",
        f"  sampling              is_ess_frac={g('learn_is_ess_frac')} "
        f"age_p50={g('learn_sample_age_p50')} "
        f"age_p90={g('learn_sample_age_p90')} "
        f"priority_top_frac={g('learn_priority_top_frac')} "
        f"prio_staleness={g('learn_prio_staleness_frac')}",
    ]
    if "learn_shard_td_mean_min" in gauges:
        lines.append(
            f"  shards (dp)           td_mean "
            f"min={g('learn_shard_td_mean_min')} "
            f"max={g('learn_shard_td_mean_max')}")
    for name in _LEARN_WARN_ROWS:
        if name not in gauges:
            continue
        kind, bound, why = HEALTHY[name]
        low_side = kind == "value_min"
        v = float(gauges[name])
        if (v < bound) if low_side else (abs(v) > bound):
            verb = "falls below" if low_side else "exceeds"
            lines.append(f"    ⚠ {name}={_n(v)} {verb} healthy "
                         f"~{_n(float(bound))}: {why}")
    tenants = summary.get("tenants", {})
    if tenants:
        lines.append(f"  tenants ({len(tenants)}):")
        for t in sorted(tenants):
            d = tenants[t]

            def tn(key: str, d=d) -> str:
                v = d.get(key)
                return _n(float(v)) if v is not None else "-"

            lines.append(
                f"    {t:<22} td_p90={tn('td_abs_p90')} "
                f"q_mean={tn('q_mean')} q_max={tn('q_max')} "
                f"ess={tn('is_ess_frac')} "
                f"update_ratio={tn('update_ratio')}")
    return lines


def _fmt_serving(summary: dict[str, Any]) -> list[str]:
    """Serving-tier section (multi-tenant inference, ISSUE 13): the
    admission controller's aggregate accounting plus a per-tenant table
    from the `serve/<tenant>/` gauges, each tenant's p99 flagged
    against the infer_latency_ms healthy bound."""
    ctrs = summary.get("ctrs", {})
    gauges = summary.get("gauges", {})
    serving = summary.get("serving", {})
    if "serve_offered" not in ctrs and not serving:
        return []
    offered = int(ctrs.get("serve_offered", 0))
    admitted = int(ctrs.get("serve_admitted", 0))
    shed = int(ctrs.get("serve_shed", 0))
    expired = int(ctrs.get("serve_expired", 0))
    bp = gauges.get("serve_backpressure")
    lines = [
        "serving tier (multi-tenant admission):",
        f"  offered={offered} admitted={admitted} shed={shed} "
        f"(of which expired={expired}) "
        f"tenants={_n(gauges.get('serve_tenants'))} "
        f"queue_depth={_n(gauges.get('serve_queue_items'))} "
        f"backpressure={'ENGAGED' if bp else 'off'}"]
    # the closure invariant the admission tests assert; a report over a
    # live (non-quiescent) stream may show a small in-flight gap
    if offered and offered != admitted + shed:
        lines.append(f"    (in-flight gap: offered - admitted - shed = "
                     f"{offered - admitted - shed} requests still "
                     f"queued at last publish)")
    if serving:
        p99_bound = HEALTHY["infer_latency_ms"][1]
        lines.append(f"  tenants ({len(serving)}):")
        for t in sorted(serving):
            d = serving[t]

            def tn(key: str, d=d) -> str:
                v = d.get(key)
                return _n(float(v)) if v is not None else "-"

            lines.append(
                f"    {t:<22} p50_ms={tn('p50_ms')} "
                f"p99_ms={tn('p99_ms')} depth={tn('queue_depth')} "
                f"offered={tn('offered')} admitted={tn('admitted')} "
                f"shed={tn('shed')}")
            p99 = d.get("p99_ms")
            if p99 is not None and float(p99) > p99_bound:
                lines.append(
                    f"      ⚠ p99={_n(float(p99))}ms exceeds healthy "
                    f"~{_n(float(p99_bound))}ms: "
                    f"{HEALTHY['infer_latency_ms'][2]}")
    return lines


def _fmt_learn_events(summary: dict[str, Any]) -> list[str]:
    """LearnMonitor `learning_degradation` events (warn-only; the run
    continued), attributed to the env family that tripped the rule."""
    events = summary.get("learn_events", [])
    if not events:
        return []
    lines = [f"learning-degradation events: {len(events)} (warn-only; "
             f"the run continued)"]
    for e in events:
        who = f" tenant={e['tenant']}" if e.get("tenant") else ""
        base = (f" baseline={_n(e['baseline'])}"
                if e.get("baseline") else "")
        lines.append(f"  step={_n(e['step'])} {e['rule']}{who}: "
                     f"value={_n(e['value'])}{base}")
    return lines


def _fmt_perf_events(summary: dict[str, Any]) -> list[str]:
    """PerfDegradation events (warn-only EWMA regression engine), with
    peer attribution when the baseline was a fleet peer's."""
    events = summary.get("perf_events", [])
    if not events:
        return []
    lines = [f"perf-degradation events: {len(events)} (warn-only; the "
             f"run continued)"]
    for e in events:
        who = f" peer={e['peer']}" if e.get("peer") else ""
        lines.append(
            f"  step={_n(e['step'])} {e['name']}{who}: "
            f"{_n(e['value'])} fell below {_n(e['frac'])}x baseline "
            f"{_n(e['baseline'])}")
    return lines


def _fmt_cold(summary: dict[str, Any]) -> list[str]:
    """Tiered-replay section (replay/cold_store.py +
    replay/disk_store.py): the host-RAM cold store's residency and
    door outcomes, and — when the disk rung is enabled — the spill /
    promotion / queue-refusal counters of the async writeback tier.
    Mirrors the bespoke cold-door thrash row in check_violations."""
    gauges = summary.get("gauges", {})
    ctrs = summary.get("ctrs", {})
    if "cold_segments" not in gauges \
            and "cold_evictions" not in ctrs:
        return []
    lines = [
        "tiered replay (host-RAM cold store):",
        f"  resident: segments={_n(gauges.get('cold_segments'))} "
        f"bytes={_n(gauges.get('cold_bytes'))} "
        f"compression={_n(gauges.get('cold_compression_ratio'))}x",
        f"  door: evictions={int(ctrs.get('cold_evictions', 0))} "
        f"recalls={int(ctrs.get('cold_recalls', 0))} "
        f"displaced={int(ctrs.get('cold_displaced', 0))} "
        f"dropped={int(ctrs.get('cold_dropped', 0))}"]
    drops = int(ctrs.get("cold_dropped", 0))
    displ = int(ctrs.get("cold_displaced", 0))
    disk_on = "cold_disk_transitions" in gauges \
        or "cold_disk_spills" in ctrs
    if disk_on:
        lines.append(
            f"  disk rung: segments="
            f"{_n(gauges.get('cold_disk_segments'))} "
            f"transitions={_n(gauges.get('cold_disk_transitions'))} "
            f"bytes={_n(gauges.get('cold_disk_bytes'))}")
        lines.append(
            f"    spills={int(ctrs.get('cold_disk_spills', 0))} "
            f"promotions={int(ctrs.get('cold_disk_promotions', 0))} "
            f"queue_full={int(ctrs.get('cold_disk_queue_full', 0))} "
            f"io_errors={int(ctrs.get('cold_disk_errors', 0))}")
    if drops > displ and int(ctrs.get("cold_disk_spills", 0)) < drops:
        lines.append("    ⚠ door drops outrun displacements and disk "
                     "spills did not absorb them — the store is "
                     "saturated with heavier segments and experience "
                     "is lost at the door (grow cold_tier_capacity or "
                     "enable cold_tier_disk_capacity)")
    return lines


def _fmt_params(summary: dict[str, Any]) -> list[str]:
    """Param-plane codec section (comm/param_codec.py): wire spend vs
    raw-equivalent cost for the weight broadcast, resync and
    queue-drop counters. The ratio is cumulative over the run; 1.0
    means every peer negotiated raw (old build or
    comm.param_codec=raw)."""
    gauges = summary.get("gauges", {})
    ctrs = summary.get("ctrs", {})
    ratio = gauges.get("param_compression_ratio")
    if ratio is None and "param_bytes_out" not in ctrs:
        return []
    lines = ["param plane (delta+quantized weight broadcast):"]
    lines.append(
        f"  wire bytes out={_n(ctrs.get('param_bytes_out'))} "
        f"compression={_n(ratio)}x raw-equivalent/wire")
    lines.append(
        f"  resyncs={int(ctrs.get('param_resyncs', 0))} "
        f"queue_drops={int(ctrs.get('param_push_queue_drops', 0))}")
    if ratio is not None and float(ratio) < 1.5:
        lines.append("    ⚠ param ratio <1.5x: peers negotiated raw "
                     "(old build / comm.param_codec=raw) or every "
                     "publish forced a full resync — the weight "
                     "broadcast runs (near-)uncompressed")
    return lines


def _fmt_remediation(summary: dict[str, Any]) -> list[str]:
    """Remediation-plane section (runtime/remediation.py): the policy
    engine's decisions grouped by rule/target/action/outcome, the
    outcome counters, and the live action-budget headroom — flagged
    when enforce mode has run out of tokens."""
    events = summary.get("remediation_events", [])
    gauges = summary.get("gauges", {})
    ctrs = summary.get("ctrs", {})
    mode_v = gauges.get("remediation_mode")
    if not events and mode_v is None \
            and "remediation_actions" not in ctrs:
        return []
    mode = {1.0: "observe", 2.0: "enforce"}.get(
        float(mode_v) if mode_v is not None else 0.0, "off")
    headroom = gauges.get("remediation_budget_headroom")
    lines = [
        f"remediation plane (mode={mode}):",
        f"  applied={int(ctrs.get('remediation_actions', 0))} "
        f"observed={int(ctrs.get('remediation_observed', 0))} "
        f"suppressed={int(ctrs.get('remediation_suppressed', 0))} "
        f"failed={int(ctrs.get('remediation_failed', 0))} "
        f"budget_headroom={_n(headroom)} tokens"]
    if events:
        by_key: dict[tuple, int] = {}
        for e in events:
            key = (str(e.get("rule")), str(e.get("target")),
                   str(e.get("action")), str(e.get("outcome")))
            by_key[key] = by_key.get(key, 0) + 1
        lines.append(f"  decisions ({len(events)}):")
        for (rule, target, action, outcome), n in sorted(
                by_key.items()):
            lines.append(f"    {rule:<16} target={target:<12} "
                         f"{action} -> {outcome} x{n}")
    if mode == "enforce" and headroom is not None \
            and float(headroom) < 1.0:
        lines.append("    ⚠ action budget exhausted at last publish: "
                     "enforce-mode decisions are being suppressed — "
                     "faults outpace remediation.budget_per_min")
    return lines


def _fmt_peers(summary: dict[str, Any]) -> list[str]:
    """Per-peer fleet telemetry: one block per remote actor host with
    its heartbeat ages, ingest rate, stage-time breakdown, and any
    histogram rows (healthy-range flags apply to remote instruments
    exactly as to local ones)."""
    peers = summary.get("peers", {})
    if not peers:
        return []
    lines = [f"fleet peers ({len(peers)}):"]
    for peer in sorted(peers):
        p = peers[peer]
        rate = p.get("gauge", {}).get("ingest_rate")
        head = f"  peer {peer}: frame seq={_n(p.get('seq'))}"
        if rate is not None:
            head += f", ingest rate={float(rate):.1f} rows/s"
        lines.append(head)
        hb = p.get("hb", {})
        if hb:
            ages = ", ".join(f"{name}={float(age):.1f}s"
                             for name, age in sorted(hb.items()))
            lines.append(f"    heartbeat ages: {ages}")
        spans = {k: v for k, v in p.get("span", {}).items()
                 if isinstance(v, dict)}
        if spans:
            lines.append(f"    stage-time breakdown ({peer}):")
            grand = sum(s.get("total_s", 0.0)
                        for s in spans.values()) or 1.0
            for name, s in sorted(spans.items(),
                                  key=lambda kv: -kv[1].get("total_s", 0.0)):
                count = int(s.get("count", 0))
                total = float(s.get("total_s", 0.0))
                mean_ms = total / count * 1e3 if count else 0.0
                lines.append(f"      {name:<24} {count:>8} "
                             f"{total:>9.3f}s {mean_ms:>8.3f}ms/ea "
                             f"{total / grand:>6.1%}")
        for name in sorted(p.get("hist", {})):
            h = p["hist"][name]
            if isinstance(h, dict) and int(h.get("count", 0)):
                lines.extend("    " + ln
                             for ln in _fmt_hist(name, h))
    disconnects = summary.get("disconnects", [])
    if disconnects:
        lines.append(f"  peer disconnects: {len(disconnects)}")
        for d in disconnects:
            lines.append(f"    step={_n(d['step'])} peer={d['peer']}")
    return lines


def _n(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return f"{v:.3g}" if isinstance(v, float) else str(v)


def format_report(summary: dict[str, Any]) -> str:
    lines: list[str] = []
    hdr = summary["header"]
    if hdr:
        lines.append("run: " + ", ".join(f"{k}={_n(v)}"
                                         for k, v in hdr.items()))
    tp = summary["throughput"]
    lines.append(
        f"throughput: step={_n(tp['final_step'])} "
        f"frames={_n(tp['frames'])} "
        f"frames/s={_n(tp['frames_per_s'])} "
        f"grad-steps/s={_n(tp['grad_steps_per_s'])} "
        f"loss={_n(tp['loss'])} avg_return={_n(tp['avg_return'])}")
    if summary["spans"]:
        lines.append("")
        lines.extend(_fmt_spans(summary["spans"]))
    roofline_lines = _fmt_roofline(summary)
    if roofline_lines:
        lines.append("")
        lines.extend(roofline_lines)
    multichip_lines = _fmt_multichip(summary)
    if multichip_lines:
        lines.append("")
        lines.extend(multichip_lines)
    if summary["hists"]:
        lines.append("")
        lines.append("staleness / distribution percentiles:")
        for name in sorted(summary["hists"]):
            lines.extend(_fmt_hist(name, summary["hists"][name]))
    learn_lines = _fmt_learning(summary)
    if learn_lines:
        lines.append("")
        lines.extend(learn_lines)
    learn_ev_lines = _fmt_learn_events(summary)
    if learn_ev_lines:
        lines.append("")
        lines.extend(learn_ev_lines)
    slo_lines = _fmt_slo(summary)
    if slo_lines:
        lines.append("")
        lines.extend(slo_lines)
    serving_lines = _fmt_serving(summary)
    if serving_lines:
        lines.append("")
        lines.extend(serving_lines)
    ingest_lines = _fmt_ingest(summary)
    if ingest_lines:
        lines.append("")
        lines.extend(ingest_lines)
    cold_lines = _fmt_cold(summary)
    if cold_lines:
        lines.append("")
        lines.extend(cold_lines)
    param_lines = _fmt_params(summary)
    if param_lines:
        lines.append("")
        lines.extend(param_lines)
    peer_lines = _fmt_peers(summary)
    if peer_lines:
        lines.append("")
        lines.extend(peer_lines)
    perf_lines = _fmt_perf_events(summary)
    if perf_lines:
        lines.append("")
        lines.extend(perf_lines)
    remediation_lines = _fmt_remediation(summary)
    if remediation_lines:
        lines.append("")
        lines.extend(remediation_lines)
    if summary["hbm"]:
        lines.append("")
        lines.append("compiled memory (XLA memory_analysis, bytes):")
        for k in sorted(summary["hbm"]):
            lines.append(f"  {k:<40} {_n(summary['hbm'][k])}")
    lines.append("")
    if summary["stalls"]:
        lines.append(f"stall events: {len(summary['stalls'])}")
        for s in summary["stalls"]:
            lines.append(
                f"  step={_n(s['step'])} component={s['component']} "
                f"silent={_n(s['staleness_s'])}s note={s['note']!r}")
    else:
        lines.append("stall events: none")
    dumps = summary.get("blackbox_dumps", [])
    if dumps:
        lines.append(f"black-box dumps: {len(dumps)} "
                     "(obs/blackbox.py; bundle with obs/postmortem.py)")
        for d in dumps[-5:]:
            lines.append(
                f"  step={_n(d['step'])} reason={d.get('reason')} "
                f"peer={d.get('peer')} "
                f"component={d.get('component') or '-'} "
                f"path={d.get('path')}")
    return "\n".join(lines)


def check_violations(summary: dict[str, Any]) -> list[str]:
    """Every healthy-range row violated by the summary, one line each.
    This is the CI gate (`--check`): the online engines (PerfMonitor,
    LearnMonitor) stay warn-only by design; a lane that wants to FAIL
    on an unhealthy artifact runs the report over it and exits on the
    same rows the text report flags."""
    gauges = summary.get("gauges", {})
    hists = summary.get("hists", {})
    out: list[str] = []
    for name, (kind, bound, why) in HEALTHY.items():
        row_kind = INSTRUMENTS[name]["kind"]
        if row_kind == "gauge":
            raw = gauges.get(name)
            if raw is None:
                continue
            # budget exhaustion only gates enforce mode (mode gauge
            # 2.0): an observe-mode engine that runs dry is telemetry,
            # not an availability risk — no actuator was going to fire
            if name == "remediation_budget_headroom" and float(
                    gauges.get("remediation_mode", 0.0) or 0.0) < 2.0:
                continue
            v = float(raw)
            if kind == "value_min":
                bad = v < bound
                rel = "<"
            else:
                # q blowup is a magnitude rule (divergence to -inf is
                # just as dead as +inf) — mirror LearnMonitor exactly
                bad = (abs(v) if name == "learn_q_max" else v) > bound
                rel = ">"
            if bad:
                out.append(f"{name}: value={_n(v)} {rel} healthy "
                           f"{_n(float(bound))} — {why}")
        else:  # hist rows warn on a percentile
            h = hists.get(name)
            if not isinstance(h, dict) or not int(h.get("count", 0)):
                continue
            v = h.get(kind)
            if v is not None and float(v) > bound:
                out.append(f"{name}: {kind}={_n(float(v))} > healthy "
                           f"{_n(float(bound))} — {why}")
    # per-tenant serving latency: every serve/<tenant>/p99_ms gauge is
    # held to the same bound as the aggregate infer_latency_ms hist —
    # a single overloaded tenant must not hide inside a healthy mean
    _, lat_bound, lat_why = HEALTHY["infer_latency_ms"]
    for tenant, d in sorted(summary.get("serving", {}).items()):
        p99 = d.get("p99_ms")
        if p99 is not None and float(p99) > lat_bound:
            out.append(f"serve/{tenant}/p99_ms: value={_n(float(p99))} "
                       f"> healthy {_n(float(lat_bound))} — {lat_why}")
    # cold-door thrash (ISSUE 16): door drops outrunning displacements
    # means evicted mass is being rejected outright rather than
    # displacing lighter residents — the store is saturated with
    # heavier segments and experience is being lost at the door. The
    # disk rung (cold_tier_disk_capacity) exists to absorb exactly this
    # overflow; a run with spills active is exempt only if the drops
    # still found a disk slot (spills keep pace with drops).
    ctrs = summary.get("ctrs", {})
    drops = float(ctrs.get("cold_dropped", 0.0) or 0.0)
    displ = float(ctrs.get("cold_displaced", 0.0) or 0.0)
    spills = float(ctrs.get("cold_disk_spills", 0.0) or 0.0)
    if drops > displ and spills < drops:
        out.append(
            f"cold_dropped: value={_n(drops)} > cold_displaced "
            f"{_n(displ)} — door drops outrun displacements and disk "
            f"spills ({_n(spills)}) did not absorb them: the cold "
            f"store is thrashing; grow cold_tier_capacity or enable "
            f"the disk rung (cold_tier_disk_capacity)")
    # torn shm slots (ISSUE 18): validation catches them (crc+seq,
    # never delivered), but ANY tear means a writer died mid-pack or
    # something scribbled on the segment — one is an incident, a
    # stream is a crash-looping actor host. Zero is the healthy state.
    torn = float(ctrs.get("shm_torn_slots", 0.0) or 0.0)
    if torn > 0:
        out.append(
            f"shm_torn_slots: value={_n(torn)} > healthy 0 — torn "
            f"ring slots were caught (crc/seq mismatch, freed, never "
            f"delivered) but their writers died mid-pack or the "
            f"segment was corrupted; check actor-host crash loops")
    # forensics (ISSUE 17): evidence must survive the event it
    # documents. A terminal StallError / quarantine whose run left no
    # black-box dump on disk is silent loss of evidence — the same gap
    # the PR 16 thrash row closed for silent spill lag.
    terminals = (
        [("stall", s.get("component")) for s in summary.get("stalls", [])]
        + [("quarantine", q.get("component"))
           for q in summary.get("quarantines", [])]
        + [("peer_stall", p.get("component"))
           for p in summary.get("peer_stalls", [])])
    if terminals:
        on_disk = [d for d in summary.get("blackbox_dumps", [])
                   if d.get("path") and os.path.exists(str(d["path"]))]
        if not on_disk:
            names = ", ".join(sorted({f"{k}:{c}" for k, c in terminals}))
            out.append(
                f"blackbox_dumps: {len(terminals)} terminal event(s) "
                f"({names}) but no black-box dump on disk — silent "
                f"loss of evidence; the flight recorder "
                f"(obs/blackbox.py, ObsConfig.blackbox) should have "
                f"archived the victim's ring as blackbox-<peer>.json")
    # ring-drop fraction, per dump: overwriting old records is the
    # ring's normal steady state, so the global ctr ratio is NOT a
    # health signal — what matters is whether a dump that was supposed
    # to explain an incident had already lost most of its window
    for d in summary.get("blackbox_dumps", []):
        rec_n = float(d.get("recorded") or 0.0)
        drop_n = float(d.get("dropped") or 0.0)
        if rec_n > 0 and drop_n > 0.5 * rec_n:
            out.append(
                f"blackbox_dropped: dump {d.get('path')} "
                f"(reason={d.get('reason')}) overwrote {_n(drop_n)} of "
                f"{_n(rec_n)} ring records before dumping — more than "
                f"half its forensic window was lost; grow "
                f"ObsConfig.blackbox_capacity")
    return out


# -- postmortem mode (obs/postmortem.py bundles, ISSUE 17) ---------------

# kinds that end a process/component's story — the root-cause walk
# starts from the LAST of these on the merged timeline
TERMINAL_KINDS = ("crash", "stall", "quarantine", "peer_stall",
                  "supervisor_restart", "actor_error", "kill")
# kinds that count as attributed anomalies when walking backwards
# (terminal kinds included: an earlier kill can be the cause of a
# later restart)
ANOMALY_KINDS = TERMINAL_KINDS + (
    "wedge", "perf_degradation", "learning_degradation", "remediation",
    "peer_disconnect", "wire_decode_error", "reconnect", "drop",
    "backpressure", "serve_error", "instrument_range")


def _instrument_anomalies(bundle: dict) -> list[dict]:
    """Each dump's instrument snapshot run through the INSTRUMENTS
    healthy-range table (the same predicate as --check): a violated
    row becomes an attributed anomaly at the dump's wall time."""
    out = []
    for d in bundle.get("dumps", []):
        pseudo = {"gauges": d.get("gauge", {}) or {},
                  "hists": d.get("hist", {}) or {},
                  "ctrs": d.get("ctr", {}) or {}}
        for v in check_violations(pseudo):
            out.append({"t": float(d.get("wall_unix", 0.0)),
                        "peer": d.get("peer", "?"),
                        "kind": "instrument_range",
                        "component": v.split(":", 1)[0],
                        "detail": {"violation": v}})
    return out


def postmortem_root_cause(bundle: dict) -> dict | None:
    """Walk the merged timeline backwards from the terminal event and
    name the first attributed anomaly preceding it. Returns
    ``{"terminal", "anomaly", "gap_s"}`` (anomaly None when the
    terminal event is the first recorded thing), or None for an empty
    bundle."""
    timeline = sorted(list(bundle.get("timeline", []))
                      + _instrument_anomalies(bundle),
                      key=lambda e: float(e.get("t", 0.0)))
    if not timeline:
        return None
    terminal = None
    for e in reversed(timeline):
        if e.get("kind") in TERMINAL_KINDS:
            terminal = e
            break
    if terminal is None:
        terminal = timeline[-1]
    t_key = (terminal.get("kind"), terminal.get("peer"),
             terminal.get("component"))
    anomaly = None
    for e in reversed(timeline):
        if float(e.get("t", 0.0)) > float(terminal.get("t", 0.0)):
            continue
        if e is terminal or e.get("kind") not in ANOMALY_KINDS:
            continue
        # the same incident often appears twice (ring record + JSONL
        # event): an echo of the terminal itself is not its cause
        if (e.get("kind"), e.get("peer"),
                e.get("component")) == t_key:
            continue
        anomaly = e
        break
    gap = (float(terminal.get("t", 0.0)) - float(anomaly.get("t", 0.0))
           if anomaly is not None else None)
    return {"terminal": terminal, "anomaly": anomaly, "gap_s": gap}


def _fmt_event(e: dict) -> str:
    comp = e.get("component")
    return (f"{e.get('kind')} peer={e.get('peer')}"
            + (f" component={comp}" if comp else ""))


def format_postmortem(bundle: dict, tail: int = 20) -> str:
    """Human postmortem: bundle inventory, the timeline tail, and the
    root-cause line the chaos lane asserts on."""
    lines = ["postmortem bundle:"]
    lines.append(f"  peers: {', '.join(bundle.get('peers', [])) or '-'}")
    lines.append(f"  dumps: {len(bundle.get('dumps', []))}")
    for s in bundle.get("skipped_dumps", []):
        lines.append(f"  skipped dump: {s.get('file')} "
                     f"({s.get('reason')})")
    lines.append(f"  frames retained: {len(bundle.get('frames', {}))}")
    lines.append(f"  jsonl tail: {len(bundle.get('jsonl_tail', []))} "
                 "records")
    timeline = bundle.get("timeline", [])
    rc = postmortem_root_cause(bundle)
    lines.append("")
    lines.append(f"timeline (last {min(tail, len(timeline))} of "
                 f"{len(timeline)} events):")
    t_end = float(timeline[-1]["t"]) if timeline else 0.0
    for e in timeline[-tail:]:
        dt = float(e.get("t", 0.0)) - t_end
        lines.append(f"  {dt:+9.3f}s  {_fmt_event(e)}")
    lines.append("")
    if rc is None:
        lines.append("root cause: no events in bundle")
        return "\n".join(lines)
    term = rc["terminal"]
    if rc["anomaly"] is None:
        lines.append(f"root cause: none attributed — terminal event "
                     f"{_fmt_event(term)} is the first recorded event")
    else:
        a = rc["anomaly"]
        detail = a.get("detail") or {}
        why = detail.get("violation") or detail.get("error") \
            or detail.get("reason") or ""
        lines.append(
            f"root cause: {_fmt_event(a)} at -{rc['gap_s']:.3f}s "
            f"before terminal {_fmt_event(term)}"
            + (f" — {why}" if why else ""))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ape_x_dqn_tpu.obs.report",
        description="Summarize a run's metrics JSONL: stage times, "
                    "staleness percentiles, throughput, stalls.")
    ap.add_argument("jsonl", help="metrics JSONL file (--metrics-file "
                                  "of a run with obs enabled), or a "
                                  "postmortem bundle with --postmortem")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object instead "
                         "of the text report")
    ap.add_argument("--postmortem", action="store_true",
                    help="treat the positional argument as an "
                         "obs/postmortem.py bundle: print its merged "
                         "timeline and the root-cause line (walks "
                         "backwards from the terminal event to the "
                         "first attributed anomaly)")
    ap.add_argument("--check", action="store_true",
                    help="health-gate mode: print the report, then "
                         "exit 2 if any healthy-range row is violated "
                         "(the warn-only online engines never abort; "
                         "this is the CI-facing gate)")
    ap.add_argument("--follow", action="store_true",
                    help="live-tail mode: re-summarize and re-print "
                         "whenever the JSONL grows (the fleet "
                         "aggregator appends per-peer frames as they "
                         "arrive); stop with Ctrl-C")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval for --follow (seconds)")
    args = ap.parse_args(argv)
    if args.postmortem:
        try:
            with open(args.jsonl) as fh:
                bundle = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read bundle {args.jsonl}: {e}",
                  file=sys.stderr)
            return 1
        if args.json:
            rc = postmortem_root_cause(bundle)
            print(json.dumps({"root_cause": rc,
                              "peers": bundle.get("peers", []),
                              "dumps": len(bundle.get("dumps", [])),
                              "skipped_dumps":
                                  bundle.get("skipped_dumps", [])}))
        else:
            print(format_postmortem(bundle))
        return 0
    if not args.follow:
        records = load_records(args.jsonl)
        if not records:
            print(f"no records in {args.jsonl}", file=sys.stderr)
            return 1
        summary = summarize(records)
        print(json.dumps(summary) if args.json
              else format_report(summary))
        if args.check:
            violations = check_violations(summary)
            if violations:
                print("\nhealth check: FAILED "
                      f"({len(violations)} healthy-range violations)",
                      file=sys.stderr)
                for v in violations:
                    print(f"  ✗ {v}", file=sys.stderr)
                return 2
            print("\nhealth check: ok — all healthy-range rows pass")
        return 0
    return _follow(args.jsonl, args.interval, args.json)


def _follow(path: str, interval: float, as_json: bool) -> int:
    """Live tail: poll the JSONL's size, re-print the full report on
    growth. Re-summarizing from scratch keeps this trivially correct
    (last-write-wins folding is not incremental-friendly) and the files
    are small — one record per publish/frame, not per transition."""
    import os
    import time as _time

    last_size = -1
    try:
        while True:
            try:
                size = os.stat(path).st_size
            except OSError:
                size = -1  # not created yet; keep polling
            if size != last_size and size > 0:
                last_size = size
                records = load_records(path)
                if records:
                    summary = summarize(records)
                    out = (json.dumps(summary) if as_json
                           else format_report(summary))
                    print(f"--- {path} @ {size} bytes ---")
                    print(out, flush=True)
            _time.sleep(max(interval, 0.1))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
