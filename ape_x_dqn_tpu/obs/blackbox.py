"""Per-process flight recorder — the forensics plane's black box.

Every prior obs plane (trace spans, fleet telemetry, perf events,
learning health) is forward-streaming: it survives only as long as the
process that produced it. The FlightRecorder is the opposite — a
fixed-size, allocation-free ring of recent *significant* records
(attributed events: remediation, perf/learning degradation, reconnects,
drops, stalls; plus a short log tail) that is dumped atomically to a
per-process ``blackbox-<peer>.json`` when the process dies or is asked
to explain itself:

- unhandled exception (chained ``sys.excepthook``) and ``atexit``
- ``StallError`` (the Obs facade dumps in ``check_stalled`` before
  closing and re-raising)
- ``SIGUSR2`` — live, non-fatal "explain yourself" (main thread only;
  installation is silently skipped off the main thread)
- watchdog / supervisor request (the driver archives the victim's ring
  on every restart / quarantine decision)

Expensive context — span aggregates, ctr/gauge snapshots, heartbeat
ages, and thread stacks via ``sys._current_frames`` — is captured at
DUMP time, not per record, so ``record()`` stays cheap enough for hot
paths. The dump itself is torn-write safe (tmp + ``os.replace``); the
bundler (obs/postmortem.py) skips and counts any partial that an
unlucky kill still manages to leave behind.

Gated by ``ObsConfig.blackbox*`` knobs with the same disabled-⇒-no-op
contract as ``NULL_OBS``: a disabled config yields ``NULL_BLACKBOX``,
which records nothing and writes no files.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import socket
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable

from ape_x_dqn_tpu.obs.health import make_lock

_STACK_DEPTH = 24  # frames kept per thread in a dump's stack snapshot


def default_peer() -> str:
    """Stable-enough per-process identity for the dump filename."""
    return f"{socket.gethostname()}-{os.getpid()}"


def _thread_stacks(limit: int = _STACK_DEPTH) -> dict[str, list[str]]:
    """``sys._current_frames`` rendered as short ``file:line func``
    strings, keyed by thread name (ident when unnamed)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: dict[str, list[str]] = {}
    for ident, frame in sys._current_frames().items():
        stack = traceback.extract_stack(frame, limit=limit)
        out[names.get(ident, f"thread-{ident}")] = [
            f"{os.path.basename(fs.filename)}:{fs.lineno} {fs.name}"
            for fs in stack]
    return out


class NullBlackBox:
    """Disabled recorder: records nothing, dumps nothing, installs
    nothing. Method-for-method parity with FlightRecorder."""

    enabled = False
    peer = ""

    def set_peer(self, peer: str) -> None:
        pass

    def record(self, kind: str, **fields) -> None:
        pass

    def log_line(self, line: str) -> None:
        pass

    def add_context_provider(self, fn: Callable[[], dict]) -> None:
        pass

    def dump(self, reason: str, component: str = "", step: int = 0,
             extra: dict | None = None) -> str | None:
        return None

    def install(self, signals: bool = True) -> None:
        pass

    def uninstall(self) -> None:
        pass


NULL_BLACKBOX = NullBlackBox()


class FlightRecorder:
    """Fixed-capacity ring of (wall_time, kind, fields) records.

    The ring is preallocated and overwritten in place — recording never
    grows it past capacity; overwrites are counted as drops so the
    ``blackbox_dropped / blackbox_records`` fraction is a published,
    checkable quantity (report --check warns when most of the window
    was lost).
    """

    enabled = True

    def __init__(self, obs: Any, peer: str = "", out_dir: str = ".",
                 capacity: int = 512, log_lines: int = 64):
        self._obs = obs  # counters ride the obs facade (may be minimal)
        self.peer = peer or default_peer()
        self._dir = out_dir or "."
        self._cap = max(int(capacity), 1)
        self._ring: list = [None] * self._cap  # guarded-by: _lock
        self._pos = 0  # guarded-by: _lock
        self._n = 0  # guarded-by: _lock
        self._recorded = 0  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock
        self._log: deque = deque(maxlen=max(int(log_lines), 1))
        self._lock = make_lock("blackbox.recorder")
        self._providers: list[Callable[[], dict]] = []
        self._dumps = 0
        self._last_dump_path: str | None = None
        self._installed = False
        self._prev_excepthook: Any = None
        self._prev_sigusr2: Any = None
        self._sig_installed = False

    # -- recording (hot path) -------------------------------------------

    def set_peer(self, peer: str) -> None:
        if peer:
            self.peer = peer

    def record(self, kind: str, **fields) -> None:
        """Append one significant record, overwriting the oldest when
        full. Cheap by design: one tuple, no snapshotting."""
        dropped = False
        with self._lock:
            if self._n == self._cap:
                dropped = True
                self._dropped += 1
            else:
                self._n += 1
            self._ring[self._pos] = (time.time(), kind, fields)
            self._pos = (self._pos + 1) % self._cap
            self._recorded += 1
        # counters outside the ring lock: registry locks are leaves,
        # never held while taking blackbox.recorder
        self._obs.count("blackbox_records")
        if dropped:
            self._obs.count("blackbox_dropped")

    def log_line(self, line: str) -> None:
        """Keep the last N log lines (separate from the event ring so
        chatty logging can't evict attributed events)."""
        self._log.append((time.time(), str(line)))

    def add_context_provider(self, fn: Callable[[], dict]) -> None:
        """Register a callable whose dict result is merged into every
        dump (e.g. the driver contributes the fleet's retained per-peer
        telemetry frames — the remote's black box of last resort)."""
        self._providers.append(fn)

    # -- dumping --------------------------------------------------------

    @property
    def path(self) -> str:
        return os.path.abspath(
            os.path.join(self._dir, f"blackbox-{self.peer}.json"))

    def _snapshot(self) -> tuple[list[dict], int, int, int]:
        with self._lock:
            n, pos = self._n, self._pos
            oldest = (pos - n) % self._cap
            recs = [self._ring[(oldest + i) % self._cap]
                    for i in range(n)]
            recorded, dropped = self._recorded, self._dropped
        out = []
        for t, kind, fields in recs:
            rec = {"t": t, "kind": kind}
            rec.update(fields)
            out.append(rec)
        return out, recorded, dropped, len(out)

    def dump(self, reason: str, component: str = "", step: int = 0,
             extra: dict | None = None) -> str | None:
        """Write the box atomically; returns the path (None on failure
        — the dump path must never mask the crash it documents)."""
        try:
            records, recorded, dropped, n = self._snapshot()
            payload: dict[str, Any] = {
                "blackbox": 1,
                "peer": self.peer,
                "host": socket.gethostname(),
                "pid": os.getpid(),
                "reason": reason,
                "component": component,
                "step": int(step),
                "wall_unix": time.time(),
                "records": records,
                "recorded": recorded,
                "dropped": dropped,
                "log_tail": [[t, line] for t, line in list(self._log)],
            }
            try:
                payload["threads"] = _thread_stacks()
            except Exception:
                pass
            # instrument + span + heartbeat context when riding a full
            # Obs (minimal facades — e.g. the chaos bench sink — only
            # need .count)
            reg = getattr(self._obs, "registry", None)
            if reg is not None:
                payload.update(reg.snapshot_frame())
            tracer = getattr(self._obs, "tracer", None)
            if tracer is not None:
                try:
                    payload["span"] = tracer.aggregates()
                except Exception:
                    pass
            hb = getattr(self._obs, "heartbeats", None)
            if hb is not None:
                payload["hb"] = {name: [round(age, 3), note]
                                 for name, (age, note)
                                 in hb.ages().items()}
            for fn in self._providers:
                try:
                    payload.update(fn() or {})
                except Exception:
                    pass
            if extra:
                payload["extra"] = extra
            path = self.path
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(payload, fh)
            os.replace(tmp, path)
            self._dumps += 1
            self._last_dump_path = path
            self._obs.count("blackbox_dumps")
            # correlate into the run JSONL so `report --check` can
            # demand a dump on disk for every terminal stall/quarantine
            metrics = getattr(self._obs, "metrics", None)
            if metrics is not None:
                metrics.log(int(step), blackbox_dump=path,
                            blackbox_reason=reason,
                            blackbox_peer=self.peer,
                            blackbox_component=component,
                            blackbox_ring_recorded=recorded,
                            blackbox_ring_dropped=dropped)
            return path
        except Exception:
            return None

    # -- crash-path installation ----------------------------------------

    def install(self, signals: bool = True) -> None:
        """Chain the crash hooks: excepthook + atexit, and (main thread
        only) a live SIGUSR2 dump. Idempotent."""
        if self._installed:
            return
        self._installed = True
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        atexit.register(self._atexit_dump)
        if signals and hasattr(signal, "SIGUSR2"):
            try:
                self._prev_sigusr2 = signal.signal(
                    signal.SIGUSR2, self._sigusr2)
                self._sig_installed = True
            except (ValueError, OSError):
                # signal.signal only works on the main thread; embedded
                # runs (tests spawning actor hosts in threads) skip it
                self._sig_installed = False

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        if sys.excepthook is self._excepthook:
            sys.excepthook = self._prev_excepthook
        try:
            atexit.unregister(self._atexit_dump)
        except Exception:
            pass
        if self._sig_installed:
            try:
                signal.signal(signal.SIGUSR2, self._prev_sigusr2)
            except (ValueError, OSError):
                pass
            self._sig_installed = False

    def _excepthook(self, exc_type, exc, tb) -> None:
        try:
            self.record("crash", error=repr(exc)[:200])
            self.dump("crash", component=exc_type.__name__, extra={
                "traceback": traceback.format_exception(
                    exc_type, exc, tb)[-_STACK_DEPTH:]})
        finally:
            prev = self._prev_excepthook or sys.__excepthook__
            prev(exc_type, exc, tb)

    def _atexit_dump(self) -> None:
        # only when nothing else dumped: a crash/stall dump already has
        # the attributed reason — don't overwrite it with "atexit"
        if self._dumps == 0:
            self.dump("atexit")

    def _sigusr2(self, signum, frame) -> None:
        self.record("sigusr2")
        self.dump("sigusr2")
