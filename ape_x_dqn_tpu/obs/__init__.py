"""End-to-end observability for the actor→replay→learner loop.

- obs.trace: Chrome/Perfetto `trace_event` span tracer (host-side).
- obs.registry: counters / gauges / fixed-bucket histograms feeding
  the canonical metrics JSONL.
- obs.health: heartbeats + attributed stall watchdogs.
- obs.core: the `Obs` facade drivers thread through the runtime
  (`build_obs(cfg.obs, metrics)`), with a no-op twin when disabled.
- obs.report: offline CLI (`python -m ape_x_dqn_tpu.obs.report`).

Everything here is jax-free at import time (the multihost StallWatchdog
defers its jax import) so the report CLI stays cheap to start.
"""

from ape_x_dqn_tpu.obs.core import (
    NULL_OBS, NullObs, Obs, SampleAgeTracker, build_obs)
from ape_x_dqn_tpu.obs.health import (
    HeartbeatRegistry, HeartbeatWatchdog, LockOrderError,
    LockOrderRecorder, StallError, StallWatchdog, WitnessLock,
    make_lock)
from ape_x_dqn_tpu.obs.registry import (
    Counter, Gauge, Histogram, MetricRegistry, geometric_edges)
from ape_x_dqn_tpu.obs.trace import (
    NULL_TRACER, NullTracer, SpanTracer, load_trace, span_names)

__all__ = [
    "NULL_OBS", "NULL_TRACER", "Counter", "Gauge", "HeartbeatRegistry",
    "HeartbeatWatchdog", "Histogram", "LockOrderError",
    "LockOrderRecorder", "MetricRegistry", "NullObs", "NullTracer",
    "Obs", "SampleAgeTracker", "SpanTracer", "StallError",
    "StallWatchdog", "WitnessLock", "build_obs", "geometric_edges",
    "load_trace", "make_lock", "span_names",
]
