"""Fleet telemetry plane: per-peer obs snapshots over the wire.

PR 2's observability layer is strictly per-process: each actor host,
inference server, and learner writes its own metrics stream, spans
never cross a socket, and the stall watchdog only attributes stalls it
can see locally — "today a lost actor is silence" (ROADMAP item 4).
This module closes that gap on top of the existing transport and obs
stack, in three pieces:

- `StampingTransport` (actor-host side): wraps the experience
  transport, stamping every shipped batch with a monotonically-
  assigned `batch_id` and the origin `peer` id as scalar meta (they
  ride the JSON header of the wire payload, readable without decoding
  any array — comm/socket_transport.WireBatch.get). Each ship is also
  recorded as a correlation event so the learner can reconstruct the
  actor->encode->wire->decode->staging->add journey of a transition
  batch as ONE cross-process trace.

- `TelemetryEmitter` (actor-host side): a low-rate pump thread that
  snapshots the local Obs — heartbeat ages, counters/gauges/histogram
  snapshots, span aggregates, recent ship events — into a compact
  JSON frame and ships it as MSG_TELEMETRY (send_telemetry is
  best-effort and capability-gated: against an old server the frame
  is simply never sent).

- `FleetAggregator` (learner/driver side): installed on the ingest
  server's `on_telemetry`/`on_disconnect` hooks. Each arriving frame
  is merged into the single run JSONL under `peer/<id>/...` keys
  (one self-contained artifact per run stays the invariant), remote
  heartbeats are re-beaten into the local `HeartbeatRegistry` with
  `now = local_now - age_s` — ages cross clock domains, absolute
  stamps do not — so the driver's existing `check_stalled()` poll
  raises an attributed StallError for a wedged REMOTE actor, and ship
  events become `remote_span` entries on a `peer/<id>` track of the
  learner's trace. A peer's socket closing bumps the
  `peer_disconnects` counter and logs an attributed record instead of
  silence.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

from ape_x_dqn_tpu.obs.health import make_lock

# correlation events kept between telemetry pumps; at the default
# 2s cadence this caps frame growth while covering hundreds of ships
_EVENT_RING = 256
# span-arg batch_id attribution lists are truncated to this many ids
MAX_SPAN_IDS = 8


class StampingTransport:
    """Experience-transport wrapper that stamps origin correlation
    metadata on every shipped batch.

    Drop-in where a Transport goes (actors only call send_experience;
    everything else delegates). Stamps are plain scalar entries —
    `batch_id` (monotonic per origin) and `peer` — so they survive any
    wire codec and are header-readable on the learner side."""

    def __init__(self, inner: Any, peer: str):
        self._inner = inner
        self.peer = peer
        self._lock = make_lock("fleet.stamper")
        self._next_id = 0  # guarded-by: _lock
        self._rows_out = 0  # guarded-by: _lock
        self._events: deque = deque(maxlen=_EVENT_RING)  # guarded-by: _lock

    def send_experience(self, batch: dict) -> None:
        rows = 0
        pri = batch.get("priorities")
        if pri is not None:
            rows = int(pri.shape[0])
        with self._lock:
            bid = self._next_id
            self._next_id += 1
            self._rows_out += rows
            self._events.append(
                ("actor.ship", 0.0, time.monotonic(),
                 {"batch_id": bid, "rows": rows}))
        batch["batch_id"] = bid
        batch["peer"] = self.peer
        self._inner.send_experience(batch)

    def drain_events(self, now: float | None = None
                     ) -> list[list]:
        """Correlation events since the last drain, each as
        [name, dur_s, age_s, args] — ages computed at drain time so
        they are fresh when the frame ships."""
        now = time.monotonic() if now is None else now
        with self._lock:
            events = list(self._events)
            self._events.clear()
        return [[name, dur, max(now - t_end, 0.0), args]
                for name, dur, t_end, args in events]

    @property
    def rows_out(self) -> int:
        """Cumulative transition rows shipped (the aggregator derives
        per-peer ingest rate from deltas of this across frames)."""
        with self._lock:
            return self._rows_out

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


def build_frame(obs: Any, peer: str, seq: int,
                events: list | None = None,
                rows_out: int | None = None) -> dict:
    """One compact telemetry frame from a live Obs: peer identity,
    heartbeat AGES (clock-domain free), instrument snapshots, span
    aggregates, and correlation events. Everything JSON-safe."""
    frame: dict[str, Any] = {"peer": peer, "seq": int(seq)}
    frame["hb"] = {name: [round(age, 3), note]
                   for name, (age, note) in obs.heartbeats.ages().items()}
    frame.update(obs.registry.snapshot_frame())
    frame["span"] = obs.tracer.aggregates()
    if events:
        frame["events"] = events
    if rows_out is not None:
        frame["rows_out"] = int(rows_out)
    return frame


class TelemetryEmitter:
    """Actor-host pump: every `interval_s`, build a frame from the
    local Obs and ship it (best-effort) over the transport.

    The transport may or may not be a StampingTransport; when it is,
    its ship events and rows_out ride along for correlation and
    per-peer rate. A final frame ships at stop() so the learner sees
    shutdown-fresh heartbeat ages."""

    def __init__(self, transport: Any, obs: Any, peer: str,
                 interval_s: float = 2.0):
        self._transport = transport
        self._obs = obs
        self.peer = peer
        self._interval = interval_s
        self._seq = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="telemetry-pump", daemon=True)

    def pump_once(self) -> bool:
        events = None
        rows = None
        drain = getattr(self._transport, "drain_events", None)
        if drain is not None:
            events = drain()
            rows = self._transport.rows_out
        frame = build_frame(self._obs, self.peer, self._seq,
                            events=events, rows_out=rows)
        sent = bool(self._transport.send_telemetry(frame))
        if sent:
            self._seq += 1
        return sent

    def start(self) -> None:
        if self._interval > 0:
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2)
        # shutdown-fresh final frame (also covers interval_s=0 callers
        # that never started the thread but want one frame at exit)
        self.pump_once()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.pump_once()


class FleetAggregator:
    """Learner/driver-side merge of per-peer telemetry into the run's
    single obs surface. Construct with the driver's (enabled) Obs and
    install on the ingest transport; both hooks are called from
    transport reader threads and are thread-safe."""

    def __init__(self, obs: Any, metrics: Any = None):
        self._obs = obs
        self._metrics = metrics if metrics is not None else obs.metrics
        self._lock = make_lock("fleet.aggregator")
        # peer -> {"seq", "rows_out", "t", "rate", "connected"}
        self._peers: dict[str, dict] = {}  # guarded-by: _lock

    def install(self, transport: Any) -> bool:
        """Attach to a transport exposing on_telemetry/on_disconnect
        (SocketIngestServer, LoopbackTransport). Returns False for
        transports without a telemetry plane — callers need no
        hasattr-dance."""
        if not hasattr(transport, "on_telemetry"):
            return False
        transport.on_telemetry = self.on_frame
        if hasattr(transport, "on_disconnect"):
            transport.on_disconnect = self.on_disconnect
        if hasattr(transport, "on_decode_error"):
            transport.on_decode_error = self.on_decode_error
        return True

    @property
    def peers(self) -> list[str]:
        with self._lock:
            return sorted(self._peers)

    def _step(self) -> int:
        return int(getattr(self._obs, "_learner_step", 0))

    def on_frame(self, peer: str, frame: dict) -> None:
        obs = self._obs
        now = time.monotonic()
        seq = int(frame.get("seq", 0))
        rows_out = frame.get("rows_out")
        with self._lock:
            st = self._peers.setdefault(
                peer, {"seq": -1, "rows_out": None, "t": now,
                       "rate": 0.0, "connected": True})
            st["connected"] = True
            if seq <= st["seq"]:
                return  # duplicate/reordered frame: keep state monotonic
            st["seq"] = seq
            # forensics retention (obs/postmortem.py): the LAST frame
            # from each peer is kept even after disconnect — for a peer
            # that dies without dumping its own black box, this is the
            # black box of last resort
            st["frame"] = frame
            st["recv_unix"] = time.time()
            if rows_out is not None and st["rows_out"] is not None \
                    and now > st["t"]:
                st["rate"] = (max(int(rows_out) - st["rows_out"], 0)
                              / (now - st["t"]))
            if rows_out is not None:
                st["rows_out"] = int(rows_out)
            st["t"] = now
            n_connected = sum(1 for p in self._peers.values()
                              if p["connected"])
            rate = st["rate"]
        obs.count("telemetry_frames")
        obs.gauge("fleet_peers", n_connected)
        # per-peer perf-regression baseline (obs/profiling.PerfMonitor):
        # a peer whose experience output collapses below its own EWMA
        # baseline fires an attributed PerfDegradation record carrying
        # the peer id — warn-only, distinct from the stall watchdog
        if rate > 0.0:
            obs.perf_rate("ingest_rows_per_s", rate, step=self._step(),
                          peer=peer)
        # the peer itself heartbeats by sending frames at all; each
        # remote component re-beats at local_now - reported_age so the
        # driver's check_stalled() attributes a wedged REMOTE component
        # exactly like a local one (component name "<peer>/<name>")
        obs.heartbeats.beat(peer, f"telemetry seq {seq}", now=now)
        for name, entry in dict(frame.get("hb", {})).items():
            try:
                age, note = float(entry[0]), str(entry[1])
            except (TypeError, ValueError, IndexError):
                continue
            obs.heartbeats.beat(f"{peer}/{name}", note, now=now - age)
        # correlation events -> synthetic peer track in the trace
        for ev in frame.get("events", ()):
            try:
                name, dur, age, args = ev
            except (TypeError, ValueError):
                continue
            obs.tracer.remote_span(str(name), float(dur), float(age),
                                   peer=peer, **dict(args))
        # merge the peer's instruments into the run JSONL with
        # peer/<id>/ attribution (dynamic keys: the report groups them
        # back per peer; the obs-names checker scans literals only)
        rec: dict[str, Any] = {f"peer/{peer}/seq": seq,
                               f"peer/{peer}/gauge/ingest_rate": rate}
        for kind in ("ctr", "gauge"):
            for k, v in dict(frame.get(kind, {})).items():
                rec[f"peer/{peer}/{kind}/{k}"] = v
        for k, v in dict(frame.get("hist", {})).items():
            if isinstance(v, dict):
                rec[f"peer/{peer}/hist/{k}"] = v
        for k, v in dict(frame.get("span", {})).items():
            if isinstance(v, dict):
                rec[f"peer/{peer}/span/{k}"] = v
        for k, v in dict(frame.get("hb", {})).items():
            try:
                rec[f"peer/{peer}/hb/{k}"] = float(v[0])
            except (TypeError, ValueError, IndexError):
                continue
        self._metrics.log(self._step(), **rec)

    def on_disconnect(self, peer: str) -> None:
        """An identified peer's socket closed: attributed, counted,
        logged — never silence. Its heartbeat entries stay registered,
        so if nothing reconnects the stall watchdog ALSO raises with
        the peer's name (the chaos-lane contract: kill an actor
        mid-run and the run says so twice, loudly)."""
        obs = self._obs
        with self._lock:
            st = self._peers.get(peer)
            if st is not None:
                st["connected"] = False
                st["rows_out"] = None  # a reconnect restarts the rate
            n_connected = sum(1 for p in self._peers.values()
                              if p["connected"])
        obs.count("peer_disconnects")
        obs.gauge("fleet_peers", n_connected)
        bb = getattr(obs, "blackbox", None)
        if bb is not None:
            bb.record("peer_disconnect", peer=peer)
        self._metrics.log(self._step(), peer_disconnect=peer)

    def retained_frames(self) -> dict[str, dict]:
        """Last telemetry frame per peer (connected or not), for the
        postmortem bundler: ``{peer: {frame, recv_unix, connected}}``.
        Peers that never completed a frame are omitted."""
        with self._lock:
            return {peer: {"frame": st["frame"],
                           "recv_unix": st["recv_unix"],
                           "connected": bool(st["connected"])}
                    for peer, st in self._peers.items()
                    if st.get("frame") is not None}

    def on_decode_error(self, peer: str, reason: str) -> None:
        """A truncated/garbled frame arrived (and dropped its
        connection): counter + attributed JSONL record, so a byzantine
        or proxy-mangled peer shows up as ITSELF in the run artifact —
        peer is "unidentified" when the connection never sent
        telemetry."""
        self._obs.count("wire_decode_errors")
        self._metrics.log(self._step(), wire_decode_error=peer,
                          wire_decode_reason=str(reason)[:200])
