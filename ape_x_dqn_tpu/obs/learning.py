"""Learning-health plane: in-graph training diagnostics + LearnMonitor
(ISSUE 10, the fourth obs plane).

PRs 2/6/8 watch the *systems* (spans, fleet telemetry, MFU/roofline);
nothing watched whether the RL itself was healthy. This module closes
that in three pieces:

- jit-safe diagnostic helpers (`sgd_diag`, `replay_health`,
  `replay_health_sharded`) the four learner cycles call INSIDE their
  existing jits. Everything is a cheap scalar reduction over arrays the
  loss/optimizer already materialized (TD quantiles, overestimation
  gap, grad/update norms, IS-weight effective sample size,
  priority-mass concentration, in-graph sampled-transition age, and the
  descent-time vs write-back-time priority-staleness delta the prefetch
  pipeline accepts by design — ROADMAP item 3 said "quantify, don't
  assume"; this is the instrument). The result rides the learner's
  metrics pytree through the train_many scan, so the host reads it only
  at the block_until_ready sync points the drivers already pay for:
  zero new device syncs on the default path.
- `publish_learn` — one literal `learn_*` gauge emission per
  diagnostic (the obs-names contract: every instrument is a listed,
  greppable row in obs/report.py), plus dynamic `learn/<tenant>/...`
  duplicates so the 57-game rotation becomes 57 attributable tenants
  (tenant = cfg.env.id, same identity the suite runner uses).
- `LearnMonitor` — warn-only anomaly engine, sibling of profiling.py's
  PerfMonitor: an EWMA baseline over the loss plus absolute-threshold
  rules over the diagnostics (loss spike, Q blowup, ESS collapse, dead
  gradients, priority collapse). Fires ONE attributed
  `learning_degradation` JSONL event per (tenant, rule) cooldown and a
  counter — never an exception: a sick learner is survivable and the
  artifact should say so; aborting is the driver's job, not the
  monitor's. The CI gate lives in `obs/report.py --check`, not here.

Disabled obs routes through NullObs and never reaches this module's
host side; the in-graph helpers import jax lazily and add the same
handful of fused scalar reductions whether or not anyone reads them
(measured in bench.py --smoke: below noise).
"""

from __future__ import annotations

import logging
import time
from typing import Any

from ape_x_dqn_tpu.obs.health import make_lock

# Absolute-threshold rule bounds. These are deliberately loose — the
# monitor flags pathology (divergence, collapse), not suboptimality —
# and each is mirrored by the matching healthy-range row in
# obs/report.py INSTRUMENTS so the offline report flags the same line
# the online monitor fires on.
Q_MAX_LIMIT = 1e3          # |q_max| above this = Q blowup (catch/atari
#                            Qs live in clipped-reward units, O(1..100))
ESS_FRAC_MIN = 0.05        # IS effective-sample-size below 5% of batch
UPDATE_RATIO_MIN = 1e-9    # ||update||/||param|| below this = dead grads
TOP_FRAC_MAX = 0.5         # one transition holding half the priority mass


# -- in-graph diagnostics (pure, jit-safe; called inside learner jits) ----

def sgd_diag(aux: dict, is_w, grads, updates, params) -> dict:
    """Per-SGD-step learning diagnostics as a flat dict of f32 device
    scalars. `aux` is the loss aux (ops/losses.py), `is_w` the IS
    weights actually applied, `grads`/`updates`/`params` the optimizer
    triple. Everything here is a reduction over arrays the step already
    computed — no new matmuls, no new memory traffic beyond scalars."""
    import jax.numpy as jnp
    import optax

    td = aux["td_abs"].astype(jnp.float32).reshape(-1)
    qs = jnp.percentile(td, jnp.asarray([50.0, 90.0, 99.0]))
    w = is_w.astype(jnp.float32).reshape(-1)
    # Kish effective sample size as a fraction of the batch: 1.0 under
    # uniform weights, ->1/B when one sample dominates (beta pathology)
    ess = jnp.square(w.sum()) / (
        w.size * jnp.maximum((w * w).sum(), 1e-12))
    pn = optax.global_norm(params)
    zero = jnp.float32(0.0)
    return {
        "td_abs_p50": qs[0],
        "td_abs_p90": qs[1],
        "td_abs_p99": qs[2],
        "td_signed_mean": aux.get("td_mean", zero),
        "q_mean": aux.get("q_mean", zero),
        "q_max": aux.get("q_max", zero),
        "target_q_mean": aux.get("target_q_mean", zero),
        # overestimation gap (van Hasselt 2016): online bootstrap vs
        # the double-DQN target-net bootstrap, the quantity Double-DQN
        # exists to shrink — computed in the loss, surfaced here
        "q_gap": aux.get("q_gap", zero),
        "grad_norm": optax.global_norm(grads),
        "update_ratio": optax.global_norm(updates)
        / jnp.maximum(pn, 1e-12),
        "is_ess_frac": ess,
    }


def replay_health(replay, rs, idx, pri_then) -> dict:
    """Replay-side diagnostics at write-back time (single-chip states).

    `idx` is any int array of sampled leaf indices; `pri_then` the
    matching priorities read AT DESCENT time (None on paths where the
    draw and write-back see the same tree — staleness is identically 0
    there and reported as such). Ages are ring distances from the write
    cursor in TRANSITIONS, so flat and frame-ring layouts agree."""
    import jax.numpy as jnp

    cap = int(replay.capacity)
    idx = idx.reshape(-1)
    cursor = replay.cursor_transitions(rs)
    age = jnp.mod(cursor - 1 - idx, cap).astype(jnp.float32)
    ages = jnp.percentile(age, jnp.asarray([50.0, 90.0]))
    out = {"sample_age_p50": ages[0], "sample_age_p90": ages[1]}
    zero = jnp.float32(0.0)
    if not getattr(replay, "has_priorities", True):
        out["prio_staleness_frac"] = zero
        out["priority_top_frac"] = zero
        return out
    if pri_then is None:
        out["prio_staleness_frac"] = zero
    else:
        then = pri_then.reshape(-1).astype(jnp.float32)
        now = replay.leaf_priorities(rs, idx)
        # mean |delta p| relative to the mean descent-time priority:
        # 0 on the fused path, the measured one-dispatch lag under
        # sample_prefetch / K-batch write-back
        out["prio_staleness_frac"] = jnp.abs(now - then).mean() \
            / jnp.maximum(then.mean(), 1e-12)
    # concentration: largest single leaf's share of the total priority
    # mass — ->1.0 means the sampler has collapsed onto one transition
    leaves = rs.tree[cap:]
    out["priority_top_frac"] = leaves.max() \
        / jnp.maximum(rs.tree[1], 1e-12)
    return out


def replay_health_sharded(replay, rs, idx, pri_then) -> dict:
    """`replay_health` for the dist learner's [dp]-leading shard states
    (`replay` is the per-shard replay; `idx` is [dp, n]). Reductions run
    over all shards — under GSPMD the plain jnp ops lower to the psum /
    all-gather collectives, so the result is the global statistic."""
    import jax
    import jax.numpy as jnp

    cap = int(replay.capacity)
    cursor = jax.vmap(replay.cursor_transitions)(rs)  # [dp]
    age = jnp.mod(cursor[:, None] - 1 - idx, cap).astype(jnp.float32)
    ages = jnp.percentile(age.reshape(-1), jnp.asarray([50.0, 90.0]))
    out = {"sample_age_p50": ages[0], "sample_age_p90": ages[1]}
    zero = jnp.float32(0.0)
    if not getattr(replay, "has_priorities", True):
        out["prio_staleness_frac"] = zero
        out["priority_top_frac"] = zero
        return out
    if pri_then is None:
        out["prio_staleness_frac"] = zero
    else:
        then = pri_then.astype(jnp.float32)
        now = jax.vmap(replay.leaf_priorities)(rs, idx)
        out["prio_staleness_frac"] = jnp.abs(now - then).mean() \
            / jnp.maximum(then.mean(), 1e-12)
    leaves = rs.tree[:, cap:]            # [dp, cap]
    mass = rs.tree[:, 1].sum()           # global mass across shards
    out["priority_top_frac"] = leaves.max() / jnp.maximum(mass, 1e-12)
    return out


# -- host-side publication -------------------------------------------------

def publish_learn(obs, vals: dict, tenant: str = "") -> None:
    """Publish one host-read diag snapshot as `learn_*` gauges.

    One LITERAL emission per instrument (tools/apexlint obs-names
    cross-checks each against its obs/report.py INSTRUMENTS row); the
    per-tenant duplicates ride dynamic slash-prefixed keys, which the
    registry namespaces and the report regroups per game."""
    g = vals.get
    obs.gauge("learn_td_abs_p50", g("td_abs_p50", 0.0))
    obs.gauge("learn_td_abs_p90", g("td_abs_p90", 0.0))
    obs.gauge("learn_td_abs_p99", g("td_abs_p99", 0.0))
    obs.gauge("learn_td_signed_mean", g("td_signed_mean", 0.0))
    obs.gauge("learn_q_mean", g("q_mean", 0.0))
    obs.gauge("learn_q_max", g("q_max", 0.0))
    obs.gauge("learn_target_q_mean", g("target_q_mean", 0.0))
    obs.gauge("learn_q_gap", g("q_gap", 0.0))
    obs.gauge("learn_grad_norm", g("grad_norm", 0.0))
    obs.gauge("learn_update_ratio", g("update_ratio", 0.0))
    obs.gauge("learn_is_ess_frac", g("is_ess_frac", 1.0))
    obs.gauge("learn_priority_top_frac", g("priority_top_frac", 0.0))
    obs.gauge("learn_sample_age_p50", g("sample_age_p50", 0.0))
    obs.gauge("learn_sample_age_p90", g("sample_age_p90", 0.0))
    obs.gauge("learn_prio_staleness_frac", g("prio_staleness_frac", 0.0))
    if "shard_td_mean_min" in vals:  # dist learner only
        obs.gauge("learn_shard_td_mean_min", vals["shard_td_mean_min"])
        obs.gauge("learn_shard_td_mean_max", vals["shard_td_mean_max"])
    if tenant:
        for k, v in vals.items():
            obs.gauge(f"learn/{tenant}/{k}", v)


# -- the anomaly engine ----------------------------------------------------

class LearnMonitor:
    """Warn-only learning-anomaly engine (PerfMonitor's sibling).

    One EWMA baseline per tenant over the loss (relative rule: spike =
    loss > spike_mult x baseline after min_samples) plus four absolute
    rules over the diagnostics. Each (tenant, rule) fires at most once
    per cooldown; a fire is a counter bump + one attributed JSONL event
    — never an exception. Like PerfMonitor, the baseline keeps
    absorbing the new regime, so a persistently sick learner alerts
    once per cooldown and then becomes the new normal in the EWMA while
    the absolute rules (and the report's healthy ranges) keep flagging.
    """

    def __init__(self, obs, metrics, spike_mult: float = 10.0,
                 alpha: float = 0.2, min_samples: int = 8,
                 cooldown_s: float = 30.0):
        self._obs = obs
        self._metrics = metrics
        self.spike_mult = spike_mult
        self._alpha = alpha
        self._min_samples = min_samples
        self._cooldown_s = cooldown_s
        self._lock = make_lock("learning.learn_monitor")
        self._loss: dict[str, dict] = {}        # guarded-by: _lock
        self._last_fire: dict[tuple, float] = {}  # guarded-by: _lock
        # fire listeners (the remediation plane subscribes here):
        # called OUTSIDE the monitor lock, once per emitted event, with
        # (rule, value, baseline, step, tenant). Append-only at wiring
        # time, so iteration is safe without the lock.
        self._listeners: list = []

    def add_listener(self, cb) -> None:
        self._listeners.append(cb)

    def observe(self, vals: dict, loss: float, step: int = 0,
                tenant: str = "") -> None:
        loss = float(loss)
        fires: list[tuple[str, float, float]] = []
        now = time.monotonic()
        with self._lock:
            if loss == loss:  # NaN losses skip the EWMA, not the rules
                s = self._loss.setdefault(tenant, {"ewma": loss, "n": 0})
                baseline = s["ewma"]
                if (s["n"] >= self._min_samples and baseline > 0.0
                        and loss > self.spike_mult * baseline):
                    fires.append(("loss_spike", loss, baseline))
                s["ewma"] = (1 - self._alpha) * baseline \
                    + self._alpha * loss
                s["n"] += 1
            for rule, value, bad in (
                ("q_blowup", vals.get("q_max"),
                 lambda v: abs(v) > Q_MAX_LIMIT),
                ("ess_collapse", vals.get("is_ess_frac"),
                 lambda v: v < ESS_FRAC_MIN),
                ("dead_gradients", vals.get("update_ratio"),
                 lambda v: v < UPDATE_RATIO_MIN),
                ("priority_collapse", vals.get("priority_top_frac"),
                 lambda v: v > TOP_FRAC_MAX),
            ):
                if value is None:
                    continue
                value = float(value)
                if value == value and bad(value):
                    fires.append((rule, value, 0.0))
            fires = [f for f in fires
                     if now - self._last_fire.get(
                         (tenant, f[0]), float("-inf"))
                     >= self._cooldown_s]
            for rule, _, _ in fires:
                self._last_fire[(tenant, rule)] = now
        for rule, value, baseline in fires:
            self._obs.count("learning_degradations")
            self._metrics.log(
                step, learning_degradation=rule,
                learn_tenant=tenant or None,
                learn_value=round(value, 6),
                learn_baseline=round(baseline, 6))
            for cb in self._listeners:
                try:
                    cb(rule, value, baseline, step, tenant)
                except Exception:  # noqa: BLE001 - warn-only plane
                    logging.getLogger(__name__).warning(
                        "learning-degradation listener failed",
                        exc_info=True)
