"""Heartbeats + stall watchdogs: loud, attributed failure over hangs.

Two watchdog shapes live here:

- `HeartbeatRegistry` + `HeartbeatWatchdog`: the single-host driver's
  components (actors, ingest, learner, inference server) stamp
  heartbeats as they make progress; the driver's poll loop calls
  `watchdog.check()` and gets a `StallError` naming WHICH component
  went silent, for HOW long, and what it last reported — instead of a
  run that silently stops producing grad-steps because one thread is
  wedged behind a dead queue. Components that finish legitimately
  (an actor exhausting its frame budget) `clear()` themselves out.

- `StallWatchdog`: the multihost lockstep watchdog (moved here from
  runtime/multihost_driver.py, which re-exports it). A peer process
  dying mid-round leaves every survivor blocked INSIDE a collective —
  no Python-level check can run in that thread, so this one is a
  daemon that emits a diagnostic after `timeout_s` of round silence
  and aborts the process (exit 70) after two consecutive silent
  windows so job-level restart-from-checkpoint actually triggers.
"""

from __future__ import annotations

import sys
import threading
import time


class StallError(RuntimeError):
    """A component stopped heartbeating: attributed stall diagnostic."""

    def __init__(self, component: str, staleness_s: float,
                 last_note: str = "", timeout_s: float = 0.0):
        self.component = component
        self.staleness_s = staleness_s
        self.last_note = last_note
        note = f"; last report: {last_note!r}" if last_note else ""
        super().__init__(
            f"[stall-watchdog] component {component!r} silent for "
            f"{staleness_s:.1f}s (timeout {timeout_s:.1f}s){note} — "
            f"raising instead of hanging; check that component's thread "
            f"or its upstream queue")


class HeartbeatRegistry:
    """Thread-safe component -> (last_beat, note) table.

    `register` seeds the stamp so a component that never beats at all
    (wedged before its first loop iteration) is still attributed;
    `clear` removes a component that finished legitimately."""

    def __init__(self):
        self._lock = threading.Lock()
        self._beats: dict[str, tuple[float, str]] = {}

    def register(self, name: str, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._beats.setdefault(name, (now, "registered"))

    def beat(self, name: str, note: str = "",
             now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._beats[name] = (now, note)

    def clear(self, name: str) -> None:
        with self._lock:
            self._beats.pop(name, None)

    def snapshot(self) -> dict[str, tuple[float, str]]:
        with self._lock:
            return dict(self._beats)

    def stale(self, timeout_s: float, now: float | None = None
              ) -> list[tuple[str, float, str]]:
        """(component, staleness_s, last_note) for every component
        silent past timeout_s, stalest first."""
        now = time.monotonic() if now is None else now
        with self._lock:
            out = [(name, now - t, note)
                   for name, (t, note) in self._beats.items()
                   if now - t >= timeout_s]
        out.sort(key=lambda x: -x[1])
        return out


class HeartbeatWatchdog:
    """Poll-style watchdog over a HeartbeatRegistry: `check()` raises
    StallError for the stalest silent component. Lives in the caller's
    (alive) supervisory loop — the whole point is that the DRIVER
    thread still runs when a worker thread wedges, so the driver can
    convert the hang into an attributed error and tear down."""

    def __init__(self, registry: HeartbeatRegistry, timeout_s: float):
        assert timeout_s > 0
        self.registry = registry
        self.timeout_s = timeout_s

    def check(self, now: float | None = None) -> None:
        stale = self.registry.stale(self.timeout_s, now=now)
        if stale:
            name, staleness, note = stale[0]
            raise StallError(name, staleness, note,
                             timeout_s=self.timeout_s)


class StallWatchdog:
    """Surfaces collective hangs (round-2 verdict weak #8): a peer
    process dying mid-round leaves every survivor blocked inside a
    collective with no error — the documented NCCL-equivalent failure
    domain. This host-local daemon watches a progress stamp the round
    loop bumps; after `timeout_s` of silence it emits a diagnostic
    (which process, how long, what the loop last reported), and after
    TWO consecutive silent windows calls `fatal` (default os._exit) so
    the job-level restart-from-checkpoint recovery actually triggers
    instead of the fleet hanging until a human or scheduler notices.

    Purely host-local: it never issues collectives, so it cannot
    perturb the lockstep call sequence."""

    def __init__(self, timeout_s: float, describe, fatal=None,
                 emit=None):
        """describe() -> str: host-local state for the diagnostic.
        fatal/emit injectable for tests."""
        import os as _os
        self.timeout_s = timeout_s
        self._describe = describe
        self._fatal = fatal or (lambda code: _os._exit(code))
        self._emit = emit or (lambda msg: print(msg, file=sys.stderr,
                                                flush=True))
        self._stamp = time.monotonic()
        self._stop = threading.Event()
        self._fired = 0
        self._thread = threading.Thread(target=self._watch,
                                        name="stall-watchdog",
                                        daemon=True)

    def start(self) -> None:
        if self.timeout_s > 0:
            self._thread.start()

    def stamp(self) -> None:
        self._stamp = time.monotonic()
        self._fired = 0

    def stop(self) -> None:
        self._stop.set()

    def _watch(self) -> None:
        import jax  # deferred: report/offline tools import this module

        poll = min(self.timeout_s / 4, 10.0)
        while not self._stop.wait(poll):
            silent = time.monotonic() - self._stamp
            if silent < self.timeout_s:
                continue
            self._fired += 1
            self._emit(
                f"[stall-watchdog] process {jax.process_index()}: no "
                f"round progress for {silent:.0f}s (timeout "
                f"{self.timeout_s:.0f}s, strike {self._fired}/2) — a "
                f"peer process has likely died inside a collective. "
                f"State: {self._describe()}")
            if self._fired >= 2:
                self._emit(
                    f"[stall-watchdog] process {jax.process_index()}: "
                    f"aborting so the job restarts from the latest "
                    f"checkpoint (the hung collective cannot be "
                    f"recovered in-process)")
                self._fatal(70)
                return
            self._stamp = time.monotonic()  # strike window restarts
