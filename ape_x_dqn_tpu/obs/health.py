"""Heartbeats + stall watchdogs: loud, attributed failure over hangs.

Two watchdog shapes live here:

- `HeartbeatRegistry` + `HeartbeatWatchdog`: the single-host driver's
  components (actors, ingest, learner, inference server) stamp
  heartbeats as they make progress; the driver's poll loop calls
  `watchdog.check()` and gets a `StallError` naming WHICH component
  went silent, for HOW long, and what it last reported — instead of a
  run that silently stops producing grad-steps because one thread is
  wedged behind a dead queue. Components that finish legitimately
  (an actor exhausting its frame budget) `clear()` themselves out.

- `StallWatchdog`: the multihost lockstep watchdog (moved here from
  runtime/multihost_driver.py, which re-exports it). A peer process
  dying mid-round leaves every survivor blocked INSIDE a collective —
  no Python-level check can run in that thread, so this one is a
  daemon that emits a diagnostic after `timeout_s` of round silence
  and aborts the process (exit 70) after two consecutive silent
  windows so job-level restart-from-checkpoint actually triggers.

A third, preventive shape rides along: the **lock-order witness**
(`make_lock` / `WitnessLock` / `LockOrderRecorder`). Every runtime
lock is built through `make_lock("owner.name")`; in production that is
a plain `threading.Lock` with zero overhead, but under
`APEX_LOCK_WITNESS=1` (set by tests/conftest.py) each acquisition is
recorded into a global lock-*order* graph and any edge that closes a
cycle raises `LockOrderError` immediately — the witness idea from the
BSD kernel: a deadlock that would need a precise two-thread interleave
to bite in production becomes a deterministic failure on the first
test run whose code path merely *acquires* in the conflicting order.
"""

from __future__ import annotations

import os
import sys
import threading
import time


class StallError(RuntimeError):
    """A component stopped heartbeating: attributed stall diagnostic."""

    def __init__(self, component: str, staleness_s: float,
                 last_note: str = "", timeout_s: float = 0.0):
        self.component = component
        self.staleness_s = staleness_s
        self.last_note = last_note
        note = f"; last report: {last_note!r}" if last_note else ""
        super().__init__(
            f"[stall-watchdog] component {component!r} silent for "
            f"{staleness_s:.1f}s (timeout {timeout_s:.1f}s){note} — "
            f"raising instead of hanging; check that component's thread "
            f"or its upstream queue")


class LockOrderError(RuntimeError):
    """Two code paths acquire the same locks in conflicting order."""


class LockOrderRecorder:
    """Witness-style lock-order graph with cycle detection.

    Keyed by lock *name* (not instance): every `WitnessLock` acquire
    adds edges held-name -> acquired-name, and an edge that makes the
    directed graph cyclic raises `LockOrderError` with both paths.
    Name-keying means all instances sharing a name collapse to one
    node — same-name edges (a -> a) are ignored rather than treated as
    recursive deadlock, so per-instrument leaf locks can share a name
    without false positives.
    """

    def __init__(self):
        self._mu = threading.Lock()
        # edges and the first acquisition site that created each edge
        self._edges: dict[str, set[str]] = {}
        self._sites: dict[tuple[str, str], str] = {}
        self._tls = threading.local()

    def _held(self) -> list[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _path(self, src: str, dst: str) -> list[str] | None:
        """DFS path src -> dst in the edge graph, or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def note_acquire(self, name: str, site: str = "") -> None:
        """Record held -> name edges; raises LockOrderError if any new
        edge closes a cycle. Called BEFORE blocking on the lock, so
        the conflicting order is reported instead of deadlocking."""
        held = self._held()
        if not held:
            return
        # Fast path: every held -> name edge is already recorded. An
        # edge only enters the graph after passing the cycle check, so
        # seeing it present (GIL-atomic dict reads) means this order
        # was already validated — skip the global mutex entirely.
        edges = self._edges
        if all(prior == name or name in edges.get(prior, ())
               for prior in held):
            return
        with self._mu:
            for prior in held:
                if prior == name or name in self._edges.get(prior, ()):
                    continue
                back = self._path(name, prior)
                if back is not None:
                    fwd = " -> ".join([prior, name])
                    rev = " -> ".join(back)
                    first = self._sites.get((back[0], back[1]), "")
                    where = f" (first seen: {first})" if first else ""
                    raise LockOrderError(
                        f"lock-order cycle: this thread holds "
                        f"{prior!r} and acquires {name!r} ({fwd}), but "
                        f"the recorded order already has {rev}{where} "
                        f"— two such threads interleaved would "
                        f"deadlock")
                self._edges.setdefault(prior, set()).add(name)
                self._sites.setdefault((prior, name), site)

    def push(self, name: str) -> None:
        self._held().append(name)

    def pop(self, name: str) -> None:
        held = self._held()
        # release order may differ from acquire order; drop the most
        # recent occurrence of this name
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._sites.clear()


_RECORDER = LockOrderRecorder()


def lock_witness_recorder() -> LockOrderRecorder:
    """The process-global recorder `make_lock` witnesses feed."""
    return _RECORDER


class WitnessLock:
    """threading.Lock wrapper that reports acquisition order to a
    LockOrderRecorder. Drop-in for plain `with lock:` / acquire /
    release use (no Condition/RLock semantics — the runtime uses
    neither)."""

    def __init__(self, name: str,
                 recorder: LockOrderRecorder | None = None):
        self.name = name
        self._lock = threading.Lock()
        self._recorder = recorder or _RECORDER

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        self._recorder.note_acquire(self.name)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._recorder.push(self.name)
        return ok

    def release(self) -> None:
        self._lock.release()
        self._recorder.pop(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"WitnessLock({self.name!r})"


def make_lock(name: str):
    """Runtime lock factory: a plain threading.Lock in production, a
    WitnessLock feeding the global order recorder when
    APEX_LOCK_WITNESS is set (tests/conftest.py sets it, turning any
    lock-order inversion the suite merely *executes* into a
    deterministic LockOrderError)."""
    if os.environ.get("APEX_LOCK_WITNESS"):
        return WitnessLock(name)
    return threading.Lock()


class HeartbeatRegistry:
    """Thread-safe component -> (last_beat, note) table.

    `register` seeds the stamp so a component that never beats at all
    (wedged before its first loop iteration) is still attributed;
    `clear` removes a component that finished legitimately."""

    def __init__(self):
        self._lock = make_lock("health.heartbeats")
        self._beats: dict[str, tuple[float, str]] = {}  # guarded-by: _lock

    def register(self, name: str, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._beats.setdefault(name, (now, "registered"))

    def beat(self, name: str, note: str = "",
             now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._beats[name] = (now, note)

    def clear(self, name: str) -> None:
        with self._lock:
            self._beats.pop(name, None)

    def snapshot(self) -> dict[str, tuple[float, str]]:
        with self._lock:
            return dict(self._beats)

    def ages(self, now: float | None = None) -> dict[str, tuple[float, str]]:
        """component -> (age_s, last_note). The clock-domain-free view
        a fleet telemetry frame ships: an AGE survives the wire where
        an absolute monotonic stamp from another host would not — the
        receiver re-beats with `now = local_now - age_s` and the stall
        watchdog covers the remote component as if it were local."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return {name: (max(now - t, 0.0), note)
                    for name, (t, note) in self._beats.items()}

    def stale(self, timeout_s: float, now: float | None = None
              ) -> list[tuple[str, float, str]]:
        """(component, staleness_s, last_note) for every component
        silent past timeout_s, stalest first."""
        now = time.monotonic() if now is None else now
        with self._lock:
            out = [(name, now - t, note)
                   for name, (t, note) in self._beats.items()
                   if now - t >= timeout_s]
        out.sort(key=lambda x: -x[1])
        return out


class HeartbeatWatchdog:
    """Poll-style watchdog over a HeartbeatRegistry: `check()` raises
    StallError for the stalest silent component. Lives in the caller's
    (alive) supervisory loop — the whole point is that the DRIVER
    thread still runs when a worker thread wedges, so the driver can
    convert the hang into an attributed error and tear down."""

    def __init__(self, registry: HeartbeatRegistry, timeout_s: float):
        assert timeout_s > 0
        self.registry = registry
        self.timeout_s = timeout_s

    def check(self, now: float | None = None) -> None:
        stale = self.registry.stale(self.timeout_s, now=now)
        if stale:
            name, staleness, note = stale[0]
            raise StallError(name, staleness, note,
                             timeout_s=self.timeout_s)


class StallWatchdog:
    """Surfaces collective hangs (round-2 verdict weak #8): a peer
    process dying mid-round leaves every survivor blocked inside a
    collective with no error — the documented NCCL-equivalent failure
    domain. This host-local daemon watches a progress stamp the round
    loop bumps; after `timeout_s` of silence it emits a diagnostic
    (which process, how long, what the loop last reported), and after
    TWO consecutive silent windows calls `fatal` (default os._exit) so
    the job-level restart-from-checkpoint recovery actually triggers
    instead of the fleet hanging until a human or scheduler notices.

    Purely host-local: it never issues collectives, so it cannot
    perturb the lockstep call sequence."""

    def __init__(self, timeout_s: float, describe, fatal=None,
                 emit=None):
        """describe() -> str: host-local state for the diagnostic.
        fatal/emit injectable for tests."""
        import os as _os
        self.timeout_s = timeout_s
        self._describe = describe
        self._fatal = fatal or (lambda code: _os._exit(code))
        self._emit = emit or (lambda msg: print(msg, file=sys.stderr,
                                                flush=True))
        self._stamp = time.monotonic()
        self._stop = threading.Event()
        self._fired = 0
        self._thread = threading.Thread(target=self._watch,
                                        name="stall-watchdog",
                                        daemon=True)

    def start(self) -> None:
        if self.timeout_s > 0:
            self._thread.start()

    def stamp(self) -> None:
        self._stamp = time.monotonic()
        self._fired = 0

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)

    def _watch(self) -> None:
        import jax  # deferred: report/offline tools import this module

        poll = min(self.timeout_s / 4, 10.0)
        while not self._stop.wait(poll):
            silent = time.monotonic() - self._stamp
            if silent < self.timeout_s:
                continue
            self._fired += 1
            self._emit(
                f"[stall-watchdog] process {jax.process_index()}: no "
                f"round progress for {silent:.0f}s (timeout "
                f"{self.timeout_s:.0f}s, strike {self._fired}/2) — a "
                f"peer process has likely died inside a collective. "
                f"State: {self._describe()}")
            if self._fired >= 2:
                self._emit(
                    f"[stall-watchdog] process {jax.process_index()}: "
                    f"aborting so the job restarts from the latest "
                    f"checkpoint (the hung collective cannot be "
                    f"recovered in-process)")
                self._fatal(70)
                return
            self._stamp = time.monotonic()  # strike window restarts
