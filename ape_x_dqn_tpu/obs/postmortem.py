"""Fleet postmortem bundler — merge the black boxes into one story.

After a drill (or a real incident) the evidence is scattered: each
process left a ``blackbox-<peer>.json`` (obs/blackbox.py), the run's
metrics JSONL carries the learner-side attributed events, and the
learner's FleetAggregator retained the LAST telemetry frame from every
peer — for a process that died without managing a dump, that frame is
its black box of last resort. ``build_bundle`` collects all three,
normalizes everything into timeline entries ``{t, peer, kind,
component?, batch_id?, epoch?, detail}``, and writes one causally
ordered (wall-clock sorted, insertion-stable on ties) bundle that
``report --postmortem`` can walk backwards from the terminal event.

Torn dumps — a kill mid-``os.replace`` window, or a stray ``.tmp`` —
are skipped, counted, and NAMED in ``skipped_dumps`` rather than
aborting the bundle: forensics must degrade gracefully under exactly
the failures it documents.

Stdlib-only on purpose (like obs/report.py): postmortems run on
machines with no jax.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any

# JSONL keys that become attributed timeline entries; value is
# (kind, how-to-name-the-component)
_JSONL_EVENT_KEYS = (
    ("stall_component", "stall", lambda v, rec: str(v)),
    ("peer_disconnect", "peer_disconnect", lambda v, rec: str(v)),
    ("perf_degradation", "perf_degradation", lambda v, rec: str(v)),
    ("learning_degradation", "learning_degradation",
     lambda v, rec: str(v)),
    ("remediation", "remediation",
     lambda v, rec: str(rec.get("remediation_target", v))),
    ("actor_quarantined", "quarantine", lambda v, rec: f"actor-{v}"),
    ("supervisor_restart", "supervisor_restart",
     lambda v, rec: f"actor-{v}"),
    ("peer_stall", "peer_stall", lambda v, rec: str(v)),
    ("blackbox_dump", "dump",
     lambda v, rec: str(rec.get("blackbox_component", ""))),
)

_ATTR_KEYS = ("peer", "component", "batch_id", "epoch", "tenant")


def collect_dumps(blackbox_dir: str) -> tuple[list[dict], list[dict]]:
    """Parse every ``blackbox-*.json`` under ``blackbox_dir``. Returns
    (dumps, skipped) where each skipped entry names the file and why —
    truncation-safe partials must be counted, never fatal."""
    dumps: list[dict] = []
    skipped: list[dict] = []
    pattern = os.path.join(blackbox_dir, "blackbox-*.json")
    for path in sorted(glob.glob(pattern)):
        try:
            with open(path) as fh:
                d = json.load(fh)
            if not isinstance(d, dict) or "peer" not in d:
                skipped.append({"file": os.path.basename(path),
                                "reason": "not a blackbox dump"})
                continue
            d["_file"] = os.path.basename(path)
            dumps.append(d)
        except (json.JSONDecodeError, UnicodeDecodeError):
            skipped.append({"file": os.path.basename(path),
                            "reason": "truncated/unparseable"})
        except OSError as e:
            skipped.append({"file": os.path.basename(path),
                            "reason": f"unreadable: {e.__class__.__name__}"})
    # a stray .tmp is a dump that never completed its os.replace
    for path in sorted(glob.glob(pattern + ".tmp")):
        skipped.append({"file": os.path.basename(path),
                        "reason": "incomplete (tmp left behind)"})
    return dumps, skipped


def tail_jsonl(jsonl_path: str, n: int = 400) -> list[dict]:
    """Last n parseable records of the run JSONL (torn lines skipped,
    same tolerance as report.load_records)."""
    records: list[dict] = []
    try:
        with open(jsonl_path) as fh:
            lines = fh.readlines()
    except OSError:
        return records
    for line in lines[-n:]:
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return records


def _entry(t: float, peer: str, kind: str, component: str = "",
           detail: dict | None = None) -> dict:
    e: dict[str, Any] = {"t": round(float(t), 6), "peer": peer,
                         "kind": kind}
    if component:
        e["component"] = component
    if detail:
        for k in ("batch_id", "epoch", "tenant"):
            if k in detail:
                e[k] = detail[k]
        e["detail"] = detail
    return e


def _timeline_from_dump(dump: dict) -> list[dict]:
    peer = str(dump.get("peer", "?"))
    out = []
    for rec in dump.get("records", []):
        fields = {k: v for k, v in rec.items()
                  if k not in ("t", "kind")}
        rec_peer = str(fields.pop("peer", "")) or peer
        comp = str(fields.pop("component", ""))
        out.append(_entry(rec.get("t", 0.0), rec_peer,
                          str(rec.get("kind", "event")), comp, fields))
    out.append(_entry(dump.get("wall_unix", 0.0), peer, "dump",
                      str(dump.get("component", "")),
                      {"reason": dump.get("reason", ""),
                       "file": dump.get("_file", ""),
                       "dropped": dump.get("dropped", 0)}))
    return out


def _timeline_from_jsonl(records: list[dict]) -> list[dict]:
    out = []
    for rec in records:
        t = rec.get("time")
        if t is None:
            continue
        for key, kind, name in _JSONL_EVENT_KEYS:
            if rec.get(key) is None:
                continue
            peer = str(rec.get("perf_peer") or rec.get("blackbox_peer")
                       or rec.get("peer_disconnect") or "learner")
            detail = {k: v for k, v in rec.items()
                      if k not in ("time",) and v is not None}
            out.append(_entry(t, peer, kind, name(rec[key], rec),
                              detail))
    return out


def _timeline_from_frames(frames: dict) -> list[dict]:
    out = []
    for peer, st in (frames or {}).items():
        frame = st.get("frame") if isinstance(st, dict) else None
        if not isinstance(frame, dict):
            continue
        recv = float(st.get("recv_unix", 0.0))
        out.append(_entry(recv, str(peer), "telemetry_frame", "",
                          {"seq": frame.get("seq", -1),
                           "connected": bool(st.get("connected",
                                                    False))}))
        # correlation events ride the frame with ages relative to its
        # receive time: t ~= recv - age
        for ev in frame.get("events", []) or []:
            try:
                name, dur, age, args = ev
            except (TypeError, ValueError):
                continue
            detail = dict(args) if isinstance(args, dict) else {}
            detail["dur"] = dur
            out.append(_entry(recv - float(age), str(peer), str(name),
                              "", detail))
    return out


def build_bundle(blackbox_dir: str, jsonl_path: str | None = None,
                 frames: dict | None = None,
                 out_path: str | None = None, obs: Any = None,
                 tail_records: int = 400) -> dict:
    """Collect dumps + JSONL tail + retained telemetry frames into one
    causally-ordered timeline bundle; optionally write it atomically.

    ``frames`` is FleetAggregator.retained_frames() when bundling in
    the learner process; offline, the driver's own dump carries the
    same map under ``peer_frames`` and is merged from there.
    """
    dumps, skipped = collect_dumps(blackbox_dir)
    frames = dict(frames or {})
    for d in dumps:
        for peer, st in (d.get("peer_frames") or {}).items():
            frames.setdefault(peer, st)
    tail = tail_jsonl(jsonl_path, tail_records) if jsonl_path else []

    timeline: list[dict] = []
    for d in dumps:
        timeline.extend(_timeline_from_dump(d))
    timeline.extend(_timeline_from_jsonl(tail))
    timeline.extend(_timeline_from_frames(frames))
    timeline.sort(key=lambda e: e["t"])  # stable: ties keep source order

    bundle = {
        "postmortem": 1,
        "created_unix": time.time(),
        "blackbox_dir": os.path.abspath(blackbox_dir),
        "jsonl": os.path.abspath(jsonl_path) if jsonl_path else None,
        "peers": sorted({str(d.get("peer", "?")) for d in dumps}
                        | set(frames)),
        "dumps": dumps,
        "skipped_dumps": skipped,
        "frames": frames,
        "jsonl_tail": tail,
        "timeline": timeline,
    }
    if obs is not None:
        obs.count("postmortem_bundles")
    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(bundle, fh)
        os.replace(tmp, out_path)
        bundle["path"] = os.path.abspath(out_path)
    return bundle
