"""Host-side span tracing in Chrome/Perfetto `trace_event` format.

The runtime's stage structure (actor inference+env step, replay
add/sample, learner SGD, priority write-back, target sync, checkpoint
I/O, inference-server batch assembly) is invisible to `jax.profiler`:
the XLA trace shows device ops, not which HOST loop was waiting on
which dispatch. This tracer records wall-clock spans from the Python
side into the `trace_event` JSON that chrome://tracing and
https://ui.perfetto.dev load directly — one timeline row per thread,
so the actor/ingest/learner overlap (or lack of it) is readable at a
glance.

Design constraints:
- Low overhead: a span costs two `perf_counter` calls and one
  lock-guarded list append; nothing is formatted or written until
  `close()`. A bounded buffer (`max_events`) caps memory on long runs
  — once full, new events are counted as dropped, never resized.
- Fused stages: stages that execute INSIDE one XLA dispatch (the
  priority write-back and target sync live inside the learn jit)
  cannot be timed from the host; `mark()` emits a zero-ish-duration
  event with `args["fused_into"]` naming the enclosing dispatch, so
  the trace still shows *when* they happened and *that* they are
  fused.
- Stage aggregates: every span also folds into a per-name
  (count, total_s, max_s) table so the JSONL stream can carry a
  stage-time breakdown (obs/report.py) without parsing the trace file.

The no-op twin `NullTracer` keeps every call site branch-free when
tracing is off (ObsConfig.trace_path empty / obs disabled).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Iterator


class NullTracer:
    """API-compatible no-op tracer (shared singleton `NULL_TRACER`)."""

    enabled = False

    @contextmanager
    def span(self, name: str, **args: Any) -> Iterator[None]:
        yield

    def mark(self, name: str, **args: Any) -> None:
        pass

    def remote_span(self, name: str, dur_s: float, age_s: float = 0.0,
                    peer: str = "", **args: Any) -> None:
        pass

    def aggregates(self) -> dict[str, dict[str, float]]:
        return {}

    def close(self) -> None:
        pass


NULL_TRACER = NullTracer()


class SpanTracer:
    """Thread-safe span recorder writing one `trace_event` JSON file.

    Events use the 'X' (complete) phase with microsecond ts/dur
    relative to tracer construction; pid/tid map to the OS process and
    Python thread ids, with 'M' metadata events naming each thread so
    Perfetto's track labels read "learner", "actor-3", ... instead of
    raw ids.
    """

    enabled = True

    def __init__(self, path: str, max_events: int = 200_000):
        self._path = path
        self._max = max_events
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._dropped = 0
        self._thread_names: dict[int, str] = {}
        self._peer_tids: dict[str, int] = {}  # synthetic remote tracks
        self._agg: dict[str, list[float]] = {}  # name -> [count, total, max]
        self._t0 = time.perf_counter()
        self._closed = False

    @contextmanager
    def span(self, name: str, **args: Any) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            self._record(name, t0, t1, args)

    def mark(self, name: str, **args: Any) -> None:
        """Instant-ish event for a stage fused inside a device dispatch
        (1us nominal duration so 'X' renderers still draw it)."""
        t = time.perf_counter()
        self._record(name, t, t + 1e-6, args, fused=True)

    def remote_span(self, name: str, dur_s: float, age_s: float = 0.0,
                    peer: str = "", **args: Any) -> None:
        """Record a span REPORTED by a remote peer over the telemetry
        wire. The peer's clock domain does not cross the wire; only the
        event's AGE does — the event lands at local-now minus age_s on
        a synthetic `peer/<id>` track. That keeps cross-process
        correlation honest: ordering within a track and the shared
        correlation args (batch_id) are exact, absolute alignment
        across tracks is age-accurate only."""
        now = time.perf_counter()
        t1 = now - max(float(age_s), 0.0)
        t0 = t1 - max(float(dur_s), 0.0)
        label = f"peer/{peer or '?'}"
        with self._lock:
            tid = self._peer_tids.get(label)
            if tid is None:
                # high base keeps synthetic tids clear of OS thread ids
                tid = self._peer_tids[label] = 1 << 40 | len(self._peer_tids)
                self._thread_names[tid] = label
        self._record(name, t0, t1, dict(args, peer=peer), tid=tid)

    def _record(self, name: str, t0: float, t1: float, args: dict,
                fused: bool = False, tid: int | None = None) -> None:
        local = tid is None
        if local:
            tid = threading.get_ident()
        ev = {"name": name, "cat": "apex", "ph": "X",
              "ts": (t0 - self._t0) * 1e6, "dur": (t1 - t0) * 1e6,
              "pid": os.getpid(), "tid": tid}
        if args:
            ev["args"] = args
        with self._lock:
            if local and tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            a = self._agg.get(name)
            if a is None:
                a = self._agg[name] = [0, 0.0, 0.0]
            a[0] += 1
            if not fused:  # marks carry no host-measurable duration
                a[1] += t1 - t0
                a[2] = max(a[2], t1 - t0)
            if len(self._events) >= self._max:
                self._dropped += 1
                return
            self._events.append(ev)

    def aggregates(self) -> dict[str, dict[str, float]]:
        """Per-span-name stage totals (counts every event, including
        ones dropped from the bounded trace buffer)."""
        with self._lock:
            return {name: {"count": int(c), "total_s": t, "max_s": mx}
                    for name, (c, t, mx) in sorted(self._agg.items())}

    def close(self) -> None:
        """Write the trace file (valid JSON even with zero events)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            events = self._events
            self._events = []
            meta = [{"name": "thread_name", "ph": "M", "pid": os.getpid(),
                     "tid": tid, "args": {"name": tname}}
                    for tid, tname in sorted(self._thread_names.items())]
            dropped = self._dropped
        payload = {"traceEvents": meta + events,
                   "displayTimeUnit": "ms",
                   "otherData": {"dropped_events": dropped}}
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        tmp = self._path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, self._path)


def load_trace(path: str) -> dict:
    """Load a trace file back (tests / report CLI)."""
    with open(path) as fh:
        return json.load(fh)


def span_names(trace: dict) -> set[str]:
    """Distinct span ('X' event) names in a loaded trace."""
    return {ev["name"] for ev in trace.get("traceEvents", ())
            if ev.get("ph") == "X"}
