"""Obs facade: one object the runtime threads spans/metrics/beats into.

Drivers build one `Obs` from `configs.ObsConfig` and hand it to their
components (actors, ingest, learner loop, inference server). Every
call site goes through this facade so the disabled path is a method
call on the `NullObs` singleton — no conditionals in runtime code, and
~zero overhead when observability is off (the acceptance bar: bench
grad-steps/s unchanged with ObsConfig disabled, which trivially holds
because the learner jits are untouched and disabled drivers never call
into numpy or locks here).

First-class Ape-X health instruments (ISSUE 2 / Horgan et al. 2018 §4):
- hist `sample_age_steps`: learner grad-step minus the grad-step at
  which each sampled transition was written (via `SampleAgeTracker`,
  a host-side mirror of the flat ring's skip-to-head write cursor).
- hist `param_lag_steps`: learner grad-step minus the param version
  the inference server served a batch with (actor parameter lag).
- hist `td_abs`: per-dispatch mean |TD| (the priority signal).
- hist `server_batch_items`: dynamic-batching fill.
- gauges `replay_occupancy`, `server_queue_depth`, counters for adds,
  dispatches, stall strikes.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any

import numpy as np

from ape_x_dqn_tpu.obs.blackbox import NULL_BLACKBOX, FlightRecorder
from ape_x_dqn_tpu.obs.health import (
    HeartbeatRegistry, HeartbeatWatchdog, StallError)
from ape_x_dqn_tpu.obs.registry import MetricRegistry, geometric_edges
from ape_x_dqn_tpu.obs.trace import NULL_TRACER, SpanTracer

AGE_EDGES = geometric_edges(1.0, 1e6, per_decade=4)
LAG_EDGES = geometric_edges(1.0, 1e5, per_decade=4)
TD_EDGES = geometric_edges(1e-3, 1e3, per_decade=4)
BATCH_EDGES = tuple(float(2 ** i) for i in range(12))
# inference request latency (enqueue -> result scatter), milliseconds:
# sub-ms when the server keeps up, deadline_ms-ish when batching, and
# unbounded when the queue backs up — the serving-SLO instrument
LATENCY_EDGES = geometric_edges(0.1, 1e4, per_decade=4)
# per-dispatch training loss (learning-health plane, obs/learning.py):
# wide geometric range because a loss spike IS the signal
LOSS_EDGES = geometric_edges(1e-6, 1e3, per_decade=2)


class NullObs:
    """No-op twin: the runtime threads call this when obs is disabled.
    Keep method-for-method parity with Obs."""

    enabled = False
    tracer = NULL_TRACER
    watchdog = None
    profiler = None
    perf = None
    learn = None
    blackbox = NULL_BLACKBOX

    def span(self, name: str, **args: Any):
        return NULL_TRACER.span(name)

    def stage_window(self, stage: str, steps: int = 1):
        return NULL_TRACER.span(stage)

    def stage_attach(self, stage: str, steps: int = 1,
                     compiled: Any = None, compile_fn=None) -> None:
        pass

    def stage_attached(self, stage: str) -> bool:
        # True: disabled obs never wants the (compiling) attach path
        return True

    def perf_rate(self, name: str, value, step: int = 0,
                  peer: str = "") -> None:
        pass

    def learn_health(self, diag, loss, step: int = 0,
                     tenant: str = "") -> None:
        pass

    def mark(self, name: str, **args: Any) -> None:
        pass

    def register(self, name: str) -> None:
        pass

    def beat(self, name: str, note: str = "") -> None:
        pass

    def clear(self, name: str) -> None:
        pass

    def check_stalled(self) -> None:
        pass

    def observe(self, hist: str, value) -> None:
        pass

    def observe_many(self, hist: str, values) -> None:
        pass

    def gauge(self, name: str, value) -> None:
        pass

    def count(self, name: str, n: float = 1.0) -> None:
        pass

    def set_learner_step(self, step: int) -> None:
        pass

    def on_server_batch(self, items: int, params_version: int,
                        queue_depth: int) -> None:
        pass

    def age_tracker(self, capacity: int) -> "SampleAgeTracker | None":
        return None

    def observe_sample_ages(self, ages) -> None:
        pass

    def log_compiled(self, tag: str, compiled) -> None:
        pass

    def maybe_profile(self, step: int) -> None:
        pass

    def publish(self, step: int) -> None:
        pass

    def close(self, step: int = 0) -> None:
        pass


NULL_OBS = NullObs()


class SampleAgeTracker:
    """Host-side mirror of the flat replay ring's write cursor.

    The device ReplayState records no write times; adding them to the
    storage pytree would grow every add/sample graph for a metric. But
    flat ring writes are sequential with skip-to-head wrap
    (replay/packing.ring_write_start), so the host can mirror the
    cursor exactly: `on_add` stamps the written slots with the current
    grad-step, and `ages(idx, step)` maps sampled slot indices back to
    write steps. Valid for the flat layouts (PrioritizedReplay /
    UniformReplayDevice) whose adds all flow through one host loop —
    the single-process driver's case."""

    def __init__(self, capacity: int):
        self._write_step = np.zeros(capacity, np.int64)
        self._pos = 0
        self._cap = capacity

    def on_add(self, n: int, grad_step: int) -> None:
        if n <= 0:
            return
        n = min(n, self._cap)
        # skip-to-head: a block that would cross the ring boundary
        # restarts at slot 0 (must match replay/packing.ring_write_start)
        start = self._pos if self._pos + n <= self._cap else 0
        self._write_step[start:start + n] = grad_step
        self._pos = (start + n) % self._cap

    def ages(self, idx, grad_step: int) -> np.ndarray:
        slots = np.asarray(idx).ravel()
        return grad_step - self._write_step[slots]


class Obs:
    """Live observability session for one driver run."""

    enabled = True

    def __init__(self, cfg, metrics):
        """cfg: configs.ObsConfig (enabled already checked by build_obs);
        metrics: the run's utils.metrics.Metrics sink."""
        self.cfg = cfg
        self.metrics = metrics
        self.tracer = (SpanTracer(cfg.trace_path, cfg.trace_max_events)
                       if cfg.trace_path else NULL_TRACER)
        self.registry = MetricRegistry()
        self.heartbeats = HeartbeatRegistry()
        self.watchdog = (HeartbeatWatchdog(self.heartbeats,
                                           cfg.heartbeat_timeout_s)
                         if cfg.heartbeat_timeout_s > 0 else None)
        # seed the first-class instruments so a short run publishes
        # empty histograms rather than omitting the keys entirely
        self.registry.histogram("sample_age_steps", AGE_EDGES)
        self.registry.histogram("param_lag_steps", LAG_EDGES)
        self.registry.histogram("td_abs", TD_EDGES)
        self.registry.histogram("server_batch_items", BATCH_EDGES)
        self.registry.histogram("infer_latency_ms", LATENCY_EDGES)
        self.registry.histogram("learn_loss", LOSS_EDGES)
        self._learner_step = 0
        # jax.profiler window: False = armed, True = tracing,
        # None = done/disabled (single capture per run)
        self._prof_state: bool | None = (
            False if getattr(cfg, "jax_profile_dir", "") else None)
        self._prof_from = 0
        self._closed = False
        # continuous perf plane (obs/profiling.py, ISSUE 8): roofline
        # gauges + compile telemetry default-on with obs, the EWMA
        # regression engine likewise; each is individually knob-gated.
        # getattr defaults keep configs predating the knobs working.
        from ape_x_dqn_tpu.obs import profiling

        self.profiler = (profiling.StageProfiler(
            self,
            peak_flops=getattr(cfg, "device_peak_flops", 0.0),
            peak_bw=getattr(cfg, "device_peak_bytes_per_s", 0.0))
            if getattr(cfg, "profile_gauges", True) else None)
        self._compile_telemetry = (
            profiling.CompileTelemetry()
            if getattr(cfg, "compile_telemetry", True) else None)
        self.perf = (profiling.PerfMonitor(
            self, metrics,
            frac=getattr(cfg, "perf_frac", 0.5),
            alpha=getattr(cfg, "perf_ewma_alpha", 0.1),
            min_samples=getattr(cfg, "perf_min_samples", 8),
            cooldown_s=getattr(cfg, "perf_cooldown_s", 30.0))
            if getattr(cfg, "perf_regression", True) else None)
        # learning-health plane (obs/learning.py, ISSUE 10): warn-only
        # anomaly engine over the in-graph learner diagnostics
        from ape_x_dqn_tpu.obs import learning

        self.learn = (learning.LearnMonitor(
            self, metrics,
            spike_mult=getattr(cfg, "learn_spike_mult", 10.0),
            alpha=getattr(cfg, "learn_ewma_alpha", 0.2),
            min_samples=getattr(cfg, "learn_min_samples", 8),
            cooldown_s=getattr(cfg, "learn_cooldown_s", 30.0))
            if getattr(cfg, "learn_health", True) else None)
        # forensics plane (obs/blackbox.py, ISSUE 17): per-process
        # flight recorder, dumped on crash/stall/SIGUSR2/supervisor
        # request. Default dump dir rides next to the run JSONL;
        # in-memory-metrics runs (tests, embedded probes) fall back to
        # the system temp dir, never the CWD
        if getattr(cfg, "blackbox", True):
            bb_dir = (getattr(cfg, "blackbox_dir", "")
                      or os.path.dirname(
                          getattr(getattr(metrics, "_fh", None),
                                  "name", "") or "")
                      or tempfile.gettempdir())
            self.blackbox = FlightRecorder(
                self, out_dir=bb_dir,
                capacity=getattr(cfg, "blackbox_capacity", 512),
                log_lines=getattr(cfg, "blackbox_log_lines", 64))
            # attributed degradation events flow into the ring so the
            # box tells the story leading up to the dump
            if self.perf is not None:
                self.perf.add_listener(self._blackbox_perf_event)
            if self.learn is not None:
                self.learn.add_listener(self._blackbox_learn_event)
        else:
            self.blackbox = NULL_BLACKBOX

    def _blackbox_perf_event(self, name, value, baseline, step,
                             peer) -> None:
        self.blackbox.record("perf_degradation", component=name,
                             peer=peer, value=round(float(value), 4),
                             baseline=round(float(baseline), 4),
                             step=int(step))

    def _blackbox_learn_event(self, rule, value, baseline, step,
                              tenant) -> None:
        self.blackbox.record("learning_degradation", component=rule,
                             tenant=tenant, value=round(float(value), 4),
                             baseline=round(float(baseline), 4),
                             step=int(step))

    # -- tracing -----------------------------------------------------------

    def span(self, name: str, **args: Any):
        return self.tracer.span(name, **args)

    def mark(self, name: str, **args: Any) -> None:
        self.tracer.mark(name, **args)

    # -- heartbeats / watchdog ---------------------------------------------

    def register(self, name: str) -> None:
        self.heartbeats.register(name)

    def beat(self, name: str, note: str = "") -> None:
        self.heartbeats.beat(name, note)

    def clear(self, name: str) -> None:
        self.heartbeats.clear(name)

    def check_stalled(self) -> None:
        """Called from the driver's (alive) supervisory loop; raises
        StallError attributing the stalest silent component."""
        if self.watchdog is not None:
            try:
                self.watchdog.check()
            except StallError as e:
                # the stall rides the JSONL stream too, so offline
                # report sees it even when the raise is swallowed
                self.count("stall_errors")
                self.metrics.log(self._learner_step,
                                 stall_component=e.component,
                                 stall_staleness_s=e.staleness_s,
                                 stall_note=e.last_note)
                # archive the box BEFORE closing: the StallError is a
                # terminal event and the ring is its evidence
                self.blackbox.record("stall", component=e.component,
                                     staleness_s=round(e.staleness_s, 1),
                                     note=e.last_note)
                self.blackbox.dump("stall", component=e.component,
                                   step=self._learner_step)
                # flush the trace + final snapshot NOW: the artifacts
                # matter most on the crash path, and not every caller
                # wraps its loop in try/finally
                self.close(self._learner_step)
                raise

    # -- instruments -------------------------------------------------------

    def observe(self, hist: str, value) -> None:
        self.registry.histogram(hist).observe(float(value))

    def observe_many(self, hist: str, values) -> None:
        self.registry.histogram(hist).observe_many(values)

    def gauge(self, name: str, value) -> None:
        self.registry.gauge(name).set(float(value))

    def count(self, name: str, n: float = 1.0) -> None:
        self.registry.counter(name).inc(n)

    # -- staleness hooks ---------------------------------------------------

    def set_learner_step(self, step: int) -> None:
        # plain int attr write: GIL-atomic, read by the server thread
        self._learner_step = int(step)

    def on_server_batch(self, items: int, params_version: int,
                        queue_depth: int) -> None:
        """Inference-server hook, once per served batch: parameter lag
        is how many grad-steps the served params trail the learner."""
        self.observe("server_batch_items", items)
        self.observe("param_lag_steps",
                     max(self._learner_step - int(params_version), 0))
        self.gauge("server_queue_depth", queue_depth)
        self.beat("inference-server", f"batch of {items}")

    def age_tracker(self, capacity: int) -> SampleAgeTracker:
        return SampleAgeTracker(capacity)

    def observe_sample_ages(self, ages) -> None:
        self.observe_many("sample_age_steps", ages)

    # -- continuous perf plane (obs/profiling.py) --------------------------

    def stage_window(self, stage: str, steps: int = 1):
        """Device-time attribution window around a block_until_ready-
        bracketed stage dispatch; publishes the stage's mfu /
        hbm_bw_frac / device_ms gauges on exit. No-op context when the
        roofline gauges are knob-disabled."""
        if self.profiler is None:
            return NULL_TRACER.span(stage)
        return self.profiler.window(stage, steps)

    def stage_attach(self, stage: str, steps: int = 1,
                     compiled: Any = None, compile_fn=None) -> None:
        if self.profiler is not None:
            self.profiler.attach(stage, steps, compiled=compiled,
                                 compile_fn=compile_fn)

    def stage_attached(self, stage: str) -> bool:
        return self.profiler is None or self.profiler.attached(stage)

    def perf_rate(self, name: str, value, step: int = 0,
                  peer: str = "") -> None:
        """Feed one throughput-rate sample to the EWMA regression
        engine (warn-only PerfDegradation events)."""
        if self.perf is not None:
            self.perf.observe(name, value, step=step, peer=peer)

    # -- learning-health plane (obs/learning.py) ---------------------------

    def learn_health(self, diag, loss, step: int = 0,
                     tenant: str = "") -> None:
        """Publish one host-read learner diagnostic snapshot (the
        metrics['diag'] pytree) as `learn_*` gauges + the loss hist,
        and feed the warn-only LearnMonitor. Callers must pass values
        already synced by their existing block_until_ready — this
        method only converts ready device scalars (no new syncs)."""
        from ape_x_dqn_tpu.obs import learning

        vals = {k: float(v) for k, v in dict(diag).items()}
        learning.publish_learn(self, vals, tenant=tenant)
        loss = float(loss)
        self.observe("learn_loss", loss)
        if self.learn is not None:
            self.learn.observe(vals, loss, step=step, tenant=tenant)

    # -- jax integration ---------------------------------------------------

    def log_compiled(self, tag: str, compiled) -> None:
        """Record a compiled jit's XLA memory_analysis into the JSONL
        (reuses utils/hbm.py's budget vocabulary: these are the
        measured anchors the static budget is calibrated against)."""
        if not getattr(self.cfg, "hbm_dump", True):
            return
        from ape_x_dqn_tpu.utils.hbm import compiled_memory_summary

        summary = compiled_memory_summary(compiled)
        if summary:
            self.metrics.log(self._learner_step,
                             **{f"hbm/{tag}/{k}": v
                                for k, v in summary.items()})

    def maybe_profile(self, step: int) -> None:
        """Opt-in jax.profiler window (ObsConfig.jax_profile_dir):
        trace `jax_profile_steps` grad-steps starting at the first
        call — the XLA-level twin of the host-side span trace."""
        if self._prof_state is None:
            return
        import jax

        if self._prof_state is False:
            jax.profiler.start_trace(self.cfg.jax_profile_dir)
            self._prof_from = step
            self._prof_state = True
        elif step - self._prof_from >= self.cfg.jax_profile_steps:
            jax.profiler.stop_trace()
            self._prof_state = None
            self.metrics.log(step, profile_trace=self.cfg.jax_profile_dir)

    # -- publication -------------------------------------------------------

    def publish(self, step: int) -> None:
        """Snapshot every instrument + the span aggregates into one
        JSONL record (`span/<name>` dicts carry the stage-time
        breakdown obs/report.py prints)."""
        self.set_learner_step(step)
        # cheap periodic anchor: every dump's ring shows when the last
        # healthy publish happened, whatever else it recorded
        self.blackbox.record("publish", step=int(step))
        if self._compile_telemetry is not None:
            self._compile_telemetry.publish_into(self)
        agg = self.tracer.aggregates()
        extra = {f"span/{name}": stats for name, stats in agg.items()}
        self.registry.publish(self.metrics, step, extra=extra)

    def close(self, step: int = 0) -> None:
        if self._closed:
            return
        self._closed = True
        if self._prof_state is True:  # run ended inside the window
            import jax

            jax.profiler.stop_trace()
            self._prof_state = None
        self.publish(step)
        self.tracer.close()
        # crash hooks must not outlive the session that owns the ring
        self.blackbox.uninstall()


def build_obs(obs_cfg, metrics) -> Obs | NullObs:
    """NULL_OBS unless the config exists and is enabled — drivers call
    this with `getattr(cfg, "obs", None)` so configs predating ObsConfig
    keep working."""
    if obs_cfg is None or not getattr(obs_cfg, "enabled", False):
        return NULL_OBS
    return Obs(obs_cfg, metrics)
