"""Continuous perf observability: device-time attribution, compile
telemetry, and a perf-regression engine (ISSUE 8, the third obs plane).

PERF.md's roofline study was a one-off manual exercise; this module
turns it into live gauges riding the existing registry/JSONL surface so
`obs/report.py` renders a roofline section from any run with obs on:

- `StageProfiler` — per-jit wall-time windows around the split
  sample_k/learn_k stages (and the fused train dispatch + ingest
  staging). Every observed stage is already `jax.block_until_ready`-
  bracketed by its caller (the honest-timing contract the span tracer
  established in PR 2), so the window's wall time IS dispatch+device
  time. Combined with `compiled.cost_analysis()` FLOP / bytes-accessed
  estimates captured at warmup, each window publishes per-stage `mfu`,
  `hbm_bw_frac` and `device_ms` gauges. NOTE: on this image's backend
  the compiler FLOP count omits most conv FLOPs (~0.9 vs ~47.9
  analytic GFLOP/step — PERF.md round 4), so the live MFU gauge is a
  LOWER bound; bench.py's analytic count stays the headline authority.
- `CompileWatcher` — a process-global jax compile interceptor
  (jax.monitoring's backend_compile duration event) counting compiles,
  compile wall-time, and cumulative executable-cache growth. This
  instruments the known XLA accumulation SIGSEGV that forced
  tests/run_chunked.sh: the crash correlates with per-process compile
  count, which is now a monitored quantity (`compile_cache_entries`
  healthy-range row in obs/report.py).
- `PerfMonitor` — rolling EWMA baselines over grad-steps/s, env-fps
  and ingest rows/s with an attributed `PerfDegradation` obs event
  (warn, never fatal — distinct from StallError: the run keeps going,
  the artifact says it got slower) when a window drops below a
  configurable fraction of its baseline. Evaluated locally, and via
  the PR 6 telemetry frames per-peer on the learner (peer attribution
  rides the event).

Gauges are default-on when obs is enabled (they reuse the sync points
the span tracer already pays for); the extra sampling windows on the
async ingest ship path are default-off (ObsConfig.profile_windows) so
the zero-copy pipeline's overlap — and every jit — stays untouched
unless explicitly asked for. Disabled obs routes through NullObs and
never imports this module's jax hooks at all.
"""

from __future__ import annotations

import json
import logging
import os
import time
from contextlib import contextmanager
from typing import Any, Callable

from ape_x_dqn_tpu.obs.health import make_lock

# -- device peaks ----------------------------------------------------------

# chip peak (bf16 FLOP/s, HBM bytes/s) by device_kind prefix; the MFU
# and hbm_bw_frac denominators. Overridable via ObsConfig so a new chip
# doesn't silently report against the wrong roof.
_PEAKS = (
    ("TPU v5p", 459e12, 2.77e12),
    ("TPU v5 lite", 197e12, 0.82e12),
    ("TPU v5e", 197e12, 0.82e12),
    ("TPU v4", 275e12, 1.23e12),
    ("TPU v3", 123e12, 0.90e12),
    ("TPU v2", 46e12, 0.70e12),
)
# CPU-host fallback, per core: deliberately generous (AVX-class FMA
# throughput) so a smoke run's MFU stays a sane fraction < 1 — the CPU
# number is a development proxy, not a claim about the host
_CPU_PEAK_FLOPS_PER_CORE = 64e9
_CPU_PEAK_BW = 40e9


def device_peaks(device=None) -> tuple[float, float]:
    """(peak FLOP/s, peak HBM bytes/s) for `device` (default: device 0)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "cpu") or "cpu"
    for prefix, flops, bw in _PEAKS:
        if kind.lower().startswith(prefix.lower()):
            return flops, bw
    cores = os.cpu_count() or 1
    return cores * _CPU_PEAK_FLOPS_PER_CORE, _CPU_PEAK_BW


def compiled_cost(compiled) -> tuple[float, float]:
    """(flops, bytes accessed) per dispatch from an AOT-compiled jit's
    XLA cost analysis; (0, 0) when the backend reports none."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0) or 0.0)
        nbytes = float(cost.get("bytes accessed", 0.0) or 0.0)
        return max(flops, 0.0), max(nbytes, 0.0)
    except Exception:  # noqa: BLE001 - strictly best-effort metadata
        return 0.0, 0.0


# -- compile telemetry -----------------------------------------------------


class CompileWatcher:
    """Process-global compile interceptor: one jax.monitoring duration
    listener (there is no unregister in this jax version, so the
    listener is installed once per process and never removed) counting
    backend compiles and their wall time.

    `entries` is the cumulative executable count this process has
    built — the quantity whose unbounded growth in a long-lived CPU
    client precedes the known XLA teardown SIGSEGV (run_chunked.sh's
    raison d'etre). jax.clear_caches() frees the executables but the
    native-side footprint scar remains, so the gauge is deliberately
    monotonic: it tracks compile WORK done, not live cache size."""

    _instance: "CompileWatcher | None" = None

    def __init__(self):
        self._lock = make_lock("profiling.compile_watcher")
        self.compiles = 0  # guarded-by: _lock
        self.compile_s = 0.0  # guarded-by: _lock

    @classmethod
    def install(cls) -> "CompileWatcher":
        if cls._instance is not None:
            return cls._instance
        watcher = cls()
        from jax._src import dispatch, monitoring

        event = dispatch.BACKEND_COMPILE_EVENT

        def _on_duration(name: str, dur: float, **kw: Any) -> None:
            if name != event:
                return
            with watcher._lock:
                watcher.compiles += 1
                watcher.compile_s += float(dur)

        monitoring.register_event_duration_secs_listener(_on_duration)
        cls._instance = watcher
        return watcher

    def snapshot(self) -> tuple[int, float]:
        with self._lock:
            return self.compiles, self.compile_s

    @property
    def entries(self) -> int:
        with self._lock:
            return self.compiles


class CompileTelemetry:
    """Per-Obs view over the process-global watcher: publishes the
    delta since the last publish as counters (so a run's JSONL carries
    only ITS compiles, not a prior run's in the same process) plus the
    cumulative cache-growth gauge."""

    def __init__(self):
        self.watcher = CompileWatcher.install()
        n, s = self.watcher.snapshot()
        self._seen_n = n
        self._seen_s = s

    def publish_into(self, obs) -> None:
        n, s = self.watcher.snapshot()
        dn, ds = n - self._seen_n, s - self._seen_s
        self._seen_n, self._seen_s = n, s
        if dn > 0:
            obs.count("jit_compiles", dn)
            obs.count("jit_compile_ms", ds * 1e3)
        obs.gauge("compile_cache_entries", self.watcher.entries)


def install_compile_log(path: str) -> None:
    """Append one JSON line {argv, jit_compiles, jit_compile_ms} to
    `path` at process exit — the per-file compile-cache growth record
    tests/run_chunked.sh logs (APEX_COMPILE_LOG) to keep the SIGSEGV
    workaround a monitored quantity instead of folklore."""
    import atexit
    import sys

    watcher = CompileWatcher.install()
    base_n, base_s = watcher.snapshot()

    def _flush() -> None:
        n, s = watcher.snapshot()
        try:
            with open(path, "a") as fh:
                fh.write(json.dumps({
                    "argv": sys.argv[1:][:4],
                    "jit_compiles": n - base_n,
                    "jit_compile_ms": round((s - base_s) * 1e3, 1),
                }) + "\n")
        except OSError:
            pass  # a vanished log dir must not break interpreter exit

    atexit.register(_flush)


# -- device-time attribution ----------------------------------------------

# the observed stage vocabulary; every member has literal gauge
# emission sites in _publish_stage below (the obs-names checker
# cross-references string literals only). "train_dist" is the dist
# learner's fused train_many dispatch (parallel/dist_learner.py): the
# same roofline math against the same chip peaks, under its own gauge
# names so a mesh run's per-dp attribution never aliases single-chip
# "train" history (ISSUE 9 multichip lane)
STAGES = ("sample_k", "learn_k", "train", "train_dist", "ingest")


class StageProfiler:
    """Wall-time windows + cost-analysis roofs for the learner's
    device stages. Callers guarantee the window body is
    block_until_ready-bracketed (the existing span-tracer contract),
    so window wall time is honest dispatch+device time."""

    def __init__(self, obs, peak_flops: float = 0.0,
                 peak_bw: float = 0.0, ewma_alpha: float = 0.25):
        self._obs = obs
        self._alpha = ewma_alpha
        self._lock = make_lock("profiling.stages")
        # stage -> {"flops_per_step", "bytes_per_step", "ms"(ewma)}
        self._stages: dict[str, dict[str, float]] = {}  # guarded-by: _lock
        self._peak_flops = peak_flops
        self._peak_bw = peak_bw

    def _peaks(self) -> tuple[float, float]:
        if not self._peak_flops or not self._peak_bw:
            flops, bw = device_peaks()
            self._peak_flops = self._peak_flops or flops
            self._peak_bw = self._peak_bw or bw
        return self._peak_flops, self._peak_bw

    def attached(self, stage: str) -> bool:
        with self._lock:
            return stage in self._stages

    def attach(self, stage: str, steps: int = 1,
               compiled: Any = None,
               compile_fn: Callable[[], Any] | None = None) -> None:
        """Record a stage's per-step FLOP/byte roof from an (AOT)
        compiled executable covering `steps` steps. Idempotent; the
        lazy `compile_fn` form is only invoked on first attach (drivers
        pass `lambda: jit.lower(...).compile()`, which populates the
        jit call cache — no second compile when the real call runs)."""
        with self._lock:
            if stage in self._stages:
                return
        if compiled is None and compile_fn is not None:
            try:
                compiled = compile_fn()
            except Exception:  # noqa: BLE001 - attribution is best-effort
                compiled = None
        flops, nbytes = compiled_cost(compiled) if compiled is not None \
            else (0.0, 0.0)
        steps = max(int(steps), 1)
        with self._lock:
            self._stages.setdefault(stage, {
                "flops_per_step": flops / steps,
                "bytes_per_step": nbytes / steps,
                "ms": 0.0,
            })

    @contextmanager
    def window(self, stage: str, steps: int = 1):
        t0 = time.perf_counter()
        yield
        self.record(stage, time.perf_counter() - t0, steps)

    def record(self, stage: str, wall_s: float, steps: int = 1) -> None:
        if wall_s <= 0.0:
            return
        peak_flops, peak_bw = self._peaks()
        with self._lock:
            st = self._stages.get(stage)
            if st is None:
                st = self._stages[stage] = {
                    "flops_per_step": 0.0, "bytes_per_step": 0.0,
                    "ms": 0.0}
            ms = wall_s * 1e3
            st["ms"] = ms if st["ms"] == 0.0 else (
                (1 - self._alpha) * st["ms"] + self._alpha * ms)
            flops = st["flops_per_step"] * steps
            nbytes = st["bytes_per_step"] * steps
            dev_ms = st["ms"]
        mfu = (flops / wall_s) / peak_flops if flops else 0.0
        bw = (nbytes / wall_s) / peak_bw if nbytes else 0.0
        _publish_stage(self._obs, stage, mfu, bw, dev_ms)


def _publish_stage(obs, stage: str, mfu: float, bw_frac: float,
                   dev_ms: float) -> None:
    """Literal per-stage gauge emissions — spelled out per stage so the
    apexlint obs-names checker (string literals only) cross-references
    every row both ways."""
    if stage == "sample_k":
        obs.gauge("mfu_sample_k", mfu)
        obs.gauge("hbm_bw_frac_sample_k", bw_frac)
        obs.gauge("device_ms_sample_k", dev_ms)
    elif stage == "learn_k":
        obs.gauge("mfu_learn_k", mfu)
        obs.gauge("hbm_bw_frac_learn_k", bw_frac)
        obs.gauge("device_ms_learn_k", dev_ms)
    elif stage == "train":
        obs.gauge("mfu_train", mfu)
        obs.gauge("hbm_bw_frac_train", bw_frac)
        obs.gauge("device_ms_train", dev_ms)
    elif stage == "train_dist":
        obs.gauge("mfu_train_dist", mfu)
        obs.gauge("hbm_bw_frac_train_dist", bw_frac)
        obs.gauge("device_ms_train_dist", dev_ms)
    elif stage == "ingest":
        # staging/ship is a pure-bandwidth stage: no MFU roof
        obs.gauge("hbm_bw_frac_ingest", bw_frac)
        obs.gauge("device_ms_ingest", dev_ms)


def publish_multichip(obs, efficiency: float | None = None,
                      fill_min: float | None = None,
                      fill_max: float | None = None) -> None:
    """Literal gauge emissions for the dp-scaling plane (ISSUE 9):

    - dp_scaling_efficiency: grad-steps/s at dp normalized by dp x the
      dp=1 rate — 1.0 is linear scaling. Published by the multichip
      bench lane (bench.py --multichip), which is the only place the
      dp=1 baseline exists; live driver runs carry the fill gauges.
    - replay_shard_fill_min / _max: bounds of per-shard replay
      occupancy fractions. Lockstep ingest keeps these equal; a gap
      means shards are filling unevenly and the stratified sampler is
      over-sampling (and down-weighting) the starved shards.

    None skips a gauge — callers publish what they actually measured.
    """
    if efficiency is not None:
        obs.gauge("dp_scaling_efficiency", efficiency)
    if fill_min is not None:
        obs.gauge("replay_shard_fill_min", fill_min)
    if fill_max is not None:
        obs.gauge("replay_shard_fill_max", fill_max)


# -- perf-regression engine ------------------------------------------------


class PerfMonitor:
    """Rolling EWMA baselines over throughput rates; a window below
    `frac` of its baseline emits ONE attributed PerfDegradation obs
    event per cooldown — a warning in the artifact, never an exception
    (deliberately distinct from StallError: slow is survivable,
    silent is not)."""

    def __init__(self, obs, metrics, frac: float = 0.5,
                 alpha: float = 0.1, min_samples: int = 8,
                 cooldown_s: float = 30.0):
        self._obs = obs
        self._metrics = metrics
        self.frac = frac
        self._alpha = alpha
        self._min_samples = min_samples
        self._cooldown_s = cooldown_s
        self._lock = make_lock("profiling.perf_monitor")
        # (peer, name) -> {"ewma", "n", "last_fire"}
        self._series: dict[tuple[str, str], dict] = {}  # guarded-by: _lock
        # fire listeners (the remediation plane subscribes here):
        # called OUTSIDE the monitor lock, once per emitted event, with
        # (name, value, baseline, step, peer). Append-only at wiring
        # time, so iteration is safe without the lock.
        self._listeners: list = []

    def add_listener(self, cb) -> None:
        self._listeners.append(cb)

    def observe(self, name: str, value: float, step: int = 0,
                peer: str = "") -> None:
        value = float(value)
        if value != value or value < 0.0:  # NaN / nonsense rate
            return
        now = time.monotonic()
        fire = False
        baseline = 0.0
        with self._lock:
            s = self._series.setdefault((peer, name), {
                "ewma": value, "n": 0, "last_fire": 0.0})
            baseline = s["ewma"]
            degraded = (s["n"] >= self._min_samples
                        and baseline > 0.0
                        and value < self.frac * baseline)
            if degraded and now - s["last_fire"] >= self._cooldown_s:
                s["last_fire"] = now
                fire = True
            # the baseline keeps absorbing the new regime (slowly):
            # a persistent slowdown fires once per cooldown, then
            # becomes the new normal rather than alerting forever
            s["ewma"] = (1 - self._alpha) * baseline + self._alpha * value
            s["n"] += 1
        if not peer:
            self._publish_local(name, baseline if baseline else value)
        if fire:
            self._obs.count("perf_degradations")
            self._metrics.log(
                step, perf_degradation=name,
                perf_peer=peer or None,
                perf_value=round(value, 3),
                perf_baseline=round(baseline, 3),
                perf_frac=self.frac)
            for cb in self._listeners:
                try:
                    cb(name, value, baseline, step, peer)
                except Exception:  # noqa: BLE001 - warn-only plane
                    logging.getLogger(__name__).warning(
                        "perf-degradation listener failed",
                        exc_info=True)

    def _publish_local(self, name: str, ewma: float) -> None:
        # literal emissions per tracked local rate (obs-names contract)
        if name == "grad_steps_per_s":
            self._obs.gauge("ewma_grad_steps_per_s", ewma)
        elif name == "env_fps":
            self._obs.gauge("ewma_env_fps", ewma)
        elif name == "ingest_rows_per_s":
            self._obs.gauge("ewma_ingest_rows_per_s", ewma)
