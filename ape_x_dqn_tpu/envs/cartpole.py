"""CartPole-v1, implemented natively.

Classic cart-pole balancing dynamics (Barto, Sutton & Anderson 1983), with
the standard CartPole-v1 constants and termination bounds so agents and
scores are directly comparable with the reference's config-1 smoke run
(SURVEY.md §2.1 config 1): reward +1 per step, episode cap 500, solved at
average return >= 475.
"""

from __future__ import annotations

import math

import numpy as np

from ape_x_dqn_tpu.envs.base import Env, EnvSpec


class CartPole(Env):
    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    TOTAL_MASS = CART_MASS + POLE_MASS
    HALF_LENGTH = 0.5
    POLE_MASS_LENGTH = POLE_MASS * HALF_LENGTH
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * math.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    spec = EnvSpec(obs_shape=(4,), obs_dtype=np.dtype(np.float32),
                   discrete=True, num_actions=2)

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros(4, np.float32)
        self._steps = 0
        self._ep_return = 0.0

    def seed(self, seed: int) -> None:
        self._rng = np.random.default_rng(seed)

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, size=4).astype(np.float32)
        self._steps = 0
        self._ep_return = 0.0
        return self._state.copy()

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        cos_t, sin_t = math.cos(theta), math.sin(theta)
        temp = (force + self.POLE_MASS_LENGTH * theta_dot**2 * sin_t) \
            / self.TOTAL_MASS
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.HALF_LENGTH
            * (4.0 / 3.0 - self.POLE_MASS * cos_t**2 / self.TOTAL_MASS))
        x_acc = temp - self.POLE_MASS_LENGTH * theta_acc * cos_t \
            / self.TOTAL_MASS
        # Euler integration, semi-implicit order as in the classic task
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._steps += 1

        fell = bool(abs(x) > self.X_LIMIT or abs(theta) > self.THETA_LIMIT)
        truncated = self._steps >= self.MAX_STEPS
        done = fell or truncated
        reward = 1.0
        self._ep_return += reward
        info: dict = {"terminal": fell}
        if done:
            info["episode_return"] = self._ep_return
            info["episode_length"] = self._steps
        return self._state.copy(), reward, done, info


class MaskedCartPole(CartPole):
    """Partially observable CartPole: velocities are hidden.

    obs = [x, theta] only — the classic POMDP variant where a memoryless
    policy cannot infer x_dot/theta_dot, so improving return requires the
    recurrent state. This is the R2D2 runtime's end-to-end correctness
    task (SURVEY.md §2.1 config 4 stand-in for this image, like synthetic
    catch stands in for ALE).
    """

    spec = EnvSpec(obs_shape=(2,), obs_dtype=np.dtype(np.float32),
                   discrete=True, num_actions=2)

    def _mask(self, obs: np.ndarray) -> np.ndarray:
        return obs[[0, 2]]

    def reset(self) -> np.ndarray:
        return self._mask(super().reset())

    def step(self, action):
        obs, reward, done, info = super().step(action)
        return self._mask(obs), reward, done, info
