"""Continuous-control environments for the Ape-X DPG config.

The reference's config 5 targets DM Control humanoid (SURVEY.md §2.1).
`dm_control` IS importable in this image (verified by training on it —
PERF.md "Real-physics DPG"): env ids with an underscore
("pendulum_swingup", "humanoid_stand", any "<domain>_<task>") route to
`DMControlAdapter`, which runs the real MuJoCo physics behind the Env
API. The native `PendulumSwingUp` stand-in (id "pendulum", no
underscore) stays as the dependency-free fast deterministic backend for
unit tests and images without dm_control.
"""

from __future__ import annotations

import numpy as np

from ape_x_dqn_tpu.envs.base import Env, EnvSpec

try:
    from dm_control import suite  # type: ignore
    HAVE_DM_CONTROL = True
except ImportError:
    HAVE_DM_CONTROL = False


class PendulumSwingUp(Env):
    """Torque-limited pendulum swing-up.

    obs = [cos th, sin th, th_dot], action = torque in [-2, 2],
    reward = -(angle^2 + 0.1 th_dot^2 + 0.001 torque^2), horizon 200.
    """

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    spec = EnvSpec(obs_shape=(3,), obs_dtype=np.dtype(np.float32),
                   discrete=False, action_dim=1,
                   action_low=-2.0, action_high=2.0)

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._th = 0.0
        self._th_dot = 0.0
        self._steps = 0
        self._ep_return = 0.0

    def seed(self, seed: int) -> None:
        self._rng = np.random.default_rng(seed)

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._th), np.sin(self._th), self._th_dot],
                        np.float32)

    def reset(self) -> np.ndarray:
        self._th = float(self._rng.uniform(-np.pi, np.pi))
        self._th_dot = float(self._rng.uniform(-1.0, 1.0))
        self._steps = 0
        self._ep_return = 0.0
        return self._obs()

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.MAX_TORQUE, self.MAX_TORQUE))
        th = ((self._th + np.pi) % (2 * np.pi)) - np.pi  # wrap to [-pi, pi]
        cost = th**2 + 0.1 * self._th_dot**2 + 0.001 * u**2
        self._th_dot += (3 * self.G / (2 * self.L) * np.sin(self._th)
                         + 3.0 / (self.M * self.L**2) * u) * self.DT
        self._th_dot = float(np.clip(self._th_dot, -self.MAX_SPEED,
                                     self.MAX_SPEED))
        self._th += self._th_dot * self.DT
        self._steps += 1
        done = self._steps >= self.MAX_STEPS
        reward = -float(cost)
        self._ep_return += reward
        info: dict = {"terminal": False}  # time-limit only; bootstrap through
        if done:
            info["episode_return"] = self._ep_return
            info["episode_length"] = self._steps
        return self._obs(), reward, done, info


class DMControlAdapter(Env):
    """dm_control.suite task behind the Env API (flattened observations)."""

    def __init__(self, domain: str, task: str, seed: int = 0):
        self._env = suite.load(domain, task, task_kwargs={"random": seed})
        a_spec = self._env.action_spec()
        t = self._env.reset()
        dim = sum(int(np.prod(v.shape)) for v in t.observation.values())
        self.spec = EnvSpec(
            obs_shape=(dim,), obs_dtype=np.dtype(np.float32), discrete=False,
            action_dim=int(np.prod(a_spec.shape)),
            action_low=float(a_spec.minimum.min()),
            action_high=float(a_spec.maximum.max()))
        self._ep_return = 0.0

    def _flatten(self, obs_dict) -> np.ndarray:
        return np.concatenate(
            [np.asarray(v, np.float32).ravel() for v in obs_dict.values()])

    def reset(self) -> np.ndarray:
        self._ep_return = 0.0
        return self._flatten(self._env.reset().observation)

    def step(self, action):
        ts = self._env.step(np.asarray(action))
        reward = float(ts.reward or 0.0)
        self._ep_return += reward
        done = ts.last()
        info: dict = {"terminal": done and ts.discount == 0.0}
        if done:
            info["episode_return"] = self._ep_return
        return self._flatten(ts.observation), reward, done, info


def make_control(cfg, seed: int = 0) -> Env:
    if "_" in cfg.id:
        # an underscore id explicitly names a dm_control <domain>_<task>;
        # silently substituting the 3-d synthetic pendulum would train a
        # completely different task under the requested label
        if not HAVE_DM_CONTROL:
            raise ImportError(
                f"env id {cfg.id!r} names a dm_control task but "
                f"dm_control is not importable in this environment; "
                f"use id='pendulum' for the native stand-in")
        domain, task = cfg.id.split("_", 1)
        return DMControlAdapter(domain, task, seed=seed)
    return PendulumSwingUp(seed=seed)
