"""Environment interface.

Host-side (numpy) environment API used by actors and the eval worker.
Reference parity: the reference's env layer wraps ALE / CartPole / DM
Control (SURVEY.md §1 layer 1). This image has none of those packages, so
the framework ships native implementations (CartPole physics, a synthetic
ALE-compatible game, pendulum swing-up) and gates the real backends behind
imports — a user with `ale_py` / `dm_control` installed gets the real
games through the same wrapper stack.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class EnvSpec:
    """Static description of an environment's interfaces."""

    obs_shape: tuple[int, ...]
    obs_dtype: np.dtype
    discrete: bool
    num_actions: int = 0  # discrete only
    action_dim: int = 0  # continuous only
    action_low: float = -1.0
    action_high: float = 1.0


class Env(abc.ABC):
    """Minimal synchronous env: reset() -> obs, step(a) -> (obs, r, done, info).

    `done` is episode termination (true terminal OR time limit); `info` may
    carry `terminal` (bootstrapping-relevant termination, i.e. excluding
    time limits), `lives`, and `episode_return` on episode end.
    """

    spec: EnvSpec

    @abc.abstractmethod
    def reset(self) -> np.ndarray:
        ...

    @abc.abstractmethod
    def step(self, action) -> tuple[np.ndarray, float, bool, dict]:
        ...

    def seed(self, seed: int) -> None:  # pragma: no cover - default noop
        pass


def make_env(cfg, seed: int = 0, actor_index: int = 0) -> Env:
    """Factory from an EnvConfig (ape_x_dqn_tpu.configs.EnvConfig)."""
    from ape_x_dqn_tpu.envs import atari, cartpole, control

    kind = cfg.kind
    if kind == "cartpole":
        return cartpole.CartPole(seed=seed)
    if kind == "cartpole_po":
        return cartpole.MaskedCartPole(seed=seed)
    if kind in ("atari", "synthetic_atari"):
        return atari.make_atari(cfg, seed=seed, actor_index=actor_index)
    if kind == "control":
        return control.make_control(cfg, seed=seed)
    raise ValueError(f"unknown env kind {kind!r}")
