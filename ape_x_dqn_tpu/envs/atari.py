"""Atari environment stack.

Two halves, mirroring the reference's env layer (SURVEY.md §2.2 "Env
wrappers"):

1. A **raw** ALE-like interface (`RawAtariEnv`): 210x160x3 uint8 frames,
   minimal discrete action set, `lives`. Backed by the real Arcade
   Learning Environment when `ale_py` is importable, else by
   `SyntheticAtari` — a native, deterministic catch-style game that
   exercises every preprocessing stage (sprite flicker for max-pooling,
   lives for episodic-life, dense +/-1 rewards for clipping) so the full
   pipeline is testable and benchable in this image, which has no ALE.

2. `AtariPreprocessing`: the canonical DQN pipeline — noop starts,
   frame-skip 4 with max-pool over the last two raw frames, grayscale,
   84x84 bilinear resize, episodic life, reward clipping, frame-stack 4 —
   producing (84, 84, 4) uint8 observations.
"""

from __future__ import annotations

import numpy as np

from ape_x_dqn_tpu.envs import native
from ape_x_dqn_tpu.envs.base import Env, EnvSpec

try:  # real ALE if the user's environment has it
    import ale_py  # type: ignore  # noqa: F401
    HAVE_ALE = True
except ImportError:
    HAVE_ALE = False


# ---------------------------------------------------------------------------
# Raw layer


class RawAtariEnv:
    """ALE-compatible raw interface: 210x160x3 uint8 frames."""

    height = 210
    width = 160
    num_actions: int

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> tuple[np.ndarray, float, bool]:
        raise NotImplementedError

    @property
    def lives(self) -> int:
        raise NotImplementedError

    def seed(self, seed: int) -> None:
        pass


class SyntheticAtari(RawAtariEnv):
    """Native catch-style game with ALE-shaped output.

    A ball falls from the top of a 210x160 screen; a paddle near the
    bottom moves with Pong's minimal action set (NOOP FIRE RIGHT LEFT
    RIGHTFIRE LEFTFIRE). Catch = +1, miss = -1 and loses one of 5 lives.
    The ball sprite is drawn only on even raw frames (ALE-style sprite
    flicker), so skipping without max-pooling loses the ball half the
    time — a behavioral test of the preprocessing stack.
    """

    num_actions = 6
    BALL = 8  # ball edge px
    PADDLE_W = 24
    PADDLE_H = 6
    PADDLE_Y = 190
    BALL_SPEED = 2
    PADDLE_SPEED = 4
    LIVES = 5

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._frame_count = 0
        self._lives = self.LIVES
        self._done = True
        self._ball_x = 0
        self._ball_y = 0
        self._paddle_x = 0

    def seed(self, seed: int) -> None:
        self._rng = np.random.default_rng(seed)

    @property
    def lives(self) -> int:
        return self._lives

    def _spawn_ball(self) -> None:
        self._ball_x = int(self._rng.integers(0, self.width - self.BALL))
        self._ball_y = 0

    def reset(self) -> np.ndarray:
        self._lives = self.LIVES
        self._done = False
        self._frame_count = 0
        self._paddle_x = (self.width - self.PADDLE_W) // 2
        self._spawn_ball()
        return self._render()

    def step(self, action: int):
        if self._done:
            raise RuntimeError("step() on done env; call reset()")
        if not 0 <= action < self.num_actions:
            raise ValueError(f"action {action} outside [0, {self.num_actions})")
        if action in (2, 4):  # RIGHT / RIGHTFIRE
            self._paddle_x += self.PADDLE_SPEED
        elif action in (3, 5):  # LEFT / LEFTFIRE
            self._paddle_x -= self.PADDLE_SPEED
        self._paddle_x = int(
            np.clip(self._paddle_x, 0, self.width - self.PADDLE_W))

        self._ball_y += self.BALL_SPEED
        self._frame_count += 1
        reward = 0.0
        if self._ball_y + self.BALL >= self.PADDLE_Y:
            caught = (self._ball_x + self.BALL > self._paddle_x
                      and self._ball_x < self._paddle_x + self.PADDLE_W)
            if caught:
                reward = 1.0
            else:
                reward = -1.0
                self._lives -= 1
                if self._lives == 0:
                    self._done = True
            self._spawn_ball()
        return self._render(), reward, self._done

    def _render(self) -> np.ndarray:
        frame = np.zeros((self.height, self.width, 3), np.uint8)
        frame[..., 2] = 40  # dark blue background
        # paddle: always drawn
        frame[self.PADDLE_Y:self.PADDLE_Y + self.PADDLE_H,
              self._paddle_x:self._paddle_x + self.PADDLE_W] = (200, 72, 72)
        # ball: flickers (drawn on even frames only)
        if self._frame_count % 2 == 0:
            y, x = self._ball_y, self._ball_x
            frame[y:y + self.BALL, x:x + self.BALL] = (236, 236, 236)
        return frame


class ALERawEnv(RawAtariEnv):
    """Real Arcade Learning Environment behind the raw interface.

    full_action_set: use ALE's 18-action legal set instead of the
    per-game minimal set. Required when one Q-net serves MANY games
    (the atari57 fleet): minimal sets differ per game (4 for breakout,
    18 for alien), so a shared net sized off one game's probe env would
    emit out-of-range action indices on the others; the legal set is
    valid everywhere (redundant actions just alias NOOP/directions)."""

    def __init__(self, game: str, seed: int = 0, repeat_action_prob=0.25,
                 full_action_set: bool = False):
        from ale_py import ALEInterface, roms  # type: ignore
        self._ale = ALEInterface()
        self._ale.setInt("random_seed", seed)
        self._ale.setFloat("repeat_action_probability", repeat_action_prob)
        self._ale.loadROM(roms.get_rom_path(game))
        self._actions = (self._ale.getLegalActionSet() if full_action_set
                         else self._ale.getMinimalActionSet())
        self.num_actions = len(self._actions)

    def reset(self) -> np.ndarray:
        self._ale.reset_game()
        return self._ale.getScreenRGB()

    def step(self, action: int):
        reward = self._ale.act(self._actions[action])
        return (self._ale.getScreenRGB(), float(reward),
                self._ale.game_over())

    @property
    def lives(self) -> int:
        return self._ale.lives()


# ---------------------------------------------------------------------------
# Preprocessing


_RESIZE_CACHE: dict = {}


def bilinear_resize(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Bilinear resize of a (H, W) array with cached index/weight tables."""
    h, w = img.shape
    key = (h, w, out_h, out_w)
    tables = _RESIZE_CACHE.get(key)
    if tables is None:
        # align_corners=False convention (matches cv2.INTER_LINEAR)
        ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
        xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
        y0 = np.clip(np.floor(ys).astype(np.int32), 0, h - 1)
        x0 = np.clip(np.floor(xs).astype(np.int32), 0, w - 1)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = np.clip(ys - y0, 0.0, 1.0).astype(np.float32)
        wx = np.clip(xs - x0, 0.0, 1.0).astype(np.float32)
        tables = (y0, y1, wy[:, None], x0, x1, wx[None, :])
        _RESIZE_CACHE[key] = tables
    y0, y1, wy, x0, x1, wx = tables
    img = img.astype(np.float32)
    r0, r1 = img[y0], img[y1]
    top = r0[:, x0] * (1 - wx) + r0[:, x1] * wx
    bot = r1[:, x0] * (1 - wx) + r1[:, x1] * wx
    return top * (1 - wy) + bot * wy


def grayscale(frame: np.ndarray) -> np.ndarray:
    return (0.299 * frame[..., 0] + 0.587 * frame[..., 1]
            + 0.114 * frame[..., 2])


class AtariPreprocessing(Env):
    """The canonical DQN preprocessing pipeline over a RawAtariEnv."""

    def __init__(self, raw: RawAtariEnv, frame_skip=4, frame_stack=4,
                 resize=84, max_noop_start=30, episodic_life=True,
                 clip_rewards=True, max_episode_frames=108_000, seed=0):
        self._raw = raw
        self._frame_skip = frame_skip
        self._stack = frame_stack
        self._size = resize
        self._max_noop = max_noop_start
        self._episodic_life = episodic_life
        self._clip = clip_rewards
        self._max_frames = max_episode_frames
        self._rng = np.random.default_rng(seed)
        self._frames = np.zeros((resize, resize, frame_stack), np.uint8)
        self._raw_done = True
        self._truncated = False
        self._lives = 0
        self._elapsed = 0
        self._ep_return = 0.0  # unclipped, for eval/HNS
        self.spec = EnvSpec(obs_shape=(resize, resize, frame_stack),
                            obs_dtype=np.dtype(np.uint8), discrete=True,
                            num_actions=raw.num_actions)

    def seed(self, seed: int) -> None:
        self._rng = np.random.default_rng(seed)
        self._raw.seed(seed)

    def _observe(self, f0: np.ndarray,
                 f1: np.ndarray | None = None) -> np.ndarray:
        """max(f0, f1) -> gray -> resize -> stack shift. The fused C++
        kernel (cpp/preproc.cpp via envs/native.py) and the numpy path
        are bit-identical (tested); the native one skips the per-frame
        float intermediates that dominate the actor's env-step cost."""
        small = native.preproc(f0, f1, self._size, self._size)
        if small is None:
            fm = f0 if f1 is None else np.maximum(f0, f1)
            g = grayscale(fm)
            small = np.clip(bilinear_resize(g, self._size, self._size),
                            0, 255).astype(np.uint8)
        self._frames = np.concatenate(
            [self._frames[..., 1:], small[..., None]], axis=-1)
        return self._frames.copy()

    def reset(self) -> np.ndarray:
        if self._raw_done or self._truncated or not self._episodic_life:
            frame = self._full_reset()
        else:
            # episodic-life pseudo-reset: continue the same raw episode
            frame, r, done = self._raw.step(0)
            self._elapsed += 1
            self._ep_return += r  # keep eval/HNS scores exact
            if done:
                # the noop itself ended the raw episode; its return is
                # dropped (matches standard EpisodicLife wrapper behavior)
                frame = self._full_reset()
        self._lives = self._raw.lives
        return self._observe(frame)

    def _full_reset(self) -> np.ndarray:
        frame = self._raw.reset()
        self._raw_done = False
        self._truncated = False
        self._elapsed = 0
        self._ep_return = 0.0
        self._frames[:] = 0
        # noop starts: k ~ Uniform[1, max_noop] raw noop frames
        if self._max_noop > 0:
            for _ in range(int(self._rng.integers(1, self._max_noop + 1))):
                frame, _, done = self._raw.step(0)
                if done:
                    frame = self._raw.reset()
        return frame

    def step(self, action):
        total_reward = 0.0
        raw_done = False
        last2 = [None, None]
        for _ in range(self._frame_skip):
            frame, r, raw_done = self._raw.step(int(action))
            total_reward += r
            last2[0], last2[1] = last2[1], frame
            self._elapsed += 1
            if raw_done:
                break
        self._raw_done = raw_done
        self._ep_return += total_reward

        life_lost = self._raw.lives < self._lives
        self._lives = self._raw.lives
        truncated = self._elapsed >= self._max_frames
        self._truncated = truncated  # forces a full reset next reset()
        done = raw_done or truncated or (self._episodic_life and life_lost)
        terminal = raw_done or (self._episodic_life and life_lost)

        reward = float(np.sign(total_reward)) if self._clip else total_reward
        obs = self._observe(last2[1], last2[0])
        info: dict = {"terminal": terminal, "lives": self._lives,
                      "raw_reward": total_reward}
        if raw_done or truncated:
            info["episode_return"] = self._ep_return
            info["episode_length"] = self._elapsed
        return obs, reward, done, info


def atari_backend(kind: str) -> str:
    """Which raw backend `make_atari` builds for an EnvConfig kind:
    "ale" only when the real Arcade Learning Environment is importable
    AND the config asks for real Atari; otherwise "synthetic" (the
    in-image catch stand-in). Eval results must carry this marker so a
    synthetic score can never masquerade as the north-star median-HNS
    (runtime/evaluation.py)."""
    return "ale" if (HAVE_ALE and kind == "atari") else "synthetic"


def make_atari(cfg, seed: int = 0, actor_index: int = 0) -> Env:
    """Build the full preprocessed Atari env from an EnvConfig.

    id="atari57" is the flagship suite id (SURVEY.md §2.1 config 3):
    the actor fleet spreads round-robin across the 57 games by global
    actor slot — vector actors pass their per-env global slot here, so
    a 256-thread x 16-env fleet covers every game ~72x."""
    game = cfg.id
    multi_game = game == "atari57"
    if multi_game:
        from ape_x_dqn_tpu.utils.metrics import ATARI_HUMAN_RANDOM
        games = sorted(ATARI_HUMAN_RANDOM)
        game = games[actor_index % len(games)]
    if atari_backend(cfg.kind) == "ale":
        # multi-game fleets share one Q-net, so every game exposes the
        # same 18-action legal set (see ALERawEnv.full_action_set);
        # cfg.full_action_set carries the same property into per-game
        # eval envs built from a multi-game config
        raw: RawAtariEnv = ALERawEnv(
            _gym_id_to_ale(game), seed=seed,
            full_action_set=multi_game or getattr(
                cfg, "full_action_set", False))
    else:
        raw = SyntheticAtari(seed=seed * 9973 + actor_index)
    return AtariPreprocessing(
        raw, frame_skip=cfg.frame_skip, frame_stack=cfg.frame_stack,
        resize=cfg.resize, max_noop_start=cfg.max_noop_start,
        episodic_life=cfg.episodic_life, clip_rewards=cfg.clip_rewards,
        max_episode_frames=cfg.max_episode_frames, seed=seed)


def _gym_id_to_ale(env_id: str) -> str:
    """'PongNoFrameskip-v4' -> 'pong' (snake_case ALE rom name)."""
    name = env_id.split("NoFrameskip")[0].split("-v")[0]
    out = [name[0].lower()]
    for ch in name[1:]:
        if ch.isupper():
            out.append("_")
        out.append(ch.lower())
    return "".join(out)
