"""Synchronous vectorized env driver.

Actors run several envs each so one batched forward through the inference
server serves many env steps (SURVEY.md §2.4 "inference batching
parallelism"). Autoresets on done: the observation returned for a done
env is the first observation of its next episode; the pre-reset terminal
flag and episode stats are reported in that step's info.
"""

from __future__ import annotations

import numpy as np

from ape_x_dqn_tpu.envs.base import Env


class SyncVectorEnv:
    def __init__(self, envs: list[Env]):
        assert envs, "need at least one env"
        self.envs = envs
        self.spec = envs[0].spec
        self.num_envs = len(envs)

    def reset(self) -> np.ndarray:
        return np.stack([e.reset() for e in self.envs])

    def step(self, actions):
        obs, rewards, dones, infos = [], [], [], []
        for env, a in zip(self.envs, actions):
            o, r, d, info = env.step(a)
            if d:
                # keep the pre-reset observation: time-limit ends bootstrap
                # from it (terminal=False), so it must survive the autoreset
                info["terminal_obs"] = o
                o = env.reset()
            obs.append(o)
            rewards.append(r)
            dones.append(d)
            infos.append(info)
        return (np.stack(obs), np.asarray(rewards, np.float32),
                np.asarray(dones, bool), infos)
