from ape_x_dqn_tpu.envs.base import Env, EnvSpec, make_env
from ape_x_dqn_tpu.envs.vector import SyncVectorEnv
