"""ctypes bindings for the native Atari observation kernel
(cpp/preproc.cpp).

Compiled lazily via utils/native_build.py; without a toolchain,
preproc() returns None and envs/atari.py falls back to the numpy
pipeline, which is numerically identical (tests/test_envs.py asserts
bit-equality) — just slower, since it materializes per-frame float
intermediates.

Flags: -march=native is safe AND load-bearing (~1.7x; the .so name
carries a per-CPU-model tag so a shared checkout never serves a
wrong-ISA binary); -ffp-contract=off keeps numpy bit-parity — the
kernel mirrors numpy's discrete float operations, and a fused
multiply-add would round differently.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from ape_x_dqn_tpu.utils.native_build import build_and_load, machine_tag

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "cpp", "preproc.cpp")
_SO = os.path.join(os.path.dirname(_SRC),
                   f"libapex_preproc.{machine_tag()}.so")


_lib: ctypes.CDLL | None = None
_tried = False


def _load() -> ctypes.CDLL | None:
    # module-level cache: preproc() runs once per env step in every
    # actor thread, so it must not re-enter build_and_load's global
    # lock or rebind argtypes per frame (benign if two threads race
    # the first call — the work is idempotent)
    global _lib, _tried
    if _tried:
        return _lib
    lib = build_and_load(_SRC, _SO,
                         flags=("-march=native", "-ffp-contract=off"))
    if lib is not None:
        try:
            lib.apex_preproc.restype = None
            lib.apex_preproc.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
        except AttributeError:
            lib = None  # stale .so missing the symbol: numpy fallback
    _lib, _tried = lib, True
    return _lib


def available() -> bool:
    return _load() is not None


def preproc(f0: np.ndarray, f1: np.ndarray | None,
            out_h: int, out_w: int) -> np.ndarray | None:
    """max(f0, f1) -> grayscale -> bilinear (out_h, out_w) -> uint8.

    f0/f1: uint8 [H, W, 3] RGB (f1 None = single frame). Returns None
    when the native library is unavailable (caller falls back to
    numpy).
    """
    lib = _load()
    if lib is None:
        return None
    f0 = np.ascontiguousarray(f0, np.uint8)
    p1 = None
    if f1 is not None:
        f1 = np.ascontiguousarray(f1, np.uint8)
        p1 = f1.ctypes.data_as(ctypes.c_void_p)
    h, w = f0.shape[:2]
    out = np.empty((out_h, out_w), np.uint8)
    lib.apex_preproc(f0.ctypes.data_as(ctypes.c_void_p), p1, h, w,
                     out.ctypes.data_as(ctypes.c_void_p), out_h, out_w)
    return out
