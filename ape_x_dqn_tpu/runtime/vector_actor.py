"""Vectorized actor: one thread drives K envs per batched inference query.

The scalar actor (runtime/actor.py) makes one single-observation RPC per
env step, so its throughput is bounded by RPC round-trips — the round-2
live soak measured the whole driver actor-bound at ~10-15 env-fps
(PERF.md "Live driver vs bench"). The reference keeps ~50k aggregate
env-fps with per-actor GPUs (SURVEY.md §6); the TPU-native answer is the
batched inference server (SURVEY.md §2.3 item 4) — which only pays off
when queries arrive in bulk. This module closes that loop: one actor
thread steps a SyncVectorEnv of K envs and ships ONE K-item query per
vector step (`BatchedInferenceServer.query_batch`), so the server sees
batch-K work from a single thread and the per-step RPC cost amortizes
K ways (SURVEY.md §2.4 "inference batching parallelism", §7 hard part 3).

Per-env bookkeeping (n-step building, initial-priority resolution,
frame-segment assembly) stays host-side numpy per env core — it is cheap
relative to the RPC+forward that the batching removes. The one-step
pending mechanism is the scalar actor's, applied per env: a transition
emitted at step t needs max_a Q(s_{t+n}), which is exactly env j's slice
of the NEXT vector query; truncation flushes batch their terminal
observations into one extra query per vector step.

Each env core owns a distinct slot of the global Horgan eps schedule:
vector actor i's env j is global slot i*K+j of num_actors*K, so a fleet
of vector actors spans the same exploration diversity as num_actors*K
scalar actors.
"""

from __future__ import annotations

import threading
from typing import Callable

import jax
import numpy as np

from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.envs.vector import SyncVectorEnv
from ape_x_dqn_tpu.obs.core import NULL_OBS
from ape_x_dqn_tpu.ops.nstep import NStepBuilder, NStepTransition
from ape_x_dqn_tpu.replay.frame_ring import FrameSegmentBuilder
from ape_x_dqn_tpu.runtime.actor import (
    ContinuousPolicyHooks, DiscretePolicyHooks, actor_epsilon,
    feed_sequence, resolve_pending, sequence_ship_after,
    ship_flat_outbox, ship_sequence_outbox)


class _EnvCore:
    """Per-env actor state: eps slot, n-step window, pending
    initial-priority list, optional frame-segment builder."""

    __slots__ = ("eps", "nstep", "pending", "seg")

    def __init__(self, eps: float, nstep: NStepBuilder,
                 seg: FrameSegmentBuilder | None):
        self.eps = eps
        self.nstep = nstep
        self.pending: list[NStepTransition] = []
        self.seg = seg


def _split(out, k: int) -> list:
    """Slice a batched reply pytree into k per-env pytrees."""
    return [jax.tree.map(lambda x, j=j: x[j], out) for j in range(k)]


class VectorActor(DiscretePolicyHooks):
    """Flat-DQN family vector actor. Same constructor/run contract as
    runtime.actor.Actor, except query_fn is the server's `query_batch`
    (inputs carry a leading [K] batch dim). Policy hooks come from the
    shared DiscretePolicyHooks (ContinuousVectorActor swaps in the
    continuous set)."""

    def __init__(self, cfg: RunConfig, actor_index: int,
                 query_fn: Callable[[np.ndarray, int], np.ndarray],
                 transport, seed: int | None = None,
                 episode_callback: Callable[[int, dict], None] | None = None,
                 obs: object | None = None):
        self.cfg = cfg
        self.index = actor_index
        self.query = query_fn
        self.transport = transport
        self.obs = obs if obs is not None else NULL_OBS
        self._hb = f"actor-{actor_index}"
        seed = cfg.seed if seed is None else seed
        self.K = max(cfg.actors.envs_per_actor, 1)
        total_slots = cfg.actors.num_actors * self.K
        envs = []
        self.cores: list[_EnvCore] = []
        frame_ring = (self._ships_frame_segments
                      and getattr(cfg.replay, "storage", "flat")
                      == "frame_ring")
        for j in range(self.K):
            g = actor_index * self.K + j  # global eps-schedule slot
            envs.append(make_env(cfg.env, seed=seed * 10_007 + g,
                                 actor_index=g))
            seg = None
            if frame_ring:
                spec = envs[-1].spec
                assert spec.discrete and len(spec.obs_shape) == 3, \
                    "frame_ring storage needs discrete [H, W, stack] " \
                    "pixel envs"
                seg = FrameSegmentBuilder(
                    cfg.replay.seg_transitions, cfg.learner.n_step,
                    stack=spec.obs_shape[-1])
            self.cores.append(_EnvCore(
                actor_epsilon(g, total_slots, cfg.actors.base_eps,
                              cfg.actors.eps_alpha),
                NStepBuilder(cfg.learner.n_step, cfg.learner.gamma), seg))
        self.venv = SyncVectorEnv(envs)
        self.spec = self.venv.spec
        self.rng = np.random.default_rng(seed * 7919 + actor_index)
        self.episode_callback = episode_callback
        self.frames = 0
        self._frames_unshipped = 0
        self._outbox: list[tuple[NStepTransition, float]] = []

    _ships_frame_segments = True

    # -- priority resolution / shipping (per-env cores, shared outbox) ----

    def _queue(self, core: _EnvCore, t: NStepTransition,
               priority: float) -> None:
        if core.seg is not None:
            core.seg.add(t.action, t.reward, t.discount, t.span, priority)
        else:
            self._outbox.append((t, priority))

    def _resolve_pending(self, core: _EnvCore, out) -> None:
        if not core.pending:
            return
        resolve_pending(core.pending, self._bootstrap_value(out),
                        lambda t, p: self._queue(core, t, p))

    def _ship(self, force: bool = False) -> None:
        if any(c.seg is not None for c in self.cores):
            for core in self.cores:
                segs = (core.seg.flush() if force
                        else core.seg.take_ready())
                for seg in segs:
                    seg["actor"] = self.index
                    seg["frames"] = self._frames_unshipped
                    self._frames_unshipped = 0
                    self.transport.send_experience(seg)
            return
        if not self._outbox:
            return
        if not force and len(self._outbox) < self.cfg.actors.ingest_batch:
            return
        ship_flat_outbox(self._outbox, self._action_array, self.index,
                         self._frames_unshipped, self.transport)
        self._outbox = []
        self._frames_unshipped = 0

    # -- main loop ---------------------------------------------------------

    def run(self, max_frames: int,
            stop_event: threading.Event | None = None) -> int:
        obs = self.venv.reset()  # [K, ...]
        for j, core in enumerate(self.cores):
            if core.seg is not None:
                core.seg.on_reset(obs[j])
        while self.frames < max_frames and not (
                stop_event is not None and stop_event.is_set()):
            self.obs.beat(self._hb)
            with self.obs.span("actor.inference", k=self.K):
                out = self.query(obs, self.K)
            outs = _split(out, self.K)
            actions = []
            for j, core in enumerate(self.cores):
                self._resolve_pending(core, outs[j])
                actions.append(self._select_action(outs[j], core.eps))
            next_obs, rewards, dones, infos = self.venv.step(actions)
            self.frames += self.K
            self._frames_unshipped += self.K
            # per-env n-step append; the autoreset means env j's true
            # post-step observation is terminal_obs when done
            emitted: list[list[NStepTransition]] = []
            trunc_j: list[int] = []
            for j, core in enumerate(self.cores):
                info = infos[j]
                done = bool(dones[j])
                terminal = bool(info.get("terminal", done))
                truncated = done and not terminal
                step_next = info["terminal_obs"] if done else next_obs[j]
                if core.seg is not None:
                    core.seg.on_step(step_next)
                emitted.append(core.nstep.append(
                    obs[j], actions[j], float(rewards[j]), step_next,
                    terminal, truncated,
                    aux=self._taken_value(outs[j], actions[j])))
                if truncated and any(t.discount != 0.0
                                     for t in emitted[-1]):
                    trunc_j.append(j)
            # truncation flushes bootstrap from their terminal obs: one
            # batched query for all truncated envs this step (rare)
            v_term: dict[int, float] = {}
            if trunc_j:
                tb = np.stack([infos[j]["terminal_obs"] for j in trunc_j])
                touts = _split(self.query(tb, len(trunc_j)), len(trunc_j))
                for i, j in enumerate(trunc_j):
                    v_term[j] = self._bootstrap_value(touts[i])
            for j, core in enumerate(self.cores):
                for t in emitted[j]:
                    if t.discount == 0.0:
                        self._queue(core, t, abs(t.reward - float(t.aux)))
                    elif j in v_term:
                        target = t.reward + t.discount * v_term[j]
                        self._queue(core, t, abs(target - float(t.aux)))
                    else:
                        core.pending.append(t)
                if dones[j]:
                    if core.seg is not None:
                        # flushes the open partial segment: segments
                        # never span episodes (autoreset obs seeds next)
                        core.seg.on_reset(next_obs[j])
                    if (self.episode_callback
                            and "episode_return" in infos[j]):
                        self.episode_callback(self.index, infos[j])
            obs = next_obs
            self._ship()
        # shutdown: resolve parked transitions with one final batched
        # forward (their bootstrap obs is each env's current obs)
        if any(core.pending for core in self.cores):
            try:
                outs = _split(self.query(obs, self.K), self.K)
                for j, core in enumerate(self.cores):
                    self._resolve_pending(core, outs[j])
            except Exception:
                for core in self.cores:
                    core.pending.clear()  # server down: drop, don't die
        self._ship(force=True)
        return self.frames


class _RecurrentEnvCore:
    """Per-env recurrent actor state: eps slot, sequence builder,
    carried LSTM state, and the one-step-parked record awaiting its
    1-step TD bootstrap (mirrors runtime.actor.RecurrentActor)."""

    __slots__ = ("eps", "builder", "c", "h", "prev")

    def __init__(self, eps: float, builder, lstm_size: int):
        self.eps = eps
        self.builder = builder
        self.c = np.zeros(lstm_size, np.float32)
        self.h = np.zeros(lstm_size, np.float32)
        self.prev: dict | None = None

    def zero_state(self) -> None:
        self.c = np.zeros_like(self.c)
        self.h = np.zeros_like(self.h)


class RecurrentVectorActor:
    """R2D2 vector actor: K envs per thread, one batched stateful
    query per vector step ({obs, c, h} each with a leading [K] axis),
    per-env SequenceBuilders shipping stored-state sequences.

    Semantics mirror runtime.actor.RecurrentActor exactly per env
    core — the 1-step pending record, terminal/truncation TD seeds,
    zeroed state on episode end — with the truncation bootstrap
    queries of all truncated envs batched into one extra query per
    vector step (same trick as VectorActor)."""

    def __init__(self, cfg: RunConfig, actor_index: int,
                 query_fn, transport, seed: int | None = None,
                 episode_callback=None, obs: object | None = None):
        from ape_x_dqn_tpu.replay.sequence import SequenceBuilder

        self.cfg = cfg
        self.index = actor_index
        self.query = query_fn
        self.transport = transport
        self.obs = obs if obs is not None else NULL_OBS
        self._hb = f"actor-{actor_index}"
        seed = cfg.seed if seed is None else seed
        self.K = max(cfg.actors.envs_per_actor, 1)
        self.gamma = cfg.learner.gamma
        self.lstm_size = cfg.network.lstm_size
        total_slots = cfg.actors.num_actors * self.K
        frame_mode = cfg.replay.storage == "frame_ring"
        envs, self.cores = [], []
        for j in range(self.K):
            g = actor_index * self.K + j
            envs.append(make_env(cfg.env, seed=seed * 10_007 + g,
                                 actor_index=g))
            if frame_mode:
                assert len(envs[-1].spec.obs_shape) == 3, \
                    "frame_ring sequence storage needs [H, W, stack] " \
                    "pixel obs"
            self.cores.append(_RecurrentEnvCore(
                actor_epsilon(g, total_slots, cfg.actors.base_eps,
                              cfg.actors.eps_alpha),
                SequenceBuilder(
                    seq_len=cfg.replay.seq_length,
                    overlap=cfg.replay.seq_overlap,
                    lstm_size=self.lstm_size,
                    priority_eta=cfg.replay.priority_eta,
                    frame_mode=frame_mode),
                self.lstm_size))
        self.venv = SyncVectorEnv(envs)
        self.spec = self.venv.spec
        self.rng = np.random.default_rng(seed * 7919 + actor_index)
        self.episode_callback = episode_callback
        self.frames = 0
        self._frames_unshipped = 0
        self.ship_after = sequence_ship_after(cfg)
        self._outbox: list[dict] = []

    def _feed(self, core: _RecurrentEnvCore, rec: dict, td: float) -> None:
        feed_sequence(self._outbox, core.builder, rec, td)

    def _resolve_prev(self, core: _RecurrentEnvCore, q_next) -> None:
        """The parked record's 1-step TD bootstrap arrives with the
        next query's Q-values for this env."""
        if core.prev is None:
            return
        td = (core.prev["reward"] + self.gamma * float(np.max(q_next))
              - core.prev["q_sa"])
        self._feed(core, core.prev, td)
        core.prev = None

    def _ship(self, force: bool = False) -> None:
        if not self._outbox:
            return
        if not force and len(self._outbox) < self.ship_after:
            return
        ship_sequence_outbox(self._outbox, self.index,
                             self._frames_unshipped, self.transport)
        self._outbox = []
        self._frames_unshipped = 0

    def run(self, max_frames: int,
            stop_event: threading.Event | None = None) -> int:
        obs = self.venv.reset()
        while self.frames < max_frames and not (
                stop_event is not None and stop_event.is_set()):
            self.obs.beat(self._hb)
            with self.obs.span("actor.inference", k=self.K):
                out = self.query({
                    "obs": obs,
                    "c": np.stack([c.c for c in self.cores]),
                    "h": np.stack([c.h for c in self.cores])}, self.K)
            q, cs, hs = (np.asarray(out["q"]), np.asarray(out["c"]),
                         np.asarray(out["h"]))
            actions = []
            for j, core in enumerate(self.cores):
                self._resolve_prev(core, q[j])
                if self.rng.random() < core.eps:
                    actions.append(int(self.rng.integers(
                        self.spec.num_actions)))
                else:
                    actions.append(int(np.argmax(q[j])))
            next_obs, rewards, dones, infos = self.venv.step(actions)
            self.frames += self.K
            self._frames_unshipped += self.K
            # first pass: build records, collect truncation bootstraps
            recs, trunc_j = [], []
            for j, core in enumerate(self.cores):
                info = infos[j]
                done = bool(dones[j])
                terminal = bool(info.get("terminal", done))
                recs.append(dict(
                    obs=obs[j], action=actions[j],
                    reward=float(rewards[j]), terminal=terminal,
                    pre_state=(core.c, core.h),
                    q_sa=float(q[j][actions[j]]), episode_end=done))
                if done and not terminal:
                    trunc_j.append(j)
            # truncation: the sequence ends (state resets) but the
            # bootstrap survives — one batched query on the terminated
            # envs' final observations with their POST-step states
            v_term: dict[int, float] = {}
            if trunc_j:
                tout = self.query({
                    "obs": np.stack([infos[j]["terminal_obs"]
                                     for j in trunc_j]),
                    "c": np.stack([cs[j] for j in trunc_j]),
                    "h": np.stack([hs[j] for j in trunc_j])},
                    len(trunc_j))
                tq = np.asarray(tout["q"])
                for i, j in enumerate(trunc_j):
                    v_term[j] = float(np.max(tq[i]))
            # second pass: route records, advance/reset LSTM state
            for j, core in enumerate(self.cores):
                rec = recs[j]
                if rec["terminal"]:
                    # bootstrap is zero: TD fully determined now
                    self._feed(core, rec, rec["reward"] - rec["q_sa"])
                elif j in v_term:
                    td = (rec["reward"] + self.gamma * v_term[j]
                          - rec["q_sa"])
                    self._feed(core, rec, td)
                else:
                    core.prev = rec
                if dones[j]:
                    core.zero_state()
                    if (self.episode_callback
                            and "episode_return" in infos[j]):
                        self.episode_callback(self.index, infos[j])
                else:
                    core.c, core.h = cs[j], hs[j]
            obs = next_obs
            self._ship()
        # shutdown: resolve parked records with one final batched
        # forward, flush partial sequence tails, ship everything
        if any(core.prev is not None for core in self.cores):
            try:
                out = self.query({
                    "obs": obs,
                    "c": np.stack([c.c for c in self.cores]),
                    "h": np.stack([c.h for c in self.cores])}, self.K)
                q = np.asarray(out["q"])
                for j, core in enumerate(self.cores):
                    if core.prev is not None:
                        core.prev["episode_end"] = False
                        self._resolve_prev(core, q[j])
            except Exception:  # server down: seed without bootstrap
                for core in self.cores:
                    if core.prev is not None:
                        core.prev["episode_end"] = False
                        self._feed(core, core.prev,
                                   core.prev["reward"]
                                   - core.prev["q_sa"])
                        core.prev = None
        for core in self.cores:
            self._outbox.extend(core.builder.flush())
        self._ship(force=True)
        return self.frames


class ContinuousVectorActor(ContinuousPolicyHooks, VectorActor):
    """Ape-X DPG vector actor: the shared deterministic-policy hooks
    (runtime.actor.ContinuousPolicyHooks) over the vector loop."""

    _ships_frame_segments = False  # DPG obs are low-dimensional

    def __init__(self, cfg: RunConfig, actor_index: int,
                 query_fn, transport, seed: int | None = None,
                 episode_callback=None, obs: object | None = None):
        super().__init__(cfg, actor_index, query_fn, transport, seed=seed,
                         episode_callback=episode_callback, obs=obs)
        self._init_noise(cfg)
