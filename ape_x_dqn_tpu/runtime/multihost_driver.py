"""Multi-host Ape-X: one learner process per host, SPMD lockstep.

The reference's multi-host learner is NCCL/MPI process groups running
synchronized training steps while each host ingests its own actors'
experience (SURVEY.md §5 "distributed communication backend"). The
TPU-native shape of that design:

- Every process builds the SAME global (dp, tp) mesh (parallel/mesh.py
  over jax.devices(), which spans hosts under jax.distributed) and the
  same DistDQNLearner; GSPMD inserts the cross-host collectives.
- Each host runs its OWN actors + batched inference server + transport;
  experience lands only in the dp replay rows that host owns
  (parallel/multihost.process_rows) — experience never crosses hosts,
  exactly like the reference's per-learner replay locality.
- The learner loop is a synchronous ROUND protocol instead of the
  single-host driver's free-running threads: jitted programs on global
  arrays are collectives, so every process must issue the identical
  call sequence. Each round:

      1. all processes agree (the packed global_stats reduction)
         whether every host has a full ingest block staged; if so, all
         call `add` together — gating beats padding, because dead
         filler items would cycle the replay ring and evict real
         experience on idle hosts;
      2. the replay fill check, train_many dispatch, publication
         boundary, and termination all branch on GLOBAL values (jit
         outputs or the global_stats reduction), never on host-local
         state.

  A host whose actors all die stalls global ingest (training continues
  on existing data); a host whose PROCESS dies hangs the collectives —
  the same failure domain as the reference's NCCL group, recovered by
  restarting the job from a checkpoint.

Run via the CLI:
    python -m ape_x_dqn_tpu.runtime.train --config pong \
        --coordinator HOST:PORT --num-processes 2 --process-id 0 ...
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ape_x_dqn_tpu.comm.transport import LoopbackTransport
from ape_x_dqn_tpu.configs import RunConfig
# StallWatchdog moved to the observability layer (obs/health.py) so the
# single-host heartbeat watchdog and this lockstep watchdog live
# together; re-exported here because tests and operational docs import
# it from this module.
from ape_x_dqn_tpu.obs.health import StallWatchdog, make_lock  # noqa: F401
from ape_x_dqn_tpu.obs.core import build_obs
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.models import build_network
from ape_x_dqn_tpu.parallel.dist_learner import (
    DistDQNLearner, DistSequenceLearner)
from ape_x_dqn_tpu.parallel.inference_server import (
    BatchedInferenceServer, build_serving_tier)
from ape_x_dqn_tpu.parallel.mesh import make_mesh
from ape_x_dqn_tpu.parallel import multihost
from ape_x_dqn_tpu.runtime.driver import build_prioritized_replay
from ape_x_dqn_tpu.runtime.evaluation import (
    EvalWorker, make_eval_policy_factory)
from ape_x_dqn_tpu.runtime.family import (
    actor_class, family_of, family_setup, server_apply_fn,
    warmup_example)
from ape_x_dqn_tpu.utils.checkpoint import CheckpointManager
from ape_x_dqn_tpu.utils.hbm import check_hbm_fits
from ape_x_dqn_tpu.utils.metrics import Metrics, log_run_header
from ape_x_dqn_tpu.utils.misc import next_pow2
from ape_x_dqn_tpu.utils.rng import component_key




class MultihostApexDriver:
    """Synchronous-round Ape-X driver; one instance per learner process.

    Supports the flat-DQN family (both storage layouts) and the
    recurrent R2D2 family (stored-state sequence replay, both item
    layouts). The continuous DPG family runs multi-host today by
    putting its ACTORS on remote hosts (runtime/actor_host.py) against
    a single-process learner — its nets are small enough that a
    sharded learner buys nothing (see ApexDriver's matching gate).
    """

    def __init__(self, cfg: RunConfig, metrics: Metrics | None = None,
                 transport=None):
        if cfg.checkpoint_replay:
            # loud, not a silent no-op: the multihost payload gather is
            # a replicated-host collective, and replicating every dp
            # shard's replay to every host would multiply the payload
            # by dp x capacity — needs a sharded save path first
            raise NotImplementedError(
                "checkpoint_replay is single-host only for now "
                "(ApexDriver); the multihost driver checkpoints "
                "params/opt/rng/step/frames and refills replay on "
                "resume — set checkpoint_replay=False here")
        # a 1-process fleet is valid ONLY under an initialized
        # jax.distributed runtime (the CLI's --coordinator path; the
        # driver artifact certifies the round protocol that way) —
        # plain single-process training belongs in ApexDriver.
        # jax.distributed.is_initialized is the public signal (jax
        # >= 0.4.34); the private global_state probe is only a
        # fallback for older jax, and falling back is logged so a
        # silent False can't mask valid --coordinator runs after a
        # jax upgrade moves the private symbol (round-4 advisor)
        try:
            dist_on = bool(jax.distributed.is_initialized())
        except AttributeError:
            import logging
            logging.getLogger(__name__).warning(
                "jax.distributed.is_initialized unavailable on this "
                "jax version — probing the private global_state API")
            try:
                from jax._src import distributed as _dist
                dist_on = _dist.global_state.client is not None
            except Exception:  # noqa: BLE001 - internal-API probe only
                dist_on = False
        assert jax.process_count() > 1 or dist_on, \
            "MultihostApexDriver requires jax.distributed (use ApexDriver " \
            "for single-process runs)"
        self.cfg = cfg
        self.family = family_of(cfg)
        if self.family == "dpg":
            raise NotImplementedError(
                "the multihost lockstep loop covers the DQN and R2D2 "
                "families; DPG nets are small — run the learner "
                "single-process with remote actor hosts "
                "(runtime/actor_host.py)")
        self.metrics = metrics or Metrics()
        # observability facade (obs/): spans around the collective
        # round stages + per-publish instrument snapshots; NULL_OBS
        # unless cfg.obs.enabled. The round-progress StallWatchdog
        # below is collective-aware and stays the stall authority here.
        self.obs = build_obs(getattr(cfg, "obs", None), self.metrics)
        probe_env = make_env(cfg.env, seed=cfg.seed)
        self.spec = probe_env.spec
        self.net = build_network(cfg.network, self.spec)
        obs0 = probe_env.reset()

        self.mesh = make_mesh(dp=cfg.parallel.dp, tp=cfg.parallel.tp)
        self.row_start, self.row_stop = multihost.process_rows(self.mesh)
        self.dp = cfg.parallel.dp
        self.dp_local = self.row_stop - self.row_start

        # family_setup (runtime/family.py) owns params init + replay
        # item layout + staging geometry, shared with ApexDriver
        setup = family_setup(cfg, self.spec, self.net, obs0)
        params, item_spec = setup.params, setup.item_spec
        self._frame_mode = setup.frame_mode
        self._chunk = setup.stage_chunk
        self._item_keys = tuple(item_spec.keys())
        self._item_spec = item_spec
        if cfg.replay.kind not in ("prioritized", "sequence"):
            # ValueError, not assert: user-config validation must
            # survive `python -O` (neighboring checkpoint_dir check
            # raises too) — an invalid kind would otherwise surface as
            # an opaque failure inside the dist learner
            raise ValueError(
                "the multihost learner requires prioritized replay "
                "(the per-shard sum-trees ARE the sharded state; "
                "kind='sequence' for R2D2); got "
                f"replay.kind={cfg.replay.kind!r}")

        # early, loud HBM fits-check (utils/hbm.py): the per-shard
        # replay + replicated model state must fit each chip before any
        # device allocation happens
        check_hbm_fits(
            cfg, self.spec.obs_shape, self.spec.obs_dtype,
            param_count=sum(int(np.prod(l.shape))
                            for l in jax.tree.leaves(params)))

        # identical construction on every process (same cfg.seed) ->
        # identical initial params; learner.init then shards them over
        # the global mesh (a collective: all processes reach this line)
        shard_cap = next_pow2(max(cfg.replay.capacity // self.dp, 2))
        self.replay = build_prioritized_replay(cfg, self.spec, shard_cap,
                                               self._frame_mode)
        self.capacity = shard_cap * self.dp
        if self.family == "r2d2":
            self.learner = DistSequenceLearner(
                lambda p, o, s: self.net.apply(p, o, s),
                self.replay, cfg.learner, cfg.replay, self.mesh)
        else:
            self.learner = DistDQNLearner(self.net.apply, self.replay,
                                          cfg.learner, self.mesh)
        self.state = self.learner.init(
            params, item_spec, component_key(cfg.seed, "learner"))

        # publication is a global collective (tp all-gather + cross-host
        # replication); the inference server's jit runs process-LOCALLY,
        # so it gets a host copy — a global array would not mix with the
        # server's local inputs. With shard_over_mesh the server spreads
        # query batches over THIS process's devices (a process-local
        # mesh: only addressable devices, so its jit stays collective-
        # free and cannot perturb the global lockstep).
        local = jax.local_devices()
        self._inference_mesh = (
            make_mesh(dp=len(local), tp=1, devices=local)
            if cfg.inference.shard_over_mesh and len(local) > 1 else None)
        server_params = self._host_params()
        # the serving tier stays process-local for the same reason the
        # inference mesh does: admission/dispatch never cross hosts, so
        # multi-tenancy cannot perturb the global lockstep
        self.serving = None
        if cfg.serving.multi_tenant:
            self.serving = build_serving_tier(
                cfg.serving,
                max_batch=cfg.inference.max_batch,
                deadline_ms=cfg.inference.deadline_ms,
                mesh=self._inference_mesh, obs=self.obs)
            self.server = self.serving.register_policy(
                cfg.env.id, server_apply_fn(self.family, self.net),
                server_params, family=self.family,
                priority=cfg.serving.default_class)
        else:
            self.server = BatchedInferenceServer(
                server_apply_fn(self.family, self.net), server_params,
                max_batch=cfg.inference.max_batch,
                deadline_ms=cfg.inference.deadline_ms,
                mesh=self._inference_mesh, obs=self.obs)
        self.transport = transport if transport is not None \
            else LoopbackTransport()
        # fleet telemetry (obs/fleet.py): merge remote actor hosts'
        # snapshot frames into this process's JSONL — purely host-local
        # (no collectives), so it cannot perturb the lockstep rounds
        self.fleet = None
        if self.obs.enabled:
            from ape_x_dqn_tpu.obs.fleet import FleetAggregator

            agg = FleetAggregator(self.obs)
            if agg.install(self.transport):
                self.fleet = agg
        self.transport.publish_params(server_params, 0)

        self.stop_event = threading.Event()
        self.episode_returns: deque[float] = deque(maxlen=200)  # guarded-by: _lock
        self._frames_local = 0  # guarded-by: _lock
        # frame counters survive resume: _frames_base restores from the
        # checkpoint so a --total-env-frames budget CONTINUES after a
        # preemption instead of re-running in full (round-2 advisor
        # finding); _frames_global_latest mirrors the last packed
        # collective's total (identical on every process) for the
        # checkpoint payload
        self._frames_base = 0
        self._frames_global_latest = 0
        self._grad_steps = 0
        self._gather_jit = None
        self._restored_step: int | None = None
        # checkpoint/resume (SURVEY.md §5): the gather to host is a
        # collective every process joins, and every process calls the
        # (internally synchronized) orbax manager; the bytes land once
        # via the primary process, so checkpoint_dir should be a SHARED
        # filesystem for restore to reach every process (a host whose
        # dir is empty makes the fleet agree on "no restore" rather
        # than hang — see _maybe_restore)
        # all-or-none agreement BEFORE the orbax manager exists: its
        # CONSTRUCTOR already runs multiprocess collectives, so a fleet
        # where only some processes got --checkpoint-dir would issue
        # mismatched collective programs (orbax allgather on some
        # hosts, this min on others) and die in a Gloo timeout with an
        # inscrutable error; every process can see the disagreement
        # here and error loudly instead
        has = 1 if cfg.checkpoint_dir else 0
        mn = multihost.global_min_scalar(self.mesh, has)
        mx = -multihost.global_min_scalar(self.mesh, -has)
        if mn != mx:
            raise ValueError(
                "checkpoint_dir must be set on EVERY process or none "
                f"(this process: {'set' if has else 'unset'}) — "
                "checkpoint save/restore are collectives")
        self.ckpt = (CheckpointManager(cfg.checkpoint_dir)
                     if cfg.checkpoint_dir else None)
        if self.ckpt is not None:
            self._maybe_restore()
        self._stage: list[dict] = []
        self._stage_n = 0
        self._actor_threads: list[threading.Thread] = []
        self._saw_remote = False  # first remote actor-host connection
        self._lock = make_lock("multihost_driver._lock")
        self.actor_errors: list[tuple[int, Exception]] = []  # guarded-by: _lock
        self.last_eval: dict | None = None  # guarded-by: _lock
        self._eval_error: Exception | None = None  # guarded-by: _lock

    # -- checkpoint/resume -------------------------------------------------

    def _ckpt_payload(self) -> dict:
        """COLLECTIVE: TrainState minus replay, gathered to fully
        replicated host numpy — every process must call this at the
        same point; the result is identical everywhere. PRNG keys ride
        as raw key data (numpy can't hold typed keys)."""
        if self._gather_jit is None:
            repl = NamedSharding(self.mesh, P())
            self._gather_jit = jax.jit(
                lambda p, t, o, r, s: (p, t, o, jax.random.key_data(r),
                                       s),
                out_shardings=repl)
        s = self.state
        p, t, o, r, step = self._gather_jit(
            s.params, s.target_params, s.opt_state, s.rng, s.step)
        out = jax.tree.map(np.asarray, {
            "params": p, "target_params": t, "opt_state": o,
            "rng": r, "step": step})
        # host scalar, identical everywhere (it is the last packed
        # collective's output): lets a frame-budget run resume its
        # budget instead of restarting it
        out["frames_global"] = np.asarray(self._frames_global_latest,
                                          np.int64)
        return out

    def _save_checkpoint(self, wait: bool = False) -> None:
        # EVERY process calls save: orbax's multiprocess manager
        # synchronizes internally (barriers inside save/close), so a
        # process-0-only call would deadlock the others; the payload is
        # replicated host numpy, which orbax writes once from the
        # primary process
        with self.obs.span("ckpt.save", step=self._grad_steps):
            payload = self._ckpt_payload()  # collective: all processes
            self.ckpt.save(self._grad_steps, payload, wait=wait)

    def _restore_leaf(self, x, ref):
        """Host numpy -> global array with ref's sharding (the callback
        hands each process the slices it owns; every process holds the
        identical full host copy).

        Only a NamedSharding on the global mesh is trusted: scalar jit
        outputs (optimizer counters, step) can surface with a
        SingleDeviceSharding, which names a DIFFERENT device on each
        process — rebuilding with it would give every host its own
        incompatible copy and the next collective jit rejects the
        state. Those leaves restore replicated on the mesh instead."""
        x = np.asarray(x)
        sharding = (ref.sharding
                    if isinstance(ref.sharding, NamedSharding)
                    else NamedSharding(self.mesh, P()))
        if jnp.issubdtype(ref.dtype, jax.dtypes.prng_key):
            data = jax.make_array_from_callback(
                x.shape, NamedSharding(self.mesh, P("dp")),
                lambda idx: x[idx])
            return jax.jit(jax.random.wrap_key_data,
                           out_shardings=sharding)(data)
        return jax.make_array_from_callback(x.shape, sharding,
                                            lambda idx: x[idx])

    def _maybe_restore(self) -> None:
        """Restore the newest checkpoint step EVERY process can read.
        The min-agreement makes a missing/stale directory on one host
        degrade to a fresh start (or an older common step) instead of
        deadlocking the collectives."""
        local = self.ckpt.latest_step()
        agreed = multihost.global_min_scalar(
            self.mesh, -1 if local is None else int(local))
        if agreed < 0:
            return
        # template restore (the fresh state's own payload): a raw
        # restore would hand back plain dicts/lists where the live
        # opt_state is an optax NamedTuple chain, and the re-shard
        # tree.map would see mismatched structures
        raw = self.ckpt.restore(agreed, template=self._ckpt_payload())
        put = {
            k: jax.tree.map(self._restore_leaf, v,
                            getattr(self.state, k))
            for k, v in raw.items()
            if k not in ("step", "frames_global")}
        step = jax.make_array_from_callback(
            (), NamedSharding(self.mesh, P()),
            lambda idx: np.asarray(raw["step"], np.int32))
        self.state = self.state._replace(step=step, **put)
        self._grad_steps = int(raw["step"])
        self._frames_base = int(raw.get("frames_global", 0))
        self._frames_global_latest = self._frames_base
        self._restored_step = agreed
        # republish: the inference server and transport were seeded
        # with the FRESH init params at construction; without this,
        # resumed actors refill the empty replay with a random policy
        # until the first publish_every boundary (the single-host
        # _maybe_restore ends with _publish_params for the same reason)
        pub = self._host_params()
        self.server.update_params(pub, self._grad_steps)
        self.transport.publish_params(pub, self._grad_steps)

    def _host_params(self):
        """publish_params (collective, all processes call) -> host numpy
        (valid per-process because the result is fully replicated). In
        sharded-inference mode the copy lands back on the local mesh
        (replicated) so the server does not re-upload params from host
        memory on every batch dispatch."""
        pub = self.learner.publish_params(self.state)
        host = jax.tree.map(np.asarray, pub)
        if self._inference_mesh is not None:
            host = jax.device_put(
                host, NamedSharding(self._inference_mesh, P()))
        return host

    # -- local actor plumbing (per host) ----------------------------------

    def _on_episode(self, actor_index: int, info: dict) -> None:
        with self._lock:
            self.episode_returns.append(float(info["episode_return"]))

    def _actor_thread(self, i: int, max_frames: int) -> None:
        try:
            # distinct global actor identities per host: without the
            # process offset every host's actor i would share seeds,
            # eps_i, and (lockstep-identical) params — N hosts producing
            # byte-identical trajectories has the data diversity of one.
            # The eps_i schedule spans the num_actors * nproc fleet, the
            # same convention as actor_host.py --actor-offset.
            n_local = self.cfg.actors.num_actors
            acfg = dataclasses.replace(
                self.cfg, actors=dataclasses.replace(
                    self.cfg.actors,
                    num_actors=n_local * jax.process_count()))
            # vector actors (envs_per_actor > 1) compute their per-env
            # eps slots from acfg's global num_actors, so the schedule
            # spans the whole nproc * num_actors * K fleet
            vector = self.cfg.actors.envs_per_actor > 1
            query = (self.server.query_batch if vector
                     else self.server.query)
            actor = actor_class(self.family, vector=vector)(
                acfg, jax.process_index() * n_local + i,
                query, self.transport,
                episode_callback=self._on_episode, obs=self.obs)
            actor.run(max_frames, self.stop_event)
        except Exception as e:  # noqa: BLE001 - reported in run() output
            with self._lock:
                self.actor_errors.append((i, e))

    def _make_eval_worker(self, game: str | None = None) -> EvalWorker:
        factory = make_eval_policy_factory(
            self.family, self.cfg.network.lstm_size, self.server.query)
        return EvalWorker(self.cfg, self.server.query, game=game,
                          policy_factory=factory)

    def _eval_loop(self) -> None:
        """Greedy eval on PROCESS 0 only, between publish boundaries
        (SURVEY.md §2.2 'Eval worker'; round-2 verdict missing #3: the
        flagship topology could not measure its north-star metric
        during training). Collective-free by construction: the worker
        builds its own host-local env and queries the process-local
        inference server jit, so it can run concurrently with the
        lockstep round loop without perturbing any process's collective
        call sequence — the other processes neither know nor care."""
        try:
            from ape_x_dqn_tpu.runtime.evaluation import (
                RollingSuiteScore, eval_game_rotation, run_eval_measured)
            every = self.cfg.eval_every_steps
            rotate, games = eval_game_rotation(self.cfg)
            worker = None if rotate else self._make_eval_worker()
            rolling = RollingSuiteScore(self.cfg) if rotate else None
            next_at = every
            eval_i = 0
            while not self.stop_event.wait(0.2):
                if self._grad_steps < next_at:
                    continue
                game = None
                if rotate:
                    game = games[eval_i % len(games)]
                    worker = self._make_eval_worker(game=game)
                    eval_i += 1
                t_eval = time.monotonic()
                try:
                    res, depth_max = run_eval_measured(
                        worker, self.cfg.eval_episodes, self.server,
                        stop_event=self.stop_event,
                        max_frames=self.cfg.eval_max_frames)
                except TimeoutError as e:
                    # transient server stall: skip this rotation slot,
                    # keep the eval thread alive (same guard as
                    # ApexDriver._eval_loop — the round-5 live rotation
                    # died 14 games in on one stalled query)
                    self.metrics.log(self._grad_steps,
                                     eval_game=game or self.cfg.env.id,
                                     eval_error=repr(e))
                    next_at = (self._grad_steps // every + 1) * every
                    continue
                if res is None:  # cancelled mid-eval at shutdown
                    break
                with self._lock:
                    self.last_eval = res
                # max queue depth DURING the eval = the back-pressure it
                # induced (round-3 advisor: post-eval snapshots read ~0);
                # rolling suite table per round-3 weak #7
                roll = (rolling.update(game, res["mean_return"])
                        if rolling is not None and game else {})
                self.metrics.log(self._grad_steps,
                                 avg_eval_return=res["mean_return"],
                                 eval_episodes=res["episodes"],
                                 eval_game=game or self.cfg.env.id,
                                 eval_wall_s=time.monotonic() - t_eval,
                                 server_queue_depth_max=depth_max,
                                 **roll)
                next_at = (self._grad_steps // every + 1) * every
        except Exception as e:  # noqa: BLE001 - surfaced in run() output
            with self._lock:
                self._eval_error = e

    def _pump_ingest(self) -> None:
        """Drain the transport into the local stage (runs each round —
        no separate ingest thread: the round loop owns the state).

        While producers are live the stage is capped at a few ingest
        blocks: the round loop consumes at most one block per round, so
        an uncapped pump would absorb everything actors produce during
        train_many (unbounded host memory) and defeat the transport's
        drop-oldest backpressure, which is where overflow is designed
        to land. Once every producer is gone the cap lifts — leftover
        queue contents are finite, and local_idle requires pending==0,
        so a capped pump would leave this host unable to ever read
        idle (fleet-wide livelock via the all_idle gate)."""
        conns = getattr(self.transport, "active_connections", 0)
        if conns > 0 or getattr(self.transport, "ever_connected", False):
            # ever_connected catches a producer that connected and
            # vanished entirely between this loop's observations
            self._saw_remote = True
        producers_live = (
            any(t.is_alive() for t in self._actor_threads) or conns > 0)
        cap = 4 * self.dp_local * self._chunk if producers_live \
            else float("inf")
        while self._stage_n < cap:
            batch = self.transport.recv_experience(timeout=0.0)
            if batch is None:
                return
            n = int(batch["priorities"].shape[0])
            with self._lock:
                self._frames_local += int(batch.get("frames", n))
            self._stage.append(batch)
            self._stage_n += n

    def _pop_block(self) -> dict | None:
        """Take one [dp_local, chunk, ...] block off the stage."""
        need = self.dp_local * self._chunk
        if self._stage_n < need:
            return None
        fields = {
            k: np.concatenate([np.asarray(b[k]) for b in self._stage])
            for k in self._item_keys + ("priorities",)}
        take = {k: v[:need].reshape(self.dp_local, self._chunk,
                                    *v.shape[1:])
                for k, v in fields.items()}
        rest = {k: v[need:] for k, v in fields.items()}
        self._stage = [rest] if rest["priorities"].shape[0] else []
        self._stage_n -= need
        return take

    def _min_fill(self) -> int:
        return min(self.cfg.replay.min_fill, self.capacity // 2)

    def _warmup(self, chunk_steps: int) -> None:
        """AOT-compile the hot jits before actors start (same rationale
        as ApexDriver._warmup: the first add/train_many dispatch
        otherwise compiles for 20-40s inside the single-threaded round
        loop, during which nothing pumps the bounded transport queue
        and drop-oldest discards the early experience stream on every
        host). Abstract ShapeDtypeStructs with the real shardings stand
        in for the global ingest arrays — no cross-host data movement,
        and every process lowers the identical program at the same
        construction point."""
        cls = type(self.learner)
        sharding = NamedSharding(self.mesh, P("dp"))
        ptail = (self.cfg.replay.seg_transitions,) if self._frame_mode \
            else ()
        items = jax.tree.map(
            lambda t: jax.ShapeDtypeStruct(
                (self.dp, self._chunk) + t.shape, t.dtype,
                sharding=sharding),
            self._item_spec)
        pris = jax.ShapeDtypeStruct((self.dp, self._chunk) + ptail,
                                    np.float32, sharding=sharding)
        cls.add.lower(self.learner, self.state, items, pris).compile()
        cls.train_many.lower(self.learner, self.state,
                             chunk_steps).compile()
        if chunk_steps > 1:
            # the tail of a publish window dispatches single steps
            cls.train_many.lower(self.learner, self.state, 1).compile()

    # -- the lockstep round loop ------------------------------------------

    def run(self, total_env_frames: int | None = None,
            max_grad_steps: int = 10**9) -> dict:
        """Round loop. Termination derives from global frame/step counts
        only (wall clocks differ across hosts and would diverge the
        call sequences)."""
        cfg = self.cfg
        total = total_env_frames or cfg.total_env_frames
        per_actor = (total // max(jax.process_count(), 1)
                     // max(cfg.actors.num_actors, 1))
        publish_every = cfg.learner.publish_every
        chunk_steps = max(min(cfg.learner.train_chunk, publish_every), 1)

        threads = [threading.Thread(target=self._actor_thread,
                                    args=(i, per_actor),
                                    name=f"actor-{i}", daemon=True)
                   for i in range(cfg.actors.num_actors)]
        self._actor_threads = threads  # _pump_ingest's cap-lift check
        # self-describing JSONL: sampling semantics + storage layout
        # ride the stream itself (utils/metrics.log_run_header)
        log_run_header(self.metrics, cfg, self._grad_steps)
        try:
            self._warmup(chunk_steps)
        except (AttributeError, NotImplementedError) as e:
            # AOT lowering genuinely unavailable: first dispatches
            # compile lazily. Anything else is a real bug that must
            # surface, not a degraded start (mirrors ApexDriver.run).
            self.metrics.log(0, warmup_skipped=repr(e))
        try:
            self.server.warmup(
                warmup_example(self.family, cfg, self.spec),
                extra_sizes=(cfg.actors.envs_per_actor,))
        except (AttributeError, NotImplementedError) as e:
            # same degradation as the learner warmup above and the
            # actor_host path: no AOT lowering -> lazy first-query
            # compiles (anything else must surface)
            self.metrics.log(0, server_warmup_skipped=repr(e))
        evaluator = None
        if (jax.process_index() == 0 and cfg.eval_every_steps > 0
                and cfg.eval_episodes > 0):
            evaluator = threading.Thread(target=self._eval_loop,
                                         name="eval", daemon=True)
            evaluator.start()
        for t in threads:
            t.start()

        t0 = time.monotonic()
        filled = 0
        frames_global = float(self._frames_base)
        loss = float("nan")
        last_ckpt = self._grad_steps
        watchdog = StallWatchdog(
            cfg.multihost_watchdog_s,
            describe=lambda: (
                f"grad_steps={self._grad_steps} filled={filled} "
                f"frames_local={self._frames_local} "
                f"stage_n={self._stage_n}"))
        watchdog.start()
        global_size = jax.jit(
            lambda s: s.replay.size.sum(),
            out_shardings=jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec()))
        try:
            while True:
                self._pump_ingest()
                progressed = False
                # 0. ONE packed collective for this round's global control
                # values (three separate reductions would pay three
                # sequential DCN barrier round-trips per round).
                # `local_idle`: this host can never produce another ingest
                # block — actors finished/dead, no live remote actor-host
                # connections, transport drained. Deliberately independent
                # of the stage: a host stranded with a full block that OTHER
                # hosts can never match must still read as idle, or an
                # asymmetric drain spins every process forever.
                blocks_ready = 1.0 if self._stage_n >= \
                    self.dp_local * self._chunk else 0.0
                # boot grace: a host with NO local actors whose listening
                # transport has never seen a remote actor-host must not
                # read idle — at startup active_connections == 0 only
                # because producers are still booting, and an idle verdict
                # would terminate the fleet on round 1 with 0 grad steps.
                # Bounded (actors.remote_boot_grace_s): an actor-host job
                # that never launches must not pin the whole fleet in the
                # round loop forever. The deadline is host-local wall
                # clock, which is safe — it only changes this host's
                # REPORTED flag, not the collective call sequence.
                booting = (cfg.actors.num_actors == 0
                           and hasattr(self.transport, "active_connections")
                           and not self._saw_remote
                           and time.monotonic() - t0
                           < cfg.actors.remote_boot_grace_s)
                # quiesced() (socket transport) debounces transient
                # remote disconnects with a grace window; transports
                # without it (loopback) fall back to the connection
                # count, which for them never flickers
                remote_quiet = (
                    self.transport.quiesced()
                    if hasattr(self.transport, "quiesced")
                    else getattr(self.transport,
                                 "active_connections", 0) == 0)
                local_idle = 1.0 if (
                    not booting
                    and not any(t.is_alive() for t in threads)
                    and remote_quiet
                    and self.transport.pending == 0) else 0.0
                with self._lock:
                    frames_local = self._frames_local
                all_ready, all_idle, frames_global = multihost.global_stats(
                    self.mesh, blocks_ready, local_idle, float(frames_local))
                # resumed runs continue their frame budget from the
                # checkpointed global count (per-round counts restart
                # at 0 after a restore)
                frames_global += self._frames_base
                self._frames_global_latest = int(frames_global)
                # the packed collective returned: every peer is alive
                # and in lockstep as of this round
                watchdog.stamp()
                # 1. collective ingest, gated on EVERY host having a block
                if all_ready:
                    with self.obs.span("replay.add"):
                        block = self._pop_block()
                        items = multihost.make_global(
                            self.mesh,
                            {k: v for k, v in block.items()
                             if k != "priorities"})
                        pris = multihost.make_global(self.mesh,
                                                     block["priorities"])
                        self.state = self.learner.add(self.state, items,
                                                      pris)
                        filled = int(global_size(self.state))
                    progressed = True
                # 2. lockstep training, branch on global values only.
                # steps_per_frame_cap paces the learner to the GLOBAL
                # ingested frame count (frames_global comes from the
                # packed collective, so every process skips the same
                # rounds — the pacing itself is lockstep-safe)
                cap = cfg.learner.steps_per_frame_cap
                cap_bound = (cap is not None
                             and self._grad_steps >= cap * frames_global)
                if filled >= self._min_fill() and not cap_bound \
                        and self._grad_steps < max_grad_steps:
                    # whole chunks only; publication fires on boundary
                    # crossings (see ApexDriver._learner_loop_inner:
                    # snapping to exact publish multiples degrades
                    # dispatches to single steps). k is global-derived,
                    # so every process picks the same k — lockstep-safe.
                    done = self._grad_steps
                    k = chunk_steps if chunk_steps <= \
                        max_grad_steps - done else 1
                    # roofline attribution: AOT lower/compile of the
                    # exact train_many signature captures cost_analysis
                    # roofs and pre-populates the jit cache (lockstep-
                    # safe — compilation is deterministic across hosts)
                    if not self.obs.stage_attached("train"):
                        self.obs.stage_attach(
                            "train", k,
                            compile_fn=lambda: type(self.learner)
                            .train_many.lower(self.learner, self.state,
                                              k).compile())
                    with self.obs.stage_window("train", k):
                        with self.obs.span("learner.train", k=k):
                            self.state, m = self.learner.train_many(
                                self.state, k)
                            loss = float(m["loss"])  # blocks: honest timing
                    self._grad_steps += k
                    self.obs.set_learner_step(self._grad_steps)
                    self.obs.mark("replay.sample",
                                  fused_into="learner.train")
                    self.obs.mark("replay.priority_update",
                                  fused_into="learner.train")
                    progressed = True
                    if done // publish_every != \
                            self._grad_steps // publish_every:
                        with self.obs.span("learner.publish_params"):
                            pub = self._host_params()
                            self.server.update_params(pub,
                                                      self._grad_steps)
                            self.transport.publish_params(
                                pub, self._grad_steps)
                        with self._lock:
                            returns = list(self.episode_returns)
                        self.metrics.log(
                            self._grad_steps, loss=loss, replay_filled=filled,
                            frames_global=int(frames_global),
                            frames_local=frames_local,
                            avg_return=(float(np.mean(returns))
                                        if returns else None))
                        self.obs.gauge("replay_occupancy", filled)
                        self.obs.publish(self._grad_steps)
                # checkpoint on a grad-step cadence: _grad_steps is a
                # global value, so every process enters the collective
                # payload gather on the same round
                if (self.ckpt is not None
                        and self._grad_steps - last_ckpt
                        >= cfg.checkpoint_every):
                    self._save_checkpoint()
                    last_ckpt = self._grad_steps
                    watchdog.stamp()  # gathers can take minutes: the
                    # silence window restarts after a completed save
                # 3. global termination — all conditions derive from the
                # round-start packed collective, so every process breaks on
                # the same round. Guards against frame counts that never
                # reach `total` (lossy-transport drops, per-actor truncation
                # of the budget).
                if self._grad_steps >= max_grad_steps:
                    break
                if frames_global >= total and max_grad_steps >= 10**9:
                    break  # frame-budget run: actors are done
                if all_idle and not all_ready and (max_grad_steps >= 10**9
                                                   or filled
                                                   < self._min_fill()
                                                   or cap_bound):
                    # no host can ever produce experience again and the
                    # ingest gate cannot fire (stranded partial blocks can
                    # never complete); either there is no finite step target
                    # to chase, training can never start, or the frame-
                    # pacing cap binds forever (frames_global is final) —
                    # spinning helps nobody
                    break
                if not progressed:
                    # idle round: don't hammer the coordination service
                    # (sleep is host-local pacing, no collective is skipped)
                    time.sleep(0.05)
        except BaseException:
            # crash path: HOST-LOCAL teardown only. The clean-exit
            # sequence below runs collectives (final checkpoint gather,
            # orbax's synchronized close) that would hang on peers that
            # diverged or died with us; signal local actors/server and
            # let the exception surface (threads are daemon — process
            # exit is not blocked).
            watchdog.stop()
            self.stop_event.set()
            self.server.stop()
            self.obs.close(self._grad_steps)
            raise

        # final checkpoint BEFORE joining actors: the break is lockstep
        # (same round on every process), so the collective gather here
        # is aligned; actor joins are host-local and may take unequal
        # time. The watchdog stays armed through these final
        # collectives (a peer dying here hangs them too) and stops
        # only once no collective remains.
        watchdog.stamp()
        if self.ckpt is not None and self._grad_steps > last_ckpt:
            self._save_checkpoint(wait=True)
        if self.ckpt is not None:
            self.ckpt.close()
        watchdog.stop()
        self.stop_event.set()
        for t in threads:
            t.join(timeout=5)
        if evaluator is not None:
            evaluator.join(timeout=10)
        # short runs can finish inside one eval poll interval, and
        # eval_every_steps=0 disables the periodic thread entirely:
        # guarantee at least one greedy evaluation on process 0 while
        # the local inference server is still up (mirrors ApexDriver)
        if (jax.process_index() == 0 and cfg.eval_episodes > 0
                and self.last_eval is None and self._grad_steps > 0
                and self._eval_error is None):
            try:
                from ape_x_dqn_tpu.runtime.evaluation import (
                    final_eval_game)
                game = final_eval_game(cfg)
                res = self._make_eval_worker(game=game).run(
                    cfg.eval_episodes,
                    max_frames=cfg.eval_max_frames,
                    deadline_s=cfg.final_eval_deadline_s)
                if res is not None:
                    # the periodic eval thread's join above is
                    # timeout-bounded: it can still be mid-write when
                    # this teardown eval lands
                    with self._lock:
                        self.last_eval = res
                    self.metrics.log(
                        self._grad_steps,
                        avg_eval_return=res["mean_return"],
                        eval_episodes=res["episodes"],
                        eval_game=game or cfg.env.id)
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    self._eval_error = e
        self.server.stop()
        self.obs.close(self._grad_steps)
        with self._lock:
            avg_ret = (float(np.mean(self.episode_returns))
                       if self.episode_returns else 0.0)
        return {
            "process": jax.process_index(),
            "frames": int(frames_global),
            "frames_local": self._frames_local,
            "grad_steps": self._grad_steps,
            "loss": loss,
            "replay_filled": filled,
            "avg_return": avg_ret,
            "wall_s": time.monotonic() - t0,
            "restored_step": self._restored_step,
            # grad-step of the last weight publication (0 = never):
            # lets callers (and dryrun_multichip's round-protocol
            # certification) assert the publish path actually fired
            "params_version": self.server.params_version,
            "actor_errors": [f"{i}: {e!r}" for i, e in self.actor_errors],
            "eval": self.last_eval,
            "eval_error": (repr(self._eval_error)
                           if self._eval_error else None),
        }
