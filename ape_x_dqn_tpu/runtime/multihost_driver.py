"""Multi-host Ape-X: one learner process per host, SPMD lockstep.

The reference's multi-host learner is NCCL/MPI process groups running
synchronized training steps while each host ingests its own actors'
experience (SURVEY.md §5 "distributed communication backend"). The
TPU-native shape of that design:

- Every process builds the SAME global (dp, tp) mesh (parallel/mesh.py
  over jax.devices(), which spans hosts under jax.distributed) and the
  same DistDQNLearner; GSPMD inserts the cross-host collectives.
- Each host runs its OWN actors + batched inference server + transport;
  experience lands only in the dp replay rows that host owns
  (parallel/multihost.process_rows) — experience never crosses hosts,
  exactly like the reference's per-learner replay locality.
- The learner loop is a synchronous ROUND protocol instead of the
  single-host driver's free-running threads: jitted programs on global
  arrays are collectives, so every process must issue the identical
  call sequence. Each round:

      1. all processes agree (global_min) whether every host has a
         full ingest block staged; if so, all call `add` together —
         gating beats padding, because dead filler items would cycle
         the replay ring and evict real experience on idle hosts;
      2. the replay fill check, train_many dispatch, publication
         boundary, and termination all branch on GLOBAL values (jit
         outputs or global_sum/min reductions), never on host-local
         state.

  A host whose actors all die stalls global ingest (training continues
  on existing data); a host whose PROCESS dies hangs the collectives —
  the same failure domain as the reference's NCCL group, recovered by
  restarting the job from a checkpoint.

Run via the CLI:
    python -m ape_x_dqn_tpu.runtime.train --config pong \
        --coordinator HOST:PORT --num-processes 2 --process-id 0 ...
"""

from __future__ import annotations

import threading
import time
from collections import deque

import jax
import numpy as np

from ape_x_dqn_tpu.comm.transport import LoopbackTransport
from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.models import build_network
from ape_x_dqn_tpu.parallel.dist_learner import DistDQNLearner
from ape_x_dqn_tpu.parallel.inference_server import BatchedInferenceServer
from ape_x_dqn_tpu.parallel.mesh import make_mesh
from ape_x_dqn_tpu.parallel import multihost
from ape_x_dqn_tpu.replay.frame_ring import frame_segment_spec
from ape_x_dqn_tpu.runtime.driver import build_prioritized_replay
from ape_x_dqn_tpu.runtime.family import (
    actor_class, family_of, server_apply_fn, warmup_example)
from ape_x_dqn_tpu.runtime.learner import transition_item_spec
from ape_x_dqn_tpu.utils.metrics import Metrics
from ape_x_dqn_tpu.utils.misc import next_pow2
from ape_x_dqn_tpu.utils.rng import component_key


class MultihostApexDriver:
    """Synchronous-round Ape-X driver; one instance per learner process.

    Supports the flat-DQN family (both storage layouts). The recurrent
    and continuous families run multi-host today by putting their
    ACTORS on remote hosts (runtime/actor_host.py) against a
    single-process learner; extending this lockstep loop to them is
    mechanical (same learners, same round protocol) once a workload
    needs it.
    """

    def __init__(self, cfg: RunConfig, metrics: Metrics | None = None,
                 transport=None):
        assert jax.process_count() > 1, \
            "MultihostApexDriver requires jax.distributed (use ApexDriver " \
            "for single-process runs)"
        self.cfg = cfg
        self.family = family_of(cfg)
        if self.family != "dqn":
            raise NotImplementedError(
                "multihost lockstep loop covers the flat-DQN family; "
                "run r2d2/dpg learners single-process with remote actor "
                "hosts (runtime/actor_host.py)")
        self.metrics = metrics or Metrics()
        probe_env = make_env(cfg.env, seed=cfg.seed)
        self.spec = probe_env.spec
        self.net = build_network(cfg.network, self.spec)
        obs0 = probe_env.reset()
        params = self.net.init(component_key(cfg.seed, "net_init"),
                               obs0[None])

        self.mesh = make_mesh(dp=cfg.parallel.dp, tp=cfg.parallel.tp)
        self.row_start, self.row_stop = multihost.process_rows(self.mesh)
        self.dp = cfg.parallel.dp
        self.dp_local = self.row_stop - self.row_start

        self._frame_mode = cfg.replay.storage == "frame_ring"
        if self._frame_mode:
            item_spec = frame_segment_spec(
                cfg.replay.seg_transitions, cfg.learner.n_step,
                self.spec.obs_shape, self.spec.obs_dtype)
            self._unit_items = cfg.replay.seg_transitions
            self._chunk = max(cfg.replay.segs_per_add, 1)
        else:
            item_spec = transition_item_spec(self.spec.obs_shape,
                                             self.spec.obs_dtype)
            self._unit_items = 1
            self._chunk = max(cfg.actors.ingest_batch, 1)
        self._item_keys = tuple(item_spec.keys())

        # identical construction on every process (same cfg.seed) ->
        # identical initial params; learner.init then shards them over
        # the global mesh (a collective: all processes reach this line)
        shard_cap = next_pow2(max(cfg.replay.capacity // self.dp, 2))
        self.replay = build_prioritized_replay(cfg, self.spec, shard_cap,
                                               self._frame_mode)
        self.capacity = shard_cap * self.dp
        self.learner = DistDQNLearner(self.net.apply, self.replay,
                                      cfg.learner, self.mesh)
        self.state = self.learner.init(
            params, item_spec, component_key(cfg.seed, "learner"))

        # publication is a global collective (tp all-gather + cross-host
        # replication); the inference server's jit runs process-LOCALLY,
        # so it gets a host copy — a global array would not mix with the
        # server's local inputs
        server_params = self._host_params()
        self.server = BatchedInferenceServer(
            server_apply_fn(self.family, self.net), server_params,
            max_batch=cfg.inference.max_batch,
            deadline_ms=cfg.inference.deadline_ms)
        self.transport = transport if transport is not None \
            else LoopbackTransport()
        self.transport.publish_params(server_params, 0)

        self.stop_event = threading.Event()
        self.episode_returns: deque[float] = deque(maxlen=200)
        self._frames_local = 0
        self._grad_steps = 0
        self._stage: list[dict] = []
        self._stage_n = 0
        self._lock = threading.Lock()
        self.actor_errors: list[tuple[int, Exception]] = []

    def _host_params(self):
        """publish_params (collective, all processes call) -> host numpy
        (valid per-process because the result is fully replicated)."""
        pub = self.learner.publish_params(self.state)
        return jax.tree.map(np.asarray, pub)

    # -- local actor plumbing (per host) ----------------------------------

    def _on_episode(self, actor_index: int, info: dict) -> None:
        with self._lock:
            self.episode_returns.append(float(info["episode_return"]))

    def _actor_thread(self, i: int, max_frames: int) -> None:
        try:
            actor = actor_class(self.family)(
                self.cfg, i, self.server.query, self.transport,
                episode_callback=self._on_episode)
            actor.run(max_frames, self.stop_event)
        except Exception as e:  # noqa: BLE001 - reported in run() output
            with self._lock:
                self.actor_errors.append((i, e))

    def _pump_ingest(self) -> None:
        """Drain the transport into the local stage (runs each round —
        no separate ingest thread: the round loop owns the state)."""
        while True:
            batch = self.transport.recv_experience(timeout=0.0)
            if batch is None:
                return
            n = int(batch["priorities"].shape[0])
            with self._lock:
                self._frames_local += int(batch.get("frames", n))
            self._stage.append(batch)
            self._stage_n += n

    def _pop_block(self) -> dict | None:
        """Take one [dp_local, chunk, ...] block off the stage."""
        need = self.dp_local * self._chunk
        if self._stage_n < need:
            return None
        fields = {
            k: np.concatenate([np.asarray(b[k]) for b in self._stage])
            for k in self._item_keys + ("priorities",)}
        take = {k: v[:need].reshape(self.dp_local, self._chunk,
                                    *v.shape[1:])
                for k, v in fields.items()}
        rest = {k: v[need:] for k, v in fields.items()}
        self._stage = [rest] if rest["priorities"].shape[0] else []
        self._stage_n -= need
        return take

    def _min_fill(self) -> int:
        return min(self.cfg.replay.min_fill, self.capacity // 2)

    # -- the lockstep round loop ------------------------------------------

    def run(self, total_env_frames: int | None = None,
            max_grad_steps: int = 10**9) -> dict:
        """Round loop. Termination derives from global frame/step counts
        only (wall clocks differ across hosts and would diverge the
        call sequences)."""
        cfg = self.cfg
        total = total_env_frames or cfg.total_env_frames
        per_actor = (total // max(jax.process_count(), 1)
                     // max(cfg.actors.num_actors, 1))
        publish_every = cfg.learner.publish_every
        chunk_steps = max(min(cfg.learner.train_chunk, publish_every), 1)

        threads = [threading.Thread(target=self._actor_thread,
                                    args=(i, per_actor),
                                    name=f"actor-{i}", daemon=True)
                   for i in range(cfg.actors.num_actors)]
        self.server.warmup(warmup_example(self.family, cfg, self.spec))
        for t in threads:
            t.start()

        t0 = time.monotonic()
        filled = 0
        frames_global = 0.0
        loss = float("nan")
        global_size = jax.jit(
            lambda s: s.replay.size.sum(),
            out_shardings=jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec()))
        while True:
            self._pump_ingest()
            progressed = False
            # 1. collective ingest, gated on EVERY host having a block
            blocks_ready = 1.0 if self._stage_n >= \
                self.dp_local * self._chunk else 0.0
            if multihost.global_min(self.mesh, blocks_ready) >= 1.0:
                block = self._pop_block()
                items = multihost.make_global(
                    self.mesh,
                    {k: v for k, v in block.items() if k != "priorities"})
                pris = multihost.make_global(self.mesh,
                                             block["priorities"])
                self.state = self.learner.add(self.state, items, pris)
                filled = int(global_size(self.state))
                progressed = True
            # 2. lockstep training, branch on global values only
            if filled >= self._min_fill() \
                    and self._grad_steps < max_grad_steps:
                to_publish = publish_every - (self._grad_steps
                                              % publish_every)
                k = chunk_steps if chunk_steps <= min(
                    max_grad_steps - self._grad_steps, to_publish) else 1
                self.state, m = self.learner.train_many(self.state, k)
                self._grad_steps += k
                loss = float(m["loss"])
                progressed = True
                if self._grad_steps % publish_every == 0:
                    pub = self._host_params()
                    self.server.update_params(pub, self._grad_steps)
                    self.transport.publish_params(pub, self._grad_steps)
            # 3. global termination — all conditions from global values.
            # `local_idle`: this host can never ingest again (actors
            # finished/dead, transport drained, stage short of a block) —
            # guards against frame counts that never reach `total`
            # (lossy-transport drops, per-actor truncation of the budget)
            with self._lock:
                frames_local = self._frames_local
            frames_global = multihost.global_sum(self.mesh,
                                                 float(frames_local))
            local_idle = 1.0 if (not any(t.is_alive() for t in threads)
                                 and self.transport.pending == 0
                                 and blocks_ready < 1.0) else 0.0
            all_idle = multihost.global_min(self.mesh, local_idle) >= 1.0
            if self._grad_steps >= max_grad_steps:
                break
            if frames_global >= total and max_grad_steps >= 10**9:
                break  # frame-budget run: actors are done
            if all_idle and (max_grad_steps >= 10**9
                             or filled < self._min_fill()):
                # ingest can never resume anywhere; either there is no
                # finite step target to chase, or training can never
                # start — spinning helps nobody
                break
            if not progressed:
                # idle round: don't hammer the coordination service
                # (sleep is host-local pacing, no collective is skipped)
                time.sleep(0.05)

        self.stop_event.set()
        for t in threads:
            t.join(timeout=5)
        self.server.stop()
        with self._lock:
            avg_ret = (float(np.mean(self.episode_returns))
                       if self.episode_returns else 0.0)
        return {
            "process": jax.process_index(),
            "frames": int(frames_global),
            "frames_local": self._frames_local,
            "grad_steps": self._grad_steps,
            "loss": loss,
            "replay_filled": filled,
            "avg_return": avg_ret,
            "wall_s": time.monotonic() - t0,
            "actor_errors": [f"{i}: {e!r}" for i, e in self.actor_errors],
        }
