"""Zero-copy pipelined ingest staging (actor wire -> device replay).

The legacy driver staging appended each received batch to a Python list
and re-concatenated the whole backlog per flush — every wire byte was
copied at decode, again at concatenate, and the carried `rest` dict was
re-copied at every subsequent flush. This module replaces that with
preallocated fixed-shape staging buffers:

- Wire batches decode DIRECTLY into a contiguous staging row at a write
  cursor (comm/socket_transport.decode_batch_into): ONE copy per wire
  byte, contiguous by construction. Contiguity is what device_put speed
  lives on — PERF.md round 5 measured ~80 vs ~3,000 items/s between a
  fragmented and a contiguous host source.
- Double buffering: while buffer N's async device_put is in flight,
  the next batches decode into buffer N+1; the stager blocks on the
  in-flight handles only when it is about to overwrite that memory.
- Coalescing: a buffer holds `coalesce` fixed-size blocks; a FULL
  buffer ships as one `add_many` dispatch (g blocks, one donated jit,
  one _state_lock acquisition) instead of g small adds interleaving
  with the learner's train_many dispatches.

Shapes are fixed by construction (block = dp * stage_chunk units,
buffer = coalesce blocks), so the device sees exactly two add graphs:
the warmed single-block `add` (idle drains, see below) and the warmed
`add_many` at g = coalesce. Ragged shapes would each compile a fresh
XLA graph (20-40s on TPU).

Latency bound: the driver calls drain() whenever the transport queue
runs dry (its 0.1s recv timeout), which ships every COMPLETE block in
the partial buffer block-by-block through the warmed `add` graph and
compacts the remainder to the buffer front — so coalescing never holds
experience hostage behind a slow actor stream. The sub-block tail only
drops (counted by the driver, in the same three denominations as the
legacy path) at force-flush during teardown.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import numpy as np

# ship(views, g): views is {key: np.ndarray of [g*block_units, ...]}
# including "priorities"; g is the number of coalesced blocks. Returns
# the device-side handles of the asynchronous host->device transfer;
# the stager blocks on them before reusing the staging memory.
ShipFn = Callable[[dict, int], list]


class IngestStager:
    def __init__(self, item_spec: dict, ptail: tuple, block_units: int,
                 coalesce: int, buffers: int, ship: ShipFn):
        """item_spec: {key: spec with .shape/.dtype} for one staging
        unit; ptail: trailing priority axes ((seg_transitions,) in
        frame-ring mode, () otherwise); block_units: dp * stage_chunk
        units per device add; coalesce: blocks fused per full-buffer
        add_many; buffers: staging buffers to rotate (>= 2 gives the
        decode/transfer overlap)."""
        self.block = int(block_units)
        self.coalesce = max(int(coalesce), 1)
        self.rows = self.block * self.coalesce
        self.nb = max(int(buffers), 1)
        self._ship = ship
        self._keys = tuple(item_spec.keys()) + ("priorities",)
        shapes = {k: tuple(s.shape) for k, s in item_spec.items()}
        dtypes = {k: s.dtype for k, s in item_spec.items()}
        shapes["priorities"] = tuple(ptail)
        dtypes["priorities"] = np.float32
        self._bufs = [
            {k: np.zeros((self.rows,) + shapes[k], dtypes[k])
             for k in self._keys}
            for _ in range(self.nb)]
        self._inflight: list[list] = [[] for _ in range(self.nb)]
        self._active = 0
        self._cursor = 0  # rows staged in the active buffer
        # wire-codec decode accounting: cumulative wall-ms spent inside
        # decode_into/dict landing (inflate + delta-undo + the one copy)
        # — obs surfaces it as ingest_decode_ms per put
        self.decode_ms = 0.0
        self.last_put_decode_ms = 0.0
        # ship-side accounting: host wall-ms spent inside the ship
        # callback (device_put enqueue + the donated add dispatch under
        # _state_lock — NOT device execution time; the sampled roofline
        # windows in the driver measure that). A last_ship_ms creeping
        # toward the decode budget means the "async" transfer path has
        # started blocking, i.e. the overlap is lost
        self.ship_ms = 0.0
        self.last_ship_ms = 0.0
        # cross-process correlation: tags (e.g. (peer, batch_id) from
        # the wire header) of batches staged since the last ship; the
        # ship callback reads `shipping_tags` to attribute the device
        # dispatch. Approximate by design — a batch that straddles a
        # buffer boundary is attributed to the ship that took its head
        self._pending_tags: list = []
        self.shipping_tags: tuple = ()

    # -- write side --------------------------------------------------------

    def _wait(self, i: int) -> None:
        """Block until buffer i's previous host->device transfer is done
        — only then may its host memory be rewritten. With >= 2 buffers
        this almost never actually waits (the transfer overlapped the
        previous buffer's decode)."""
        if self._inflight[i]:
            jax.block_until_ready(self._inflight[i])  # apexlint: host-sync(deliberate reuse barrier: memory rewritten only after its transfer lands)
            self._inflight[i] = []

    def put(self, batch, tag=None) -> None:
        """Stage one ingest message (WireBatch or plain dict of arrays),
        splitting across buffer boundaries; full buffers ship as one
        coalesced add_many. `tag` is an opaque correlation handle
        surfaced via `shipping_tags` on the ship that carries it."""
        if tag is not None:
            self._pending_tags.append(tag)
        wire = hasattr(batch, "decode_into")
        total = batch.rows if wire \
            else int(batch["priorities"].shape[0])
        start = 0
        put_ms = 0.0
        while start < total:
            self._wait(self._active)
            buf = self._bufs[self._active]
            k = min(total - start, self.rows - self._cursor)
            t0 = time.perf_counter()
            if wire:
                batch.decode_into(buf, self._cursor, start, k)
            else:
                for key in self._keys:
                    buf[key][self._cursor:self._cursor + k] = \
                        np.asarray(batch[key])[start:start + k]  # apexlint: host-sync(wire batch is host numpy, not a device value)
            put_ms += (time.perf_counter() - t0) * 1e3
            self._cursor += k
            start += k
            if self._cursor == self.rows:
                self._ship_buffer()
        self.last_put_decode_ms = put_ms
        self.decode_ms += put_ms
        # shm slot batches alias a shared-memory ring slot; releasing
        # after the staging land returns the slot to the actor's
        # free-list (plain WireBatch/dict have no release — no-op)
        rel = getattr(batch, "release", None)
        if rel is not None:
            rel()

    def _ship_buffer(self) -> None:
        """Full buffer -> one add_many dispatch; rotate to the next
        buffer while the transfer flies."""
        buf = self._bufs[self._active]
        self.shipping_tags = tuple(self._pending_tags)
        self._pending_tags = []
        t0 = time.perf_counter()
        self._inflight[self._active] = list(
            self._ship({k: buf[k] for k in self._keys}, self.coalesce))
        self.last_ship_ms = (time.perf_counter() - t0) * 1e3
        self.ship_ms += self.last_ship_ms
        self._active = (self._active + 1) % self.nb
        self._cursor = 0

    # -- drain / teardown --------------------------------------------------

    def drain(self) -> int:
        """Ship every COMPLETE block in the partial active buffer
        through the warmed single-block add graph (g=1 keeps the graph
        count fixed: partial groups at every g in [1, coalesce) would
        each compile fresh). Remainder rows compact to the buffer front.
        Called by the driver whenever the transport queue runs dry, so
        coalescing costs bounded latency. Returns blocks shipped."""
        nblocks = self._cursor // self.block
        if nblocks == 0:
            return 0
        buf = self._bufs[self._active]
        shipped = nblocks * self.block
        self.shipping_tags = tuple(self._pending_tags)
        self._pending_tags = []
        handles: list = []
        t0 = time.perf_counter()
        for b in range(nblocks):
            views = {k: buf[k][b * self.block:(b + 1) * self.block]
                     for k in self._keys}
            handles += list(self._ship(views, 1))
        self.last_ship_ms = (time.perf_counter() - t0) * 1e3
        self.ship_ms += self.last_ship_ms
        rem = self._cursor - shipped
        if rem:
            # the shipped region becomes the compaction destination:
            # wait for its transfer before overwriting. Non-overlapping
            # copy: rem < block <= shipped.
            jax.block_until_ready(handles)  # apexlint: host-sync(compaction barrier: shipped region is the copy destination)
            for k in self._keys:
                buf[k][:rem] = buf[k][shipped:self._cursor]
        else:
            self._inflight[self._active] = handles
        self._cursor = rem
        return nblocks

    def tail_units(self) -> int:
        """Staged rows that cannot form a complete block (valid after
        drain()); the driver's force-flush drop accounting reads this."""
        return self._cursor

    def tail_view(self, key: str) -> np.ndarray:
        """View of the staged sub-block tail for `key` (e.g. frame-ring
        drop accounting counts live transitions via next_off)."""
        return self._bufs[self._active][key][:self._cursor]

    def tail_shard_units(self, dp: int) -> list[int]:
        """Unit count of the current sub-block tail per dp shard under
        the driver's round-robin block split: a shipped block reshapes
        [block] -> [dp, chunk] (chunk = block // dp) in C order, so
        tail unit i would have landed on shard i // chunk. The driver's
        per-shard drop closure (`sum(per_shard) == dropped`, pinned by
        tests/test_ingest.py) folds these counts into whichever
        denomination the storage family drops in."""
        chunk = self.block // max(dp, 1)
        tail = self.tail_units()
        return [max(0, min(tail - d * chunk, chunk)) for d in range(dp)]

    def discard_tail(self) -> None:
        self._cursor = 0

    def occupancy(self) -> float:
        """Fill fraction of the active staging buffer (obs gauge)."""
        return self._cursor / self.rows

    def free_units(self) -> int:
        """Rows the active buffer absorbs before a put triggers the
        coalesced ship. The cold tier's idle refill tick bounds its
        recall/promotion burst to this so restaging recalled segments
        never forces a synchronous mid-idle add_many dispatch (which
        would take _state_lock against train_many — the contention the
        idle tick exists to avoid)."""
        return self.rows - self._cursor
