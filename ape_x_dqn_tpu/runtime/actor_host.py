"""Remote actor host: actors on another machine feeding a learner host.

The reference scales to 256 actors by spawning actor processes on many
machines, each pushing experience and pulling parameters over gRPC
(SURVEY.md §3.1). The TPU-native equivalent: this module runs N actor
threads on a CPU host, evaluates the policy on a LOCAL batched inference
server (CPU jit — actor hosts have no TPU), pushes experience to the
learner host's SocketIngestServer over DCN, and pulls fresh parameters
on a cadence through the same connection.

Entry points:
- run_actor_host(cfg, host, port, ...) — library call.
- `python -m ape_x_dqn_tpu.runtime.actor_host --config pong
  --connect HOST:PORT --actors 4` — one actor machine.
"""

from __future__ import annotations

import dataclasses
import os
import socket as socket_mod
import sys
import threading
import time

from ape_x_dqn_tpu.comm.socket_transport import SocketTransport
from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.models import build_network
from ape_x_dqn_tpu.obs.core import build_obs
from ape_x_dqn_tpu.obs.fleet import StampingTransport, TelemetryEmitter
from ape_x_dqn_tpu.parallel.inference_server import (
    BatchedInferenceServer, build_serving_tier)
from ape_x_dqn_tpu.runtime.family import (
    actor_class, family_of, server_apply_fn, warmup_example)
from ape_x_dqn_tpu.utils.metrics import Metrics


def default_peer_id(actor_offset: int = 0) -> str:
    """Stable-for-the-process, unique-across-the-fleet peer identity:
    hostname + pid + this host's slot in the global actor schedule."""
    return (f"{socket_mod.gethostname()}-{os.getpid()}"
            f"-a{actor_offset}")


def run_actor_host(cfg: RunConfig, host: str, port: int,
                   num_actors: int | None = None,
                   actor_offset: int = 0,
                   frames_per_actor: int | None = None,
                   param_poll_s: float | None = None,
                   stop_event: threading.Event | None = None,
                   wait_for_params_s: float = 60.0,
                   peer_id: str | None = None,
                   supervise: bool = False) -> dict:
    """Run actors against a remote learner until their frame budget ends.

    actor_offset positions this host's actors inside the global eps_i
    schedule (host k of m runs indices [k*n, (k+1)*n) of num_actors*m).

    param_poll_s=None (the default) paces parameter pulls by ENV STEPS:
    the puller refreshes once the host's actors collectively advance
    cfg.actors.param_pull_every frames per actor — Horgan et al. 2018's
    "actors pull every ~400 env steps" — with a 30s keep-alive floor so
    an idle host still tracks the live epoch. Passing a float restores
    the fixed wall-clock cadence (bandwidth-constrained links where
    seconds, not steps, are the budget).

    peer_id names this host on the fleet telemetry plane (obs/fleet.py);
    with obs enabled, experience batches are stamped with it plus a
    monotonic batch_id, and a TelemetryEmitter ships obs snapshot
    frames to the learner every cfg.obs.telemetry_every_s.

    supervise=True makes this host survive learner restarts instead of
    exiting: the bootstrap wait for first params never times out (the
    transport's supervised reconnect loop keeps re-entering connect/
    negotiate under backoff until a learner — the same one or a new
    incarnation at the same address — answers), and mid-run learner
    loss is already survived by the transport (sends drop-and-back-off,
    params re-converge to the live epoch on reconnect).
    """
    n = num_actors or cfg.actors.num_actors
    stop_event = stop_event or threading.Event()
    peer = peer_id or default_peer_id(actor_offset)
    comm = cfg.comm
    serving = cfg.serving
    transport = SocketTransport(
        host, port, wire_codec=comm.wire_codec,
        reconnect_base_s=getattr(comm, "reconnect_base_s", 0.05),
        reconnect_cap_s=getattr(comm, "reconnect_cap_s", 2.0),
        params_push=getattr(comm, "params_push", False),
        param_codec=getattr(comm, "param_codec", "delta-q8"),
        serve_policy=(cfg.env.id if serving.multi_tenant else ""),
        serve_class=serving.default_class,
        shm=getattr(comm, "shm", False),
        shm_slots=getattr(comm, "shm_slots", 8),
        shm_slot_bytes=getattr(comm, "shm_slot_bytes", 1 << 22))
    # the raw socket transport, before any StampingTransport wrap: the
    # serving tier's backpressure callback must reach the object that
    # owns send_experience's drop gate
    raw_transport = transport
    # local obs: metrics stay in-memory (the learner's JSONL is the
    # run's single artifact; this host's view crosses the wire as
    # telemetry frames), and a trace path gets a per-peer suffix so
    # co-located hosts don't clobber the learner's trace file
    obs_cfg = cfg.obs
    if obs_cfg.trace_path:
        obs_cfg = dataclasses.replace(
            obs_cfg, trace_path=f"{obs_cfg.trace_path}.{peer}")
    obs = build_obs(obs_cfg, Metrics())
    # forensics plane: this host's flight recorder dumps under the
    # peer's name, with the transport's reconnect/drop tallies merged
    # into every dump (SIGUSR2 install is skipped off the main thread)
    obs.blackbox.set_peer(peer)
    obs.blackbox.add_context_provider(
        lambda: {"transport": {
            "reconnects": raw_transport.reconnects,
            "dropped": raw_transport.dropped,
            "drop_reasons": dict(raw_transport.drop_reasons),
            "epoch": raw_transport.epoch}})
    obs.blackbox.install()
    emitter: TelemetryEmitter | None = None
    if obs.enabled:
        transport = StampingTransport(transport, peer)
        emitter = TelemetryEmitter(transport, obs, peer,
                                   interval_s=cfg.obs.telemetry_every_s)

    # wait for the learner to publish a first param set; under
    # --supervise the wait is unbounded (a host that outlives its
    # learner must keep re-entering connect until one comes back)
    deadline = time.monotonic() + wait_for_params_s
    params, version = transport.get_params()
    while params is None and not stop_event.is_set() \
            and (supervise or time.monotonic() < deadline):
        time.sleep(0.2)
        params, version = transport.get_params()
    if params is None:
        transport.close()
        raise TimeoutError("learner never published parameters")

    probe = make_env(cfg.env, seed=cfg.seed)
    net = build_network(cfg.network, probe.spec)
    # family dispatch shared with the driver (runtime/family.py): the
    # server protocol, actor class, and warmup example must all match
    # what the learner host's published params expect
    family = family_of(cfg)
    if serving.multi_tenant:
        # multi-tenant serving tier: this host's policy registers under
        # env.id; the tier's admission controller pushes backpressure
        # into the transport's drop gate when the queue crosses the SLO
        tier = build_serving_tier(
            serving, max_batch=cfg.inference.max_batch,
            deadline_ms=cfg.inference.deadline_ms,
            obs=obs if obs.enabled else None)
        if serving.backpressure:
            tier.on_backpressure = raw_transport.set_backpressure
        server = tier.register_policy(
            cfg.env.id, server_apply_fn(family, net), params,
            family=family, priority=serving.default_class)
    else:
        server = BatchedInferenceServer(
            server_apply_fn(family, net), params,
            max_batch=cfg.inference.max_batch,
            deadline_ms=cfg.inference.deadline_ms,
            obs=obs if obs.enabled else None)
    server.update_params(params, version)
    if emitter is not None:
        emitter.start()
    try:  # pre-compile the forward so first queries don't time out
        server.warmup(warmup_example(family, cfg, probe.spec),
                      extra_sizes=(cfg.actors.envs_per_actor,))
    except (AttributeError, NotImplementedError):
        # AOT lowering unavailable on this backend: compile lazily on
        # first query. Anything else (shape mismatch, compile OOM) is a
        # real bug that must surface, not a silent degraded start.
        print("actor_host: AOT warmup unavailable; first query compiles "
              "lazily", file=sys.stderr, flush=True)

    # step-paced pulls (param_poll_s=None) read the live actors' frame
    # counters: refresh once the fleet advances param_pull_every frames
    # per actor. The counters are plain ints bumped by the actor
    # threads — a cadence heuristic, racy reads are fine.
    live_actors: list = [None] * n
    frame_paced = param_poll_s is None
    poll_tick = 0.2 if frame_paced else param_poll_s
    pull_every_frames = max(cfg.actors.param_pull_every, 1) * n

    def param_puller() -> None:
        # resilience contract: NOTHING in here may kill the thread — a
        # transient pull failure keeps last-good params on the server,
        # bumps the param_pull_errors counter, and widens the poll wait
        # (bounded backoff) until pulls succeed again. An epoch change
        # (learner restart) FORCES the update even when the new
        # incarnation's version counter restarted below ours — version
        # monotonicity only holds within one epoch.
        seen_epoch = transport.param_epoch
        seen_pull_errors = transport.param_pull_errors
        fail_streak = 0
        pulled_at_frames = 0
        pulled_at_t = time.monotonic()
        while not stop_event.wait(
                min(poll_tick * (2 ** min(fail_streak, 4)), 30.0)):
            if frame_paced and fail_streak == 0:
                total = sum(a.frames for a in live_actors
                            if a is not None)
                if (total - pulled_at_frames < pull_every_frames
                        and time.monotonic() - pulled_at_t < 30.0):
                    continue
                pulled_at_frames = total
            pulled_at_t = time.monotonic()
            try:
                # server-pushed params (if negotiated) take priority —
                # they are publish-fresh; the conditional poll is the
                # fallback and the keep-alive
                p, v = transport.poll_pushed_params()
                if p is None:
                    p, v = transport.get_params()
                errs = transport.param_pull_errors
                if errs > seen_pull_errors:
                    obs.count("param_pull_errors", errs - seen_pull_errors)
                    seen_pull_errors = errs
                    fail_streak += 1
                    continue
                fail_streak = 0
                if p is None:  # "unchanged" reply or nothing pushed
                    continue
                ep = transport.param_epoch
                if v > server.params_version \
                        or (ep != -1 and ep != seen_epoch):
                    server.update_params(p, v)
                seen_epoch = ep
            except Exception:  # noqa: BLE001 - puller must outlive anything
                obs.count("param_pull_errors")
                fail_streak += 1

    puller = threading.Thread(target=param_puller, name="param-pull",
                              daemon=True)
    puller.start()

    # remediation plane, host side (runtime/remediation.py): the
    # learner-side engine cannot reach this host's transport latch, so
    # an ENFORCE-mode host runs a stale-latch watchdog of its own — a
    # transport backpressure latch that the local admission controller
    # DISAGREES with (tier released or never engaged, latch still set)
    # for remediation.release_after_s is released locally. Complements
    # the epoch-change clear in comm/socket_transport._note_epoch:
    # that one needs a reply from the new incarnation to arrive; this
    # one covers a latch desynced by a controller that went silent.
    rcfg = getattr(cfg, "remediation", None)
    bp_thread: threading.Thread | None = None
    if (rcfg is not None and rcfg.mode == "enforce"
            and serving.multi_tenant and serving.backpressure):
        def bp_watchdog() -> None:
            stale_since: float | None = None
            while not stop_event.wait(1.0):
                stale = (raw_transport.backpressure_engaged
                         and not tier.backpressure_engaged)
                if not stale:
                    stale_since = None
                    continue
                now = time.monotonic()
                if stale_since is None:
                    stale_since = now
                elif now - stale_since >= rcfg.release_after_s:
                    raw_transport.set_backpressure(False)
                    obs.count("remediation_actions")
                    stale_since = None

        bp_thread = threading.Thread(target=bp_watchdog,
                                     name="remediation-bp", daemon=True)
        bp_thread.start()

    per_actor = frames_per_actor or (
        cfg.total_env_frames // max(cfg.actors.num_actors, 1))
    errors: list[tuple[int, Exception]] = []
    frames = [0] * n

    vector = cfg.actors.envs_per_actor > 1
    cls = actor_class(family, vector=vector)
    query = server.query_batch if vector else server.query

    def actor_thread(slot: int) -> None:
        idx = actor_offset + slot
        try:
            actor = cls(cfg, idx, query, transport,
                        obs=obs if obs.enabled else None)
            live_actors[slot] = actor  # puller paces pulls off .frames
            frames[slot] = actor.run(per_actor, stop_event)
            obs.clear(f"actor-{idx}")  # finished, not stalled
        except Exception as e:  # noqa: BLE001 - reported to caller
            # the thread dies quietly from the interpreter's point of
            # view (no excepthook) — archive the ring ourselves
            obs.blackbox.record("actor_error", component=f"actor-{idx}",
                                error=repr(e)[:200])
            obs.blackbox.dump("actor_error", component=f"actor-{idx}")
            errors.append((idx, e))

    threads = [threading.Thread(target=actor_thread, args=(i,),
                                name=f"actor-{actor_offset + i}",
                                daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        # bounded join in a liveness loop: actors run to frame budget,
        # but a wedged worker must not wedge teardown unobservably
        while t.is_alive():
            t.join(timeout=5.0)
    stop_event.set()
    puller.join(timeout=2)
    if bp_thread is not None:
        bp_thread.join(timeout=2)
    server.stop()
    if emitter is not None:
        emitter.stop()  # ships one shutdown-fresh frame
    obs.close()
    transport.close()
    return {"frames": sum(frames), "actors": n,
            "dropped": transport.dropped, "errors": errors,
            "drop_reasons": transport.drop_reasons,
            "reconnects": transport.reconnects,
            "epoch": transport.epoch,
            "epoch_changes": transport.epoch_changes,
            "param_pull_errors": transport.param_pull_errors,
            "param_pushes_in": transport.param_pushes_in,
            "param_codec_negotiated": transport.param_codec_negotiated,
            "param_resyncs": transport.param_resyncs,
            "bytes_out": transport.bytes_out,
            "wire_codec": transport.negotiated_codec,
            "wire_compression_ratio": round(
                transport.wire_compression_ratio, 3),
            "encode_ms": round(transport.encode_ms, 1),
            "param_bytes_in": transport.bytes_in,
            "last_param_version": server.params_version,
            "peer_id": peer,
            "telemetry_negotiated": transport.telemetry_negotiated,
            "serve_negotiated": raw_transport.serve_negotiated,
            "shm_negotiated": raw_transport.shm_negotiated,
            "shm_posts": raw_transport.shm_posts,
            "shm_fallbacks": raw_transport.shm_fallbacks,
            "shm_bytes_out": raw_transport.shm_bytes_out,
            "shm_param_reads": raw_transport.shm_param_reads,
            "telemetry_frames_out": transport.telemetry_frames_out}


def main(argv: list[str] | None = None) -> int:
    import argparse
    import os

    # actor hosts evaluate the policy on THEIR cpu (no TPU in the
    # reference's actor machines either) — honor JAX_PLATFORMS through
    # jax.config because interpreter-startup hooks (sitecustomize TPU
    # plugins) may have imported jax already, making the env var alone
    # too late (same dance as parallel/multihost.init_multihost); a
    # co-located actor host grabbing the learner's chip would otherwise
    # fight it for the device
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax
        jax.config.update("jax_platforms", platforms)

    from ape_x_dqn_tpu.configs import get_config
    from ape_x_dqn_tpu.runtime.train import apply_overrides

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", required=True)
    ap.add_argument("--connect", required=True, metavar="HOST:PORT")
    ap.add_argument("--actors", type=int, default=None)
    ap.add_argument("--actor-offset", type=int, default=0)
    ap.add_argument("--frames-per-actor", type=int, default=None)
    ap.add_argument("--param-poll-s", type=float, default=None,
                    help="fixed seconds between parameter pulls from "
                         "the learner. Default: step-paced — pull once "
                         "this host's actors advance "
                         "actors.param_pull_every env steps each "
                         "(Ape-X's ~400), 30s keep-alive. Each pull "
                         "moves the full param tree over DCN, so on "
                         "bandwidth-constrained links set the seconds "
                         "toward the staleness you can tolerate")
    ap.add_argument("--peer-id", default=None,
                    help="name of this host on the fleet telemetry "
                         "plane (default: hostname-pid-a<offset>); "
                         "shows up as peer/<id>/ in the learner's "
                         "report and in stall attributions")
    ap.add_argument("--supervise", action="store_true",
                    help="survive learner restarts: wait indefinitely "
                         "for first params and keep re-entering the "
                         "connect/negotiate path (backoff-capped) when "
                         "the learner goes away mid-run, instead of "
                         "exiting — the elastic-fleet mode for hosts "
                         "managed by a process supervisor")
    ap.add_argument("--set", action="append", default=[],
                    metavar="dotted.key=value")
    args = ap.parse_args(argv)
    cfg = apply_overrides(get_config(args.config), args.set)
    host, port = args.connect.rsplit(":", 1)
    out = run_actor_host(cfg, host, int(port), num_actors=args.actors,
                         actor_offset=args.actor_offset,
                         frames_per_actor=args.frames_per_actor,
                         param_poll_s=args.param_poll_s,
                         peer_id=args.peer_id,
                         supervise=args.supervise)
    print(out)
    return 1 if out["errors"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
