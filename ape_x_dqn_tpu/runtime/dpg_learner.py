"""Ape-X DPG learner: critic + policy + Polyak targets in one jit.

The continuous-control counterpart of runtime/learner.DQNLearner
(SURVEY.md §2.1 config 5, §2.2 "DPG actor-critic"): one donated XLA graph
fuses prioritized sequence sampling, the critic TD update, the
deterministic-policy-gradient actor update (through the *updated*
critic), Polyak soft target updates (models/base.soft_update, tau from
LearnerConfig), and the |TD| priority write-back. The reference would run
these as separate GPU kernels; fusing them keeps the whole cycle a single
device dispatch.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ape_x_dqn_tpu.models.base import soft_update
from ape_x_dqn_tpu.obs import learning as learn_obs
from ape_x_dqn_tpu.ops.losses import ContinuousBatch, make_dpg_losses
from ape_x_dqn_tpu.replay.prioritized import ReplayState


class DPGTrainState(NamedTuple):
    actor_params: Any
    critic_params: Any
    target_actor: Any
    target_critic: Any
    actor_opt: Any
    critic_opt: Any
    replay: ReplayState
    rng: jax.Array
    step: jax.Array  # int32 grad-step counter


def continuous_item_spec(obs_shape, obs_dtype, action_dim: int) -> dict:
    """Item pytree spec for one flat n-step transition (continuous)."""
    return {
        "obs": jax.ShapeDtypeStruct(obs_shape, obs_dtype),
        "action": jax.ShapeDtypeStruct((action_dim,), jnp.float32),
        "reward": jax.ShapeDtypeStruct((), jnp.float32),
        "next_obs": jax.ShapeDtypeStruct(obs_shape, obs_dtype),
        "discount": jax.ShapeDtypeStruct((), jnp.float32),
    }


class DPGLearner:  # apexlint: parity(no train_step_k/sample_k/learn_k — K-chunked sampling is rejected by the constructor's ValueError gates; no evict_region/add_at — the cold tier is frame-ring only and DPG obs are low-dim)
    """Jitted endpoints for the Ape-X DPG learner."""

    def __init__(self, actor_apply: Callable, critic_apply: Callable,
                 replay, lcfg):
        if getattr(lcfg, "sample_chunk", 1) > 1:
            # loud, not silent: the K-batch relaxation is implemented
            # for the flat-transition DQN learners only (see
            # runtime/sequence_learner.py for the same gate)
            raise ValueError(
                "learner.sample_chunk > 1 is not implemented by the "
                "DPG learner — set sample_chunk=1")
        if getattr(lcfg, "sample_prefetch", False):
            # same rule for the double-buffered sampling pipeline: this
            # learner's fused train step has no split sample/learn
            # stages to pipeline
            raise ValueError(
                "learner.sample_prefetch is not implemented by the "
                "DPG learner — set sample_prefetch=False")
        self.actor_apply = actor_apply
        self.critic_apply = critic_apply
        self.replay = replay
        self.lcfg = lcfg
        self.critic_optimizer = optax.chain(
            optax.clip_by_global_norm(lcfg.max_grad_norm),
            optax.adam(lcfg.critic_lr, eps=lcfg.adam_eps))
        self.actor_optimizer = optax.chain(
            optax.clip_by_global_norm(lcfg.max_grad_norm),
            optax.adam(lcfg.policy_lr, eps=lcfg.adam_eps))
        self.critic_loss, self.policy_loss = make_dpg_losses(
            actor_apply, critic_apply)

    # -- state ------------------------------------------------------------

    def init(self, actor_params: Any, critic_params: Any, replay_state,
             rng: jax.Array) -> DPGTrainState:
        return DPGTrainState(
            actor_params=actor_params,
            critic_params=critic_params,
            target_actor=jax.tree.map(jnp.copy, actor_params),
            target_critic=jax.tree.map(jnp.copy, critic_params),
            actor_opt=self.actor_optimizer.init(actor_params),
            critic_opt=self.critic_optimizer.init(critic_params),
            replay=replay_state,
            rng=rng,
            step=jnp.int32(0))

    # -- core step (pure) -------------------------------------------------

    def _train_step(self, state: DPGTrainState
                    ) -> tuple[DPGTrainState, dict]:
        rng, sk = jax.random.split(state.rng)
        items, idx, is_w = self.replay.sample(
            state.replay, sk, self.lcfg.batch_size)
        batch = ContinuousBatch(
            obs=items["obs"], actions=items["action"],
            rewards=items["reward"], next_obs=items["next_obs"],
            discounts=items["discount"])

        (c_loss, c_aux), c_grads = jax.value_and_grad(
            self.critic_loss, has_aux=True)(
            state.critic_params, state.target_critic, state.target_actor,
            batch, is_w)
        c_updates, critic_opt = self.critic_optimizer.update(
            c_grads, state.critic_opt, state.critic_params)
        critic_params = optax.apply_updates(state.critic_params, c_updates)

        # policy ascends the UPDATED critic (standard DDPG ordering)
        (p_loss, p_aux), p_grads = jax.value_and_grad(
            self.policy_loss, has_aux=True)(
            state.actor_params, critic_params, batch)
        p_updates, actor_opt = self.actor_optimizer.update(
            p_grads, state.actor_opt, state.actor_params)
        actor_params = optax.apply_updates(state.actor_params, p_updates)

        tau = self.lcfg.tau
        target_actor = soft_update(state.target_actor, actor_params, tau)
        target_critic = soft_update(state.target_critic, critic_params, tau)

        replay_state = self.replay.update_priorities(
            state.replay, idx, c_aux["td_abs"])
        metrics = {
            "loss": c_loss,
            "policy_loss": p_loss,
            "q_mean": c_aux["q_mean"],
            "td_abs_mean": c_aux["td_abs"].mean(),
            "a_abs_mean": p_aux["a_abs_mean"],
            # learning-health scalars over the CRITIC update (the TD
            # learner); fused path, so staleness is identically 0
            "diag": {**learn_obs.sgd_diag(c_aux, is_w, c_grads,
                                          c_updates, critic_params),
                     **learn_obs.replay_health(
                         self.replay, state.replay, idx, None)},
        }
        new_state = DPGTrainState(
            actor_params, critic_params, target_actor, target_critic,
            actor_opt, critic_opt, replay_state, rng, state.step + 1)
        return new_state, metrics

    # -- jitted endpoints --------------------------------------------------

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state: DPGTrainState):
        return self._train_step(state)

    @partial(jax.jit, static_argnums=(0, 2), donate_argnums=1)
    def train_many(self, state: DPGTrainState, n: int):
        """n grad-steps in one dispatch via lax.scan (driver hot loop)."""
        def body(s, _):
            s, m = self._train_step(s)
            return s, m
        state, metrics = jax.lax.scan(body, state, None, length=n)
        return state, jax.tree.map(lambda x: x[-1], metrics)

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add(self, state: DPGTrainState, items: Any,
            td_abs: jax.Array) -> DPGTrainState:
        return state._replace(
            replay=self.replay.add(state.replay, items, td_abs))

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add_many(self, state: DPGTrainState, items: Any,
                 td_abs: jax.Array) -> DPGTrainState:
        """Coalesced ingest: g staged blocks in one donated dispatch —
        unrolled over the static g axis (NOT lax.scan; see
        SingleChipLearner.add_many for the CPU scan pathology)."""
        rs = state.replay
        for j in range(td_abs.shape[0]):
            rs = self.replay.add(
                rs, jax.tree.map(lambda x, j=j: x[j], items), td_abs[j])
        return state._replace(replay=rs)

    def publish_params(self, state: DPGTrainState) -> dict:
        """Donation-safe {actor, critic} param copies for the inference
        server (the server evaluates mu(s) and Q(s, mu(s)) per query)."""
        return {"actor": jax.tree.map(jnp.copy, state.actor_params),
                "critic": jax.tree.map(jnp.copy, state.critic_params)}
