"""Atari-57 per-game suite trainer — the north-star protocol runner.

The reference benchmark (SURVEY.md §2.1 config 3, BASELINE.md) is
per-game: Horgan et al. 2018 train ONE agent per game and report the
median human-normalized score over the 57 games. This harness runs
those per-game trainings with one command — the full suite
sequentially, or a shard of games per invocation so a fleet of learner
hosts splits the suite — then evaluates each game's final policy
greedily and aggregates the suite metric.

Per game: a fresh ApexDriver on cfg with env.id=<game> (per-game
minimal action set, matching the paper protocol — the multi-game
id="atari57" shared-net fleet is a different, also-supported topology),
checkpoints + JSONL metrics under <out>/<game>/, and the driver's
unclipped greedy eval as the game score. Interrupted suites resume:
each game's driver auto-restores its own checkpoint directory, and
completed games (a result.json in their dir) are skipped.

Backend honesty mirrors runtime/evaluation.py: every result carries
per-game backends, and the aggregate is "median_hns" ONLY when every
game ran on the real ALE — synthetic stand-ins aggregate under
"median_hns_synthetic".

Usage:
    python -m ape_x_dqn_tpu.runtime.suite --config atari57_apex \
        --out runs/suite --frames-per-game 50000000 \
        --set parallel.dp=1 --set parallel.tp=1
    # shard the suite across hosts:
    ... --games-shard 0/4    # host 0 of 4 trains games 0,4,8,...
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable

from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.utils.metrics import (
    ATARI_HUMAN_RANDOM, Metrics, human_normalized_score, median_hns)


def suite_games(games: Iterable[str] | None = None,
                shard: tuple[int, int] | None = None) -> tuple[str, ...]:
    out = tuple(games) if games is not None else tuple(
        sorted(ATARI_HUMAN_RANDOM))
    if shard is not None:
        i, n = shard
        if not 0 <= i < n:
            raise ValueError(f"shard {i}/{n} out of range")
        out = out[i::n]
    return out


def _check_mesh_fits(cfg: RunConfig) -> None:
    """Fail BEFORE the first game if the preset's parallel layout needs
    more chips than this host has: `atari57_apex` carries dp=4 x tp=2,
    and without this check a 1-chip host only finds out deep inside
    mesh construction after building envs and networks (round-3
    verdict weak #6)."""
    need = cfg.parallel.dp * cfg.parallel.tp
    if need <= 1:
        return
    import jax

    have = len(jax.devices())
    if need > have:
        raise ValueError(
            f"config wants a dp={cfg.parallel.dp} x tp={cfg.parallel.tp} "
            f"device mesh ({need} chips) but this host has {have} "
            f"device(s). On a single-chip host run with "
            f"--set parallel.dp=1 --set parallel.tp=1, or shard the "
            f"suite across hosts that have the chips (--games-shard).")


def train_one_game(cfg: RunConfig, game: str, game_dir: str,
                   total_env_frames: int | None,
                   max_grad_steps: int,
                   wall_clock_limit_s: float | None) -> dict:
    """One per-game Ape-X run; returns the driver summary + eval."""
    from ape_x_dqn_tpu.runtime.driver import ApexDriver

    os.makedirs(game_dir, exist_ok=True)
    gcfg = cfg.replace(
        env=dataclasses.replace(cfg.env, id=game),
        checkpoint_dir=os.path.join(game_dir, "ckpt"))
    metrics = Metrics(log_path=os.path.join(game_dir, "metrics.jsonl"))
    driver = ApexDriver(gcfg, metrics=metrics)
    out = driver.run(total_env_frames=total_env_frames,
                     max_grad_steps=max_grad_steps,
                     wall_clock_limit_s=wall_clock_limit_s)
    metrics.close()
    # drop this game's jit executables + GC'd device buffers before the
    # next game builds its own: 57 sequential in-process drivers
    # otherwise accumulate compiled graphs until LLVM OOMs mid-suite
    # (observed at game ~43 of the round-4 full pass)
    del driver
    import gc
    import jax
    gc.collect()
    jax.clear_caches()
    return out


def run_suite_training(cfg: RunConfig, out_dir: str,
                       games: Iterable[str] | None = None,
                       shard: tuple[int, int] | None = None,
                       frames_per_game: int | None = None,
                       max_grad_steps_per_game: int = 10**9,
                       wall_clock_limit_s_per_game: float | None = None,
                       resume: bool = True) -> dict:
    """Train + evaluate each game; aggregate the suite metric.

    Requires cfg.eval_episodes > 0 (the per-game score IS the driver's
    final unclipped greedy eval)."""
    from ape_x_dqn_tpu.envs.atari import atari_backend

    if cfg.eval_episodes <= 0:
        raise ValueError(
            "suite training needs cfg.eval_episodes > 0: the per-game "
            "score is the driver's final greedy eval")
    _check_mesh_fits(cfg)
    backend = atari_backend(cfg.env.kind)
    names = suite_games(games, shard)
    os.makedirs(out_dir, exist_ok=True)
    per_game: dict[str, dict] = {}
    for game in names:
        game_dir = os.path.join(out_dir, game)
        result_path = os.path.join(game_dir, "result.json")
        if resume and os.path.exists(result_path):
            with open(result_path) as fh:
                per_game[game] = json.load(fh)
            continue
        out = train_one_game(cfg, game, game_dir, frames_per_game,
                             max_grad_steps_per_game,
                             wall_clock_limit_s_per_game)
        rec = {
            "game": game,
            "backend": backend,
            "frames": out["frames"],
            "grad_steps": out["grad_steps"],
            "wall_s": out["wall_s"],
            "eval": out["eval"],
            "errors": bool(out["actor_errors"] or out["loop_errors"]),
        }
        per_game[game] = rec
        # only CLEAN runs with a real eval become resumable results: a
        # cached errored/eval-less record would be skipped forever (the
        # suite could never complete) and a partial score would
        # silently feed the median. A broken game retrains on resume
        # (its driver checkpoint still carries the progress).
        if not rec["errors"] and rec["eval"] is not None:
            with open(result_path, "w") as fh:
                json.dump(rec, fh)

    agg = _aggregate(names, per_game, shard=shard)
    # a shard writes its own file and NEVER the suite-level suite.json:
    # N shards sharing --out would otherwise overwrite each other with
    # partial aggregates, and a shard's median would masquerade under
    # the suite-level key (round-3 advisor finding). The full suite is
    # aggregated from the per-game result.json files (aggregate_suite /
    # CLI --aggregate-only) once every shard has finished.
    fname = (f"suite.{shard[0]}of{shard[1]}.json" if shard is not None
             else "suite.json")
    with open(os.path.join(out_dir, fname), "w") as fh:
        json.dump(agg, fh)
    return agg


def _aggregate(names: tuple[str, ...], per_game: dict[str, dict],
               shard: tuple[int, int] | None = None) -> dict:
    """Aggregate per-game records into the suite (or shard) summary.

    A sharded aggregate covers only the shard's games, so its median is
    a SHARD median: it is emitted under shard_median_hns[_synthetic]
    and the unqualified suite-level key is refused entirely."""
    clean = {g: r for g, r in per_game.items()
             if not r["errors"] and r.get("eval")}
    scores = {g: r["eval"]["mean_return"] for g, r in clean.items()}
    known = {g: s for g, s in scores.items() if g in ATARI_HUMAN_RANDOM}
    # the median key reflects the PER-GAME backends (resumed results
    # keep the backend they actually ran on): the unmarked north-star
    # key appears only when every aggregated game ran on the real ALE
    all_ale = bool(clean) and all(r["backend"] == "ale"
                                  for r in clean.values())
    agg: dict = {
        "games": list(names),
        "scores": scores,
        "hns": {g: human_normalized_score(g, s)
                for g, s in known.items()},
        "backends": {g: per_game[g]["backend"] for g in per_game},
        "per_game": per_game,
        "complete": len(scores) == len(names),
    }
    key = "median_hns" if all_ale else "median_hns_synthetic"
    if shard is not None:
        agg["shard"] = list(shard)
        key = "shard_" + key
    elif not agg["complete"]:
        # an incomplete aggregate's median covers only the finished
        # games — the same masquerade the shard key-prefix refuses
        key = "partial_" + key
    agg[key] = median_hns(known)
    return agg


def aggregate_suite(out_dir: str,
                    games: Iterable[str] | None = None) -> dict:
    """Build the FULL suite aggregate from per-game result.json files
    (the only shard-safe source of truth — every shard writes those)
    and write <out>/suite.json. Games without a result yet leave
    complete=false."""
    names = suite_games(games)
    per_game: dict[str, dict] = {}
    for game in names:
        path = os.path.join(out_dir, game, "result.json")
        if os.path.exists(path):
            with open(path) as fh:
                per_game[game] = json.load(fh)
    agg = _aggregate(names, per_game)
    with open(os.path.join(out_dir, "suite.json"), "w") as fh:
        json.dump(agg, fh)
    return agg


def main(argv: list[str] | None = None) -> int:
    import argparse

    from ape_x_dqn_tpu.configs import get_config
    from ape_x_dqn_tpu.runtime.train import apply_overrides

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="atari57_apex")
    ap.add_argument("--out", required=True,
                    help="suite output dir (per-game subdirs)")
    ap.add_argument("--games", default=None, metavar="G1,G2,...",
                    help="subset (default: all 57)")
    ap.add_argument("--games-shard", default=None, metavar="I/N",
                    help="train games I, I+N, I+2N, ... of the list "
                         "(fleet parallelism across learner hosts)")
    ap.add_argument("--frames-per-game", type=int, default=None)
    ap.add_argument("--max-grad-steps-per-game", type=int, default=10**9)
    ap.add_argument("--wall-clock-limit-per-game", type=float,
                    default=None)
    ap.add_argument("--no-resume", action="store_true",
                    help="retrain games that already have a result.json")
    ap.add_argument("--aggregate-only", action="store_true",
                    help="skip training: rebuild <out>/suite.json from "
                         "the per-game result.json files (run after "
                         "all --games-shard invocations finish)")
    ap.add_argument("--set", action="append", default=[],
                    metavar="dotted.key=value")
    args = ap.parse_args(argv)
    games = args.games.split(",") if args.games else None
    if args.aggregate_only:
        print(json.dumps(aggregate_suite(args.out, games=games)))
        return 0
    cfg = apply_overrides(get_config(args.config), args.set)
    shard = None
    if args.games_shard:
        i, n = args.games_shard.split("/", 1)
        shard = (int(i), int(n))
    agg = run_suite_training(
        cfg, args.out, games=games, shard=shard,
        frames_per_game=args.frames_per_game,
        max_grad_steps_per_game=args.max_grad_steps_per_game,
        wall_clock_limit_s_per_game=args.wall_clock_limit_per_game,
        resume=not args.no_resume)
    print(json.dumps(agg))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
