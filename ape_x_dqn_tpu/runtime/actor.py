"""Actor runtime (SURVEY.md §2.2 "Actor runtime", §3.1 Actor_i loop).

Each actor steps CPU envs with an eps_i-greedy policy — Horgan et al.
2018: eps_i = base ** (1 + alpha * i / (N-1)) — getting Q-values from
the batched TPU inference server, accumulates n-step returns, computes
INITIAL priorities actor-side (so fresh experience enters the sum-tree
with real TD magnitudes, not a max-priority hack), and ships transition
batches through the transport.

Initial priority bookkeeping: a transition emitted at step t needs
max_a Q(s_{t+n}); the actor has Q(s_t..) from action selection, and
Q(s_{t+n}) arrives at the *next* server query — so non-terminal
transitions park in a one-step pending list. Terminal transitions
(discount 0) and truncation flushes resolve immediately (the latter via
one extra server query on the terminal observation).
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np

from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.ops.nstep import NStepBuilder, NStepTransition


def actor_epsilon(i: int, n: int, base: float = 0.4,
                  alpha: float = 7.0) -> float:
    if n <= 1:
        return base
    return base ** (1.0 + alpha * i / (n - 1))


class Actor:
    def __init__(self, cfg: RunConfig, actor_index: int,
                 query_fn: Callable[[np.ndarray], np.ndarray],
                 transport, seed: int | None = None,
                 episode_callback: Callable[[int, dict], None] | None = None):
        """query_fn(obs) -> q-values [A] (the inference server's .query)."""
        self.cfg = cfg
        self.index = actor_index
        self.query = query_fn
        self.transport = transport
        self.eps = actor_epsilon(actor_index, cfg.actors.num_actors,
                                 cfg.actors.base_eps, cfg.actors.eps_alpha)
        seed = cfg.seed if seed is None else seed
        self.env = make_env(cfg.env, seed=seed * 10_007 + actor_index,
                            actor_index=actor_index)
        self.rng = np.random.default_rng(seed * 7919 + actor_index)
        self.nstep = NStepBuilder(cfg.learner.n_step, cfg.learner.gamma)
        self.episode_callback = episode_callback
        self.frames = 0
        self._outbox: list[tuple[NStepTransition, float]] = []
        self._pending: list[NStepTransition] = []

    # -- priority resolution ----------------------------------------------

    def _resolve_pending(self, q_next: np.ndarray) -> None:
        for t in self._pending:
            target = t.reward + t.discount * float(np.max(q_next))
            self._outbox.append((t, abs(target - float(t.aux))))
        self._pending.clear()

    def _route(self, transitions: list[NStepTransition],
               terminal_obs: np.ndarray | None) -> None:
        v_term: float | None = None
        for t in transitions:
            if t.discount == 0.0:
                self._outbox.append((t, abs(t.reward - float(t.aux))))
            elif terminal_obs is not None:
                # truncation flush: the bootstrap obs won't be queried
                # again, ask the server once for its value
                if v_term is None:
                    v_term = float(np.max(self.query(terminal_obs)))
                target = t.reward + t.discount * v_term
                self._outbox.append((t, abs(target - float(t.aux))))
            else:
                self._pending.append(t)

    def _ship(self, force: bool = False) -> None:
        if not self._outbox:
            return
        if not force and len(self._outbox) < self.cfg.actors.ingest_batch:
            return
        ts = [t for t, _ in self._outbox]
        pris = np.asarray([p for _, p in self._outbox], np.float32)
        batch = {
            "obs": np.stack([t.obs for t in ts]),
            "action": np.asarray([t.action for t in ts], np.int32),
            "reward": np.asarray([t.reward for t in ts], np.float32),
            "next_obs": np.stack([t.next_obs for t in ts]),
            "discount": np.asarray([t.discount for t in ts], np.float32),
            "priorities": pris,
            "actor": self.index,
        }
        self._outbox = []
        self.transport.send_experience(batch)

    # -- main loop ---------------------------------------------------------

    def run(self, max_frames: int,
            stop_event: threading.Event | None = None) -> int:
        obs = self.env.reset()
        while self.frames < max_frames and not (
                stop_event is not None and stop_event.is_set()):
            q = self.query(obs)
            self._resolve_pending(q)
            if self.rng.random() < self.eps:
                action = int(self.rng.integers(self.env.spec.num_actions))
            else:
                action = int(np.argmax(q))
            next_obs, reward, done, info = self.env.step(action)
            self.frames += 1
            terminal = info.get("terminal", done)
            truncated = done and not terminal
            new_ts = self.nstep.append(obs, action, reward, next_obs,
                                       terminal, truncated,
                                       aux=float(q[action]))
            self._route(new_ts, terminal_obs=next_obs if truncated else None)
            if done:
                obs = self.env.reset()
                if self.episode_callback and "episode_return" in info:
                    self.episode_callback(self.index, info)
            else:
                obs = next_obs
            self._ship()
        # resolve parked transitions (waiting on Q(s_{t+n}), which would
        # have arrived at the next action query) with one final forward so
        # they aren't dropped at shutdown
        if self._pending:
            try:
                self._resolve_pending(self.query(obs))
            except Exception:
                self._pending.clear()  # server already down: drop, don't die
        self._ship(force=True)
        return self.frames
