"""Actor runtime (SURVEY.md §2.2 "Actor runtime", §3.1 Actor_i loop).

Each actor steps CPU envs with an eps_i-greedy policy — Horgan et al.
2018: eps_i = base ** (1 + alpha * i / (N-1)) — getting Q-values from
the batched TPU inference server, accumulates n-step returns, computes
INITIAL priorities actor-side (so fresh experience enters the sum-tree
with real TD magnitudes, not a max-priority hack), and ships transition
batches through the transport.

Initial priority bookkeeping: a transition emitted at step t needs
max_a Q(s_{t+n}); the actor has Q(s_t..) from action selection, and
Q(s_{t+n}) arrives at the *next* server query — so non-terminal
transitions park in a one-step pending list. Terminal transitions
(discount 0) and truncation flushes resolve immediately (the latter via
one extra server query on the terminal observation).
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np

from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.obs.core import NULL_OBS
from ape_x_dqn_tpu.ops.nstep import NStepBuilder, NStepTransition
from ape_x_dqn_tpu.replay.frame_ring import FrameSegmentBuilder
from ape_x_dqn_tpu.replay.sequence import (
    SequenceBuilder, split_priorities, stack_items)


def actor_epsilon(i: int, n: int, base: float = 0.4,
                  alpha: float = 7.0) -> float:
    if n <= 1:
        return base
    return base ** (1.0 + alpha * i / (n - 1))


def flat_transition_batch(ts: list[NStepTransition], pris: np.ndarray,
                          actions: np.ndarray, actor_index: int,
                          frames: int) -> dict:
    """The wire format for a batch of flat n-step transitions — one
    schema for the scalar and vector actors (the ingest staging and
    transition_item_spec depend on these exact keys)."""
    return {
        "obs": np.stack([t.obs for t in ts]),
        "action": actions,
        "reward": np.asarray([t.reward for t in ts], np.float32),
        "next_obs": np.stack([t.next_obs for t in ts]),
        "discount": np.asarray([t.discount for t in ts], np.float32),
        "priorities": pris,
        "actor": actor_index,
        "frames": frames,
    }


def sequence_ship_after(cfg: RunConfig) -> int:
    """Sequences per shipment: ingest_batch counts TRANSITIONS, so
    sequences ship in proportionally smaller groups to keep ingest
    latency comparable (shared by the scalar and vector recurrent
    actors)."""
    return max(1, cfg.actors.ingest_batch // cfg.replay.seq_length)


def feed_sequence(outbox: list, builder, rec: dict, td: float) -> None:
    """Append one recurrent step record to a SequenceBuilder, routing
    any completed sequence items into the outbox — the record schema
    (obs/action/reward/terminal/pre_state/episode_end) is shared by
    the scalar and vector recurrent actors."""
    outbox.extend(builder.append(
        rec["obs"], rec["action"], rec["reward"], rec["terminal"],
        rec["pre_state"], td=td, episode_end=rec["episode_end"]))


def ship_sequence_outbox(outbox: list, actor_index: int, frames: int,
                         transport) -> None:
    """Stack an outbox of sequence items into the wire batch and send
    it — the sequence shipping tail shared by the scalar and vector
    recurrent actors (one schema; sequence_item_spec depends on it)."""
    items, pris = split_priorities(outbox)
    batch = stack_items(items)
    batch["priorities"] = pris
    batch["actor"] = actor_index
    batch["frames"] = frames
    transport.send_experience(batch)


class DiscretePolicyHooks:
    """Eps-greedy Q-policy hooks shared by the scalar and vector
    discrete actors. Host class provides self.spec and self.rng.

    Hooks: `_select_action` (policy out + eps -> action),
    `_bootstrap_value` (policy out -> V(s) estimate for n-step
    targets), `_taken_value` (policy out + action -> the value whose TD
    error seeds the initial priority), `_action_array` (stacking dtype
    for shipment)."""

    def _select_action(self, out, eps: float):
        if self.rng.random() < eps:
            return int(self.rng.integers(self.spec.num_actions))
        return int(np.argmax(out))

    def _bootstrap_value(self, out) -> float:
        return float(np.max(out))

    def _taken_value(self, out, action) -> float:
        return float(out[action])

    def _action_array(self, ts: list[NStepTransition]) -> np.ndarray:
        return np.asarray([t.action for t in ts], np.int32)


def resolve_pending(pending: list[NStepTransition], v_next: float,
                    queue_fn: Callable[[NStepTransition, float], None]
                    ) -> None:
    """Resolve parked transitions with the just-arrived bootstrap value
    (max_a Q of each transition's next_obs): their initial priority is
    the |TD| against the value the actor stashed at selection time.
    One implementation for the scalar and vector actors — the initial-
    priority math must never diverge between them."""
    for t in pending:
        target = t.reward + t.discount * v_next
        queue_fn(t, abs(target - float(t.aux)))
    pending.clear()


def ship_flat_outbox(outbox: list[tuple[NStepTransition, float]],
                     action_array: Callable, actor_index: int,
                     frames: int, transport) -> None:
    """Stack an outbox of (transition, priority) into the flat wire
    batch and send it — the shipping tail shared by the scalar and
    vector actors."""
    ts = [t for t, _ in outbox]
    pris = np.asarray([p for _, p in outbox], np.float32)
    transport.send_experience(flat_transition_batch(
        ts, pris, action_array(ts), actor_index, frames))


class ContinuousPolicyHooks:
    """Ape-X DPG policy hooks shared by the scalar and vector actors:
    deterministic mu(s) + Gaussian exploration noise (Horgan et al.
    2018 "Ape-X DPG"), with initial priorities seeded from the critic's
    Q(s, mu(s)). Host class provides self.spec, self.rng, and calls
    _init_noise(cfg) after self.spec exists."""

    def _init_noise(self, cfg: RunConfig) -> None:
        self._noise_scale = (cfg.actors.noise_sigma
                             * (self.spec.action_high
                                - self.spec.action_low) / 2.0)

    def _select_action(self, out, eps: float):
        # eps is unused: continuous exploration is additive noise
        noise = self.rng.normal(0.0, self._noise_scale,
                                size=self.spec.action_dim)
        return np.clip(np.asarray(out["a"], np.float32) + noise,
                       self.spec.action_low,
                       self.spec.action_high).astype(np.float32)

    def _bootstrap_value(self, out) -> float:
        return float(out["q"])

    def _taken_value(self, out, action) -> float:
        # Q(s, mu(s)) stands in for Q(s, a_taken): the noise
        # perturbation is small, and this only seeds initial priority
        return float(out["q"])

    def _action_array(self, ts: list[NStepTransition]) -> np.ndarray:
        return np.stack([np.asarray(t.action, np.float32) for t in ts])


class Actor(DiscretePolicyHooks):
    """Discrete eps_i-greedy actor; also the base for ContinuousActor
    (which overrides the policy hooks via ContinuousPolicyHooks)."""

    _ships_frame_segments = True  # flat family only (see __init__)

    def __init__(self, cfg: RunConfig, actor_index: int,
                 query_fn: Callable[[np.ndarray], np.ndarray],
                 transport, seed: int | None = None,
                 episode_callback: Callable[[int, dict], None] | None = None,
                 obs: object | None = None):
        """query_fn(obs) -> q-values [A] (the inference server's .query).
        obs: optional obs.core.Obs facade — inference/env-step spans +
        the actor-{i} heartbeat (NULL_OBS when omitted)."""
        self.cfg = cfg
        self.index = actor_index
        self.query = query_fn
        self.transport = transport
        self.obs = obs if obs is not None else NULL_OBS
        self._hb = f"actor-{actor_index}"
        self.eps = actor_epsilon(actor_index, cfg.actors.num_actors,
                                 cfg.actors.base_eps, cfg.actors.eps_alpha)
        seed = cfg.seed if seed is None else seed
        self.env = make_env(cfg.env, seed=seed * 10_007 + actor_index,
                            actor_index=actor_index)
        self.spec = self.env.spec
        self.rng = np.random.default_rng(seed * 7919 + actor_index)
        self.nstep = NStepBuilder(cfg.learner.n_step, cfg.learner.gamma)
        self.episode_callback = episode_callback
        self.frames = 0
        self._frames_unshipped = 0
        self._outbox: list[tuple[NStepTransition, float]] = []
        self._pending: list[NStepTransition] = []
        # frame-ring shipping (replay/frame_ring.py): transitions leave as
        # fixed segments of single frames instead of stacked obs pairs.
        # Only the flat family ships segments — RecurrentActor handles
        # frame-mode inside its SequenceBuilder instead.
        self._seg: FrameSegmentBuilder | None = None
        if (self._ships_frame_segments
                and getattr(cfg.replay, "storage", "flat") == "frame_ring"):
            spec = self.env.spec
            assert spec.discrete and len(spec.obs_shape) == 3, \
                "frame_ring storage needs discrete [H, W, stack] pixel envs"
            self._seg = FrameSegmentBuilder(
                cfg.replay.seg_transitions, cfg.learner.n_step,
                stack=spec.obs_shape[-1])

    # -- priority resolution ----------------------------------------------

    def _queue(self, t: NStepTransition, priority: float) -> None:
        """A transition's initial priority is resolved: hand it to the
        shipping pipeline. Callers always queue in start-step order (the
        pending list drains before any newer transition routes), which
        the frame-segment builder relies on."""
        if self._seg is not None:
            self._seg.add(t.action, t.reward, t.discount, t.span, priority)
        else:
            self._outbox.append((t, priority))

    def _resolve_pending(self, out) -> None:
        resolve_pending(self._pending, self._bootstrap_value(out),
                        self._queue)

    def _route(self, transitions: list[NStepTransition],
               terminal_obs: np.ndarray | None) -> None:
        v_term: float | None = None
        for t in transitions:
            if t.discount == 0.0:
                self._queue(t, abs(t.reward - float(t.aux)))
            elif terminal_obs is not None:
                # truncation flush: the bootstrap obs won't be queried
                # again, ask the server once for its value
                if v_term is None:
                    v_term = self._bootstrap_value(self.query(terminal_obs))
                target = t.reward + t.discount * v_term
                self._queue(t, abs(target - float(t.aux)))
            else:
                self._pending.append(t)

    def _ship_segments(self, force: bool = False) -> None:
        segs = self._seg.flush() if force else self._seg.take_ready()
        for seg in segs:
            seg["actor"] = self.index
            # env-frame accounting rides the first segment of the batch
            seg["frames"] = self._frames_unshipped
            self._frames_unshipped = 0
            self.transport.send_experience(seg)
        if segs:
            self.obs.mark("actor.ship", segments=len(segs))

    def _ship(self, force: bool = False) -> None:
        if self._seg is not None:
            self._ship_segments(force)
            return
        if not self._outbox:
            return
        if not force and len(self._outbox) < self.cfg.actors.ingest_batch:
            return
        rows = len(self._outbox)
        ship_flat_outbox(self._outbox, self._action_array, self.index,
                         self._frames_unshipped, self.transport)
        self._outbox = []
        self._frames_unshipped = 0
        self.obs.mark("actor.ship", rows=rows)

    # -- main loop ---------------------------------------------------------

    def run(self, max_frames: int,
            stop_event: threading.Event | None = None) -> int:
        obs = self.env.reset()
        if self._seg is not None:
            self._seg.on_reset(obs)
        while self.frames < max_frames and not (
                stop_event is not None and stop_event.is_set()):
            self.obs.beat(self._hb)
            with self.obs.span("actor.inference"):
                out = self.query(obs)
            self._resolve_pending(out)
            action = self._select_action(out, self.eps)
            with self.obs.span("actor.env_step"):
                next_obs, reward, done, info = self.env.step(action)
            self.frames += 1
            self._frames_unshipped += 1
            if self._seg is not None:
                self._seg.on_step(next_obs)
            terminal = info.get("terminal", done)
            truncated = done and not terminal
            new_ts = self.nstep.append(obs, action, reward, next_obs,
                                       terminal, truncated,
                                       aux=self._taken_value(out, action))
            self._route(new_ts, terminal_obs=next_obs if truncated else None)
            if done:
                obs = self.env.reset()
                if self._seg is not None:
                    # flushes the open partial segment first: segments
                    # never span episodes
                    self._seg.on_reset(obs)
                if self.episode_callback and "episode_return" in info:
                    self.episode_callback(self.index, info)
            else:
                obs = next_obs
            self._ship()
        # resolve parked transitions (waiting on Q(s_{t+n}), which would
        # have arrived at the next action query) with one final forward so
        # they aren't dropped at shutdown
        if self._pending:
            try:
                self._resolve_pending(self.query(obs))
            except Exception:
                self._pending.clear()  # server already down: drop, don't die
        self._ship(force=True)
        return self.frames


class ContinuousActor(ContinuousPolicyHooks, Actor):
    """Ape-X DPG actor: deterministic policy + Gaussian exploration noise.

    Horgan et al. 2018 "Ape-X DPG" (SURVEY.md §2.1 config 5): actions are
    mu(s) + N(0, sigma^2) clipped to the action box, with sigma from
    ActorConfig.noise_sigma (scaled by the box half-range). The inference
    server evaluates both the policy and the critic in one batched
    forward — {"a": mu(s), "q": Q(s, mu(s))} — so actors compute initial
    priorities from the critic's value estimates exactly like discrete
    actors do from max-Q (same one-step pending mechanism). Policy hooks
    live in ContinuousPolicyHooks (shared with ContinuousVectorActor).
    """

    _ships_frame_segments = False  # DPG obs are low-dimensional

    def __init__(self, cfg: RunConfig, actor_index: int,
                 query_fn: Callable[[np.ndarray], dict],
                 transport, seed: int | None = None,
                 episode_callback: Callable[[int, dict], None] | None = None,
                 obs: object | None = None):
        super().__init__(cfg, actor_index, query_fn, transport, seed=seed,
                         episode_callback=episode_callback, obs=obs)
        self._init_noise(cfg)


class RecurrentActor(Actor):
    """R2D2 actor: carries LSTM state, ships stored-state sequences.

    Shares Actor's construction scaffolding (epsilon schedule, env/rng
    seeding, frame accounting) but replaces the flat n-step pipeline with
    a SequenceBuilder and a stateful run loop.

    The recurrent (c, h) rides the inference server's generic request
    pytree (parallel/inference_server.py): each query sends
    {"obs", "c", "h"} and gets {"q", "c", "h"} back, so the batched TPU
    forward serves many actors' recurrent steps at once (SURVEY.md §3.2).

    Initial sequence priorities are computed actor-side from 1-step TD
    estimates (the n-step-in-sequence TD is the learner's job; the 1-step
    |TD| eta-mix is the same fresh-experience signal at a fraction of the
    bookkeeping). A step's TD needs max_a Q(s_{t+1}), which arrives at
    the *next* server query — so each step parks for one iteration before
    entering the SequenceBuilder (mirroring Actor's pending list).

    Frame-mode shipping (replay storage "frame_ring") happens inside the
    SequenceBuilder (single frames per sequence), not via Actor's
    flat-transition segment path.
    """

    _ships_frame_segments = False

    def __init__(self, cfg: RunConfig, actor_index: int,
                 query_fn: Callable[[dict], dict],
                 transport, seed: int | None = None,
                 episode_callback: Callable[[int, dict], None] | None = None,
                 obs: object | None = None):
        super().__init__(cfg, actor_index, query_fn, transport, seed=seed,
                         episode_callback=episode_callback, obs=obs)
        self.gamma = cfg.learner.gamma
        self.lstm_size = cfg.network.lstm_size
        frame_mode = cfg.replay.storage == "frame_ring"
        if frame_mode:
            assert len(self.env.spec.obs_shape) == 3, \
                "frame_ring sequence storage needs [H, W, stack] pixel obs"
        self.builder = SequenceBuilder(
            seq_len=cfg.replay.seq_length, overlap=cfg.replay.seq_overlap,
            lstm_size=self.lstm_size, priority_eta=cfg.replay.priority_eta,
            frame_mode=frame_mode)
        self.ship_after = sequence_ship_after(cfg)
        self._outbox: list[dict] = []  # sequence items, not transitions

    def _zero_state(self) -> tuple[np.ndarray, np.ndarray]:
        z = np.zeros(self.lstm_size, np.float32)
        return z, z.copy()

    def _feed(self, rec: dict, td: float) -> None:
        feed_sequence(self._outbox, self.builder, rec, td)

    def _ship(self, force: bool = False) -> None:
        if not self._outbox:
            return
        if not force and len(self._outbox) < self.ship_after:
            return
        rows = len(self._outbox)
        ship_sequence_outbox(self._outbox, self.index,
                             self._frames_unshipped, self.transport)
        self._outbox = []
        self._frames_unshipped = 0
        self.obs.mark("actor.ship", sequences=rows)

    # -- main loop ---------------------------------------------------------

    def run(self, max_frames: int,
            stop_event: threading.Event | None = None) -> int:
        obs = self.env.reset()
        c, h = self._zero_state()
        prev: dict | None = None  # step awaiting its 1-step TD bootstrap
        while self.frames < max_frames and not (
                stop_event is not None and stop_event.is_set()):
            self.obs.beat(self._hb)
            with self.obs.span("actor.inference"):
                out = self.query({"obs": obs, "c": c, "h": h})
            q = out["q"]
            if prev is not None:
                td = (prev["reward"] + self.gamma * float(np.max(q))
                      - prev["q_sa"])
                self._feed(prev, td)
                prev = None
            if self.rng.random() < self.eps:
                action = int(self.rng.integers(self.env.spec.num_actions))
            else:
                action = int(np.argmax(q))
            next_obs, reward, done, info = self.env.step(action)
            self.frames += 1
            self._frames_unshipped += 1
            terminal = info.get("terminal", done)
            rec = dict(obs=obs, action=action, reward=float(reward),
                       terminal=terminal, pre_state=(c, h),
                       q_sa=float(q[action]), episode_end=done)
            if terminal:
                # bootstrap is zero: the TD is fully determined now
                self._feed(rec, rec["reward"] - rec["q_sa"])
            elif done:
                # truncation: the sequence ends (state resets) but the
                # bootstrap survives — one extra query on the final obs
                out2 = self.query({"obs": next_obs,
                                   "c": out["c"], "h": out["h"]})
                td = (reward + self.gamma * float(np.max(out2["q"]))
                      - rec["q_sa"])
                self._feed(rec, td)
            else:
                prev = rec
            if done:
                obs = self.env.reset()
                c, h = self._zero_state()
                if self.episode_callback and "episode_return" in info:
                    self.episode_callback(self.index, info)
            else:
                obs = next_obs
                c, h = out["c"], out["h"]
            self._ship()
        # shutdown: resolve the parked step with one final forward, flush
        # the builder's partial tail, and ship everything
        if prev is not None:
            try:
                out = self.query({"obs": obs, "c": c, "h": h})
                td = (prev["reward"] + self.gamma * float(np.max(out["q"]))
                      - prev["q_sa"])
            except Exception:
                td = prev["reward"] - prev["q_sa"]
            prev["episode_end"] = False
            self._feed(prev, td)
        self._outbox.extend(self.builder.flush())
        self._ship(force=True)
        return self.frames
