"""Single-process training driver — the reference's CPU smoke path.

Config 1 (SURVEY.md §3.5): one env, one net, device-resident replay, and
the single-jit learner, all in one process with no transport. This is
both the minimum end-to-end slice and the correctness oracle (CartPole
must reach >= 475 average return).

Works for any flat-transition discrete config (CartPole MLP, synthetic
Atari CNN) — the distributed runtime (runtime/driver.py) reuses the same
learner and replay, swapping the in-process env loop for actor processes.
"""

from __future__ import annotations

import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.models import build_network
from ape_x_dqn_tpu.obs.core import build_obs
from ape_x_dqn_tpu.ops.nstep import NStepBuilder
from ape_x_dqn_tpu.replay.prioritized import (
    PrioritizedReplay, UniformReplayDevice)
from ape_x_dqn_tpu.runtime.learner import (
    DQNLearner, transition_item_spec)
from ape_x_dqn_tpu.utils.metrics import Metrics, log_run_header
from ape_x_dqn_tpu.utils.misc import next_pow2
from ape_x_dqn_tpu.utils.rng import RngStream, component_key


def build_replay(rcfg):
    cap = next_pow2(rcfg.capacity)
    if rcfg.kind == "uniform":
        return UniformReplayDevice(capacity=cap)
    return PrioritizedReplay(capacity=cap, alpha=rcfg.alpha, beta=rcfg.beta,
                             eps=rcfg.eps)


def train_single_process(cfg: RunConfig, total_env_frames: int | None = None,
                         metrics: Metrics | None = None,
                         solve_return: float | None = None,
                         train_every: int = 1,
                         flush_every: int = 32) -> dict:
    """Run config-1-style training; returns summary stats."""
    total = total_env_frames or cfg.total_env_frames
    metrics = metrics or Metrics()
    log_run_header(metrics, cfg)
    # `obs` is this loop's env observation; the observability facade
    # rides as `obs_` (NULL_OBS when cfg.obs is absent/disabled)
    obs_ = build_obs(getattr(cfg, "obs", None), metrics)
    # crash hooks (obs/blackbox.py): uninstalled again by obs_.close(),
    # so a healthy run leaves no dump behind
    obs_.blackbox.install()
    obs_.register("actor-0")
    obs_.register("learner")
    env = make_env(cfg.env, seed=cfg.seed)
    net = build_network(cfg.network, env.spec)

    obs = env.reset()
    params = net.init(component_key(cfg.seed, "net_init"), obs[None])
    fwd = jax.jit(net.apply)

    replay = build_replay(cfg.replay)
    # host mirror of the ring's skip-to-head write cursor: maps sampled
    # slot indices back to the grad-step they were written at (None
    # when obs is disabled)
    age_tracker = obs_.age_tracker(next_pow2(cfg.replay.capacity))
    item_spec = transition_item_spec(env.spec.obs_shape,
                                     env.spec.obs_dtype)
    learner = DQNLearner(net.apply, replay, cfg.learner)
    state = learner.init(params, replay.init(item_spec),
                         component_key(cfg.seed, "learner"))

    nstep = NStepBuilder(cfg.learner.n_step, cfg.learner.gamma)
    actor_rng = np.random.default_rng(
        RngStream(cfg.seed, "actor_host").next_uint32())

    pending: list = []
    returns: deque[float] = deque(maxlen=100)
    losses: deque[float] = deque(maxlen=100)
    frames = 0
    grad_steps = 0
    # K-batch relaxation: bank K training opportunities, then one
    # train_many(K) macro-dispatch — same grad-steps-per-frame as the
    # exact path, routed through _train_step_k (learning-parity e2e:
    # tests/test_e2e_catch.py::test_cnn_learns_catch_kbatch)
    sample_chunk = max(getattr(cfg.learner, "sample_chunk", 1), 1)
    train_bank = 0
    # Double-buffered sampling (LearnerConfig.sample_prefetch): the
    # host keeps ONE macro-step's sample in flight — each macro
    # opportunity first dispatches sample_k against the CURRENT tree,
    # then learn_k on the sample drawn at the PREVIOUS opportunity, so
    # the descent/gather dispatch can overlap the previous dispatch's
    # SGD work on device. The pending sample's priorities (and, after
    # interleaved adds, even its slots) may be one round stale — the
    # async-replay semantics the reference's host-side sampler always
    # has, parity-tested on the catch e2e
    # (tests/test_e2e_catch.py::test_cnn_learns_catch_prefetch).
    sample_prefetch = (sample_chunk > 1
                       and getattr(cfg.learner, "sample_prefetch", False))
    pending_sample = None
    eps_final = 0.05
    eps_decay_frames = max(total // 10, 1_000)

    def flush():
        nonlocal pending, state
        if not pending:
            return
        with obs_.span("replay.add", n=len(pending)):
            items = {
                "obs": jnp.asarray(np.stack([t.obs for t in pending])),
                "action": jnp.asarray([t.action for t in pending],
                                      jnp.int32),
                "reward": jnp.asarray([t.reward for t in pending],
                                      jnp.float32),
                "next_obs": jnp.asarray(
                    np.stack([t.next_obs for t in pending])),
                "discount": jnp.asarray([t.discount for t in pending],
                                        jnp.float32),
            }
            state = learner.add(state, items, jnp.ones(len(pending)))
        if age_tracker is not None:
            age_tracker.on_add(len(pending), grad_steps)
        obs_.count("replay_adds", len(pending))
        pending = []

    def traced_train(k: int):
        """Observed macro-step: the split sample_k/learn_k dispatch
        (parity-tested against train_step/_k in PR 1) so the tracer
        sees replay.sample and learner.learn as real host spans —
        block_until_ready inside each span keeps the timing honest
        against jax's async dispatch. Priority write-back and target
        sync are fused inside the learn jit, so they ride as marks."""
        nonlocal state
        # roofline attribution (obs/profiling.py): AOT lower/compile of
        # the exact dispatch signature captures cost_analysis FLOP/byte
        # roofs AND populates the jit call cache, so the timed call
        # below compiles nothing extra. First observed macro-step only.
        if not obs_.stage_attached("sample_k"):
            obs_.stage_attach(
                "sample_k", k, compile_fn=lambda: type(learner).sample_k
                .lower(learner, state, k).compile())
        with obs_.stage_window("sample_k", k):
            with obs_.span("replay.sample", k=k):
                sample, rng2 = learner.sample_k(state, k)
                jax.block_until_ready(sample)
        if age_tracker is not None:
            obs_.observe_sample_ages(
                age_tracker.ages(np.asarray(sample[1]), grad_steps))
        if not obs_.stage_attached("learn_k"):
            obs_.stage_attach(
                "learn_k", k, compile_fn=lambda: type(learner).learn_k
                .lower(learner, state._replace(rng=rng2), sample, k)
                .compile())
        with obs_.stage_window("learn_k", k):
            with obs_.span("learner.learn", k=k):
                state, m = learner.learn_k(state._replace(rng=rng2),
                                           sample, k)
                m = jax.block_until_ready(m)
        obs_.mark("replay.priority_update", fused_into="learner.learn")
        sync = cfg.learner.target_sync_every
        if grad_steps // sync != (grad_steps + k) // sync:
            obs_.mark("learner.target_sync", fused_into="learner.learn")
        obs_.observe("td_abs", float(m["td_abs_mean"]))
        # the acting policy reads state.params directly — lag is truly 0
        obs_.observe("param_lag_steps", 0)
        return m

    pub_every = max(getattr(getattr(cfg, "obs", None),
                            "publish_every_steps", 500) or 500, 1)
    # publish-boundary rate window for the perf-regression engine
    rate_t = time.monotonic()
    rate_frames = 0
    rate_steps = 0
    while frames < total:
        obs_.beat("actor-0", f"frame {frames}")
        eps = max(eps_final, 1.0 - (1.0 - eps_final) * frames
                  / eps_decay_frames)
        with obs_.span("actor.step"):
            if actor_rng.random() < eps:
                action = int(actor_rng.integers(env.spec.num_actions))
            else:
                with obs_.span("actor.inference"):
                    q = fwd(state.params, obs[None])
                action = int(jnp.argmax(q[0]))
            next_obs, reward, done, info = env.step(action)
        frames += 1
        truncated = done and not info.get("terminal", done)
        pending.extend(nstep.append(obs, action, reward, next_obs,
                                    info.get("terminal", done), truncated))
        obs = env.reset() if done else next_obs
        if done and "episode_return" in info:
            returns.append(info["episode_return"])

        if len(pending) >= flush_every:
            flush()

        if (int(state.replay.size) + len(pending) >= cfg.replay.min_fill
                and frames % train_every == 0):
            flush()
            prev_grad_steps = grad_steps
            m = None
            if sample_chunk > 1:
                # bank K training opportunities, then one K-batch
                # macro-dispatch (<=K-1 banked opportunities evaporate
                # at loop end — same grad/frame ratio, harmless)
                train_bank += 1
                if train_bank >= sample_chunk:
                    train_bank = 0
                    if obs_.enabled:
                        # observed runs take the split dispatch so the
                        # sample/learn stages are separately timeable;
                        # the prefetch overlap is deliberately broken
                        # here — honest stage timing needs the sync
                        m = traced_train(sample_chunk)
                    elif sample_prefetch:
                        if pending_sample is None:  # pipeline prologue
                            pending_sample, rng2 = learner.sample_k(
                                state, sample_chunk)
                            state = state._replace(rng=rng2)
                        nxt, rng2 = learner.sample_k(state, sample_chunk)
                        state, m = learner.learn_k(
                            state._replace(rng=rng2), pending_sample,
                            sample_chunk)
                        pending_sample = nxt
                    else:
                        state, m = learner.train_step_k(state,
                                                        sample_chunk)
                    grad_steps += sample_chunk
            else:
                if obs_.enabled:
                    m = traced_train(1)
                else:
                    state, m = learner.train_step(state)
                grad_steps += 1
            if m is not None:
                obs_.beat("learner", f"grad_step {grad_steps}")
                obs_.maybe_profile(grad_steps)
                losses.append(float(m["loss"]))
                # boundary CROSSING, not equality: K-sized increments
                # would otherwise only hit exact multiples at lcm(K, 500)
                if prev_grad_steps // 500 != grad_steps // 500:
                    metrics.log(grad_steps, frames=frames,
                                loss=float(m["loss"]),
                                q_mean=float(m["q_mean"]),
                                avg_return=(float(np.mean(returns))
                                            if returns else 0.0),
                                eps=eps)
                if prev_grad_steps // pub_every != \
                        grad_steps // pub_every:
                    obs_.gauge("replay_occupancy",
                               int(state.replay.size))
                    if obs_.enabled and "diag" in m:
                        # learning-health plane: observed runs go
                        # through traced_train, which already
                        # block_until_ready'd m — no extra sync here
                        obs_.learn_health(
                            m["diag"], float(m["loss"]),
                            step=grad_steps, tenant=cfg.env.id)
                    now = time.monotonic()
                    if now > rate_t:
                        dt = now - rate_t
                        obs_.perf_rate("grad_steps_per_s",
                                       (grad_steps - rate_steps) / dt,
                                       step=grad_steps)
                        obs_.perf_rate("env_fps",
                                       (frames - rate_frames) / dt,
                                       step=grad_steps)
                    rate_t, rate_frames, rate_steps = \
                        now, frames, grad_steps
                    obs_.publish(grad_steps)
        obs_.check_stalled()
        if (solve_return is not None and len(returns) >= 20
                and np.mean(list(returns)[-20:]) >= solve_return):
            break

    # final snapshot + trace flush (the stall path closes inside
    # check_stalled before raising, so both exits produce artifacts)
    obs_.gauge("replay_occupancy", int(state.replay.size))
    obs_.close(grad_steps)
    return {
        "frames": frames,
        "grad_steps": grad_steps,
        "avg_return": float(np.mean(returns)) if returns else 0.0,
        "last20_return": (float(np.mean(list(returns)[-20:]))
                          if len(returns) >= 1 else 0.0),
        "episodes": len(returns),
        "final_loss": float(np.mean(losses)) if losses else float("nan"),
    }
