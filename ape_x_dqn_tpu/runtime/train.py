"""CLI entry point: launch any of the five configs from a shell.

SURVEY.md §1 layer 7 / §2.1: the reference ships per-config training
entry points; here one CLI selects a preset and overrides any field:

    python -m ape_x_dqn_tpu.runtime.train --config pong --actors 8 \
        --total-env-frames 1000000 --metrics-file run.jsonl
    python -m ape_x_dqn_tpu.runtime.train --config cartpole_smoke \
        --single-process --set learner.lr=5e-4

`--listen HOST:PORT` additionally accepts remote actor hosts
(runtime/actor_host.py) over the socket transport while local actors
(if any) keep running — the single-machine and multi-host topologies
share this entry point.

Prints one summary JSON line on stdout when the run ends.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import sys
from typing import Any

from ape_x_dqn_tpu.configs import PRESETS, RunConfig, get_config
from ape_x_dqn_tpu.utils.metrics import Metrics


def _coerce(value: str, ref: Any) -> Any:
    """Parse a CLI string against the type of the value it replaces."""
    if value.lower() in ("none", "null"):
        return None  # optional fields can be cleared from the CLI
    if isinstance(ref, bool):  # before int: bool is an int subclass
        if value.lower() in ("1", "true", "yes", "on"):
            return True
        if value.lower() in ("0", "false", "no", "off"):
            return False
        raise ValueError(f"not a bool: {value!r}")
    if isinstance(ref, int):
        return int(value)
    if isinstance(ref, float):
        return float(value)
    if isinstance(ref, tuple):
        parsed = ast.literal_eval(value)
        return tuple(parsed) if isinstance(parsed, (list, tuple)) \
            else (parsed,)
    if ref is None:
        # the current value carries no type (e.g. `float | None` fields
        # like learner.steps_per_frame_cap): parse the literal itself, so
        # `--set learner.steps_per_frame_cap=1.0` lands as a float and
        # not the string '1.0' (which the learner loop would crash on)
        try:
            return ast.literal_eval(value)
        except (ValueError, SyntaxError):
            return value
    return value  # str fields


def _set_dotted(cfg: Any, path: list[str], value: str) -> Any:
    field_names = {f.name for f in dataclasses.fields(cfg)}
    head = path[0]
    if head not in field_names:
        raise KeyError(
            f"unknown config field {head!r}; known: {sorted(field_names)}")
    current = getattr(cfg, head)
    if len(path) == 1:
        return dataclasses.replace(cfg, **{head: _coerce(value, current)})
    return dataclasses.replace(
        cfg, **{head: _set_dotted(current, path[1:], value)})


def apply_overrides(cfg: RunConfig, sets: list[str]) -> RunConfig:
    """Apply 'dotted.path=value' overrides onto a (frozen) RunConfig."""
    for item in sets:
        if "=" not in item:
            raise ValueError(f"--set expects key=value, got {item!r}")
        key, value = item.split("=", 1)
        cfg = _set_dotted(cfg, key.split("."), value)
    return cfg


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m ape_x_dqn_tpu.runtime.train",
        description="Train any Ape-X config on TPU.")
    ap.add_argument("--config", required=True, choices=sorted(PRESETS),
                    help="preset name (SURVEY.md §2.1)")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--actors", type=int, default=None,
                    help="override actors.num_actors")
    ap.add_argument("--total-env-frames", type=int, default=None)
    ap.add_argument("--max-grad-steps", type=int, default=10**9)
    ap.add_argument("--wall-clock-limit", type=float, default=None,
                    metavar="SECONDS")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--profile-dir", default=None,
                    help="capture a JAX profiler trace of the learner "
                         "hot loop into this directory")
    ap.add_argument("--metrics-file", default=None,
                    help="JSONL metrics sink")
    ap.add_argument("--tensorboard-dir", default=None,
                    help="also write TensorBoard event files here "
                         "(JSONL stays canonical; needs torch or "
                         "tensorboardX for the writer)")
    ap.add_argument("--eval-only", action="store_true",
                    help="no training: restore the latest checkpoint and "
                         "run greedy eval (the full HNS suite for Atari "
                         "configs); prints one JSON line")
    ap.add_argument("--games", default=None, metavar="G1,G2,...",
                    help="with --eval-only: comma-separated ALE games "
                         "(default: all 57)")
    ap.add_argument("--single-process", action="store_true",
                    help="config-1 style in-process loop (no threads)")
    ap.add_argument("--param-wire-dtype", default="bfloat16",
                    choices=("bfloat16", "float32"),
                    help="dtype for float params on the DCN wire with "
                         "--listen: bf16 halves the weight-broadcast "
                         "bytes (receivers upcast; values carry bf16 "
                         "rounding only); float32 is bit-exact")
    ap.add_argument("--listen", default=None, metavar="HOST:PORT",
                    help="also accept remote actor hosts over TCP")
    # multi-host learner (one process per host, SPMD lockstep over a
    # global mesh — runtime/multihost_driver.py); all three must be set
    ap.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                    help="jax.distributed coordinator address")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--compilation-cache-dir", default=None,
                    metavar="DIR",
                    help="persistent XLA compilation cache: the hot "
                         "jits compile once per (shape, topology) and "
                         "every later run/restart/resume loads them in "
                         "milliseconds instead of 20-40s per graph")
    ap.add_argument("--set", action="append", default=[],
                    metavar="dotted.key=value",
                    help="override any config field, e.g. "
                         "learner.batch_size=256 (repeatable)")
    return ap


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.compilation_cache_dir:
        # must be set before any backend compiles; resumed/preempted
        # runs then skip straight past the warmup compiles
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          args.compilation_cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
    if args.coordinator is not None:
        if args.num_processes is None or args.process_id is None:
            parser.error("--coordinator requires --num-processes and "
                         "--process-id")
        if args.wall_clock_limit is not None:
            # wall clocks differ across hosts (they would diverge the
            # lockstep call sequences) — reject rather than silently
            # ignore
            parser.error("--wall-clock-limit is not supported in "
                         "multihost mode (host clocks differ; use "
                         "--max-grad-steps / --total-env-frames)")
        if args.single_process:
            parser.error("--single-process and --coordinator conflict")
        # must happen before any JAX backend use
        from ape_x_dqn_tpu.parallel.multihost import init_multihost
        init_multihost(args.coordinator, args.num_processes,
                       args.process_id)
    cfg = get_config(args.config)
    if args.seed is not None:
        cfg = cfg.replace(seed=args.seed)
    if args.actors is not None:
        cfg = cfg.replace(
            actors=dataclasses.replace(cfg.actors, num_actors=args.actors))
    if args.total_env_frames is not None:
        cfg = cfg.replace(total_env_frames=args.total_env_frames)
    if args.checkpoint_dir is not None:
        cfg = cfg.replace(checkpoint_dir=args.checkpoint_dir)
    if args.profile_dir is not None:
        cfg = cfg.replace(profile_dir=args.profile_dir)
    cfg = apply_overrides(cfg, args.set)

    if args.eval_only:
        if args.coordinator is not None:
            parser.error("--eval-only is single-process (no learner "
                         "mesh); drop --coordinator")
        from ape_x_dqn_tpu.runtime.evaluation import run_suite_eval
        out = run_suite_eval(
            cfg, games=args.games.split(",") if args.games else None,
            checkpoint_dir=args.checkpoint_dir or cfg.checkpoint_dir
            or None)
        print(json.dumps(out))
        return 0

    metrics = Metrics(log_path=args.metrics_file,
                      tensorboard_dir=args.tensorboard_dir)
    transport = server = None
    if args.listen and not args.single_process:
        from ape_x_dqn_tpu.comm.socket_transport import SocketIngestServer
        host, port = args.listen.rsplit(":", 1)
        server = transport = SocketIngestServer(
            host, int(port), param_wire_dtype=args.param_wire_dtype,
            wire_codec=cfg.comm.wire_codec,
            param_codec=getattr(cfg.comm, "param_codec", "delta-q8"),
            param_delta_window=getattr(cfg.comm, "param_delta_window", 8),
            shm=getattr(cfg.comm, "shm", False),
            shm_slots=getattr(cfg.comm, "shm_slots", 8),
            shm_slot_bytes=getattr(cfg.comm, "shm_slot_bytes", 1 << 22),
            shm_param_bytes=getattr(cfg.comm, "shm_param_bytes", 1 << 26))
        print(f"ingest listening on {host}:{server.port}",
              file=sys.stderr, flush=True)
    if args.coordinator is not None:
        from ape_x_dqn_tpu.runtime.multihost_driver import (
            MultihostApexDriver)
        driver = MultihostApexDriver(cfg, metrics=metrics,
                                     transport=transport)
        try:
            out = driver.run(max_grad_steps=args.max_grad_steps)
        finally:
            if server is not None:
                server.stop()
    elif args.single_process:
        from ape_x_dqn_tpu.runtime.single_process import train_single_process
        out = train_single_process(cfg, metrics=metrics)
    else:
        from ape_x_dqn_tpu.runtime.driver import ApexDriver
        driver = ApexDriver(cfg, metrics=metrics, transport=transport)
        try:
            out = driver.run(max_grad_steps=args.max_grad_steps,
                             wall_clock_limit_s=args.wall_clock_limit)
        finally:
            if server is not None:
                server.stop()
        # summary must stay one parseable JSON line
        out = dict(out)
        out["actor_errors"] = [f"{i}: {e!r}"
                               for i, e in out["actor_errors"]]
        out["loop_errors"] = [f"{which}: {e!r}"
                              for which, e in out["loop_errors"]]
    metrics.close()
    print(json.dumps(out))
    failed = bool(out.get("actor_errors") or out.get("loop_errors"))
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
