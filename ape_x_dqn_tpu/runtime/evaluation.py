"""Eval worker: greedy-policy evaluation episodes and the HNS suite.

Reference parity (SURVEY.md §2.2 "Eval worker", §5 metrics): a periodic
evaluator running near-greedy (eps = 0.001) episodes whose *unclipped*
returns feed the Atari-57 median human-normalized score — the north-star
metric (BASELINE.json `metric`). Evaluation shares the batched TPU
inference server with the actors (one more client on the same jit), so no
separate device or params copy is needed.

Eval episodes differ from training episodes in the standard ways: no
episodic-life pseudo-terminals, no reward clipping, near-greedy policy.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterable

import numpy as np

from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.utils.metrics import ATARI_HUMAN_RANDOM, median_hns


class EvalWorker:
    """Runs greedy eval episodes against a Q-value query function."""

    def __init__(self, cfg: RunConfig, query_fn: Callable,
                 game: str | None = None, seed: int | None = None,
                 policy_factory: Callable[[], Callable] | None = None):
        """query_fn(obs) -> q-values [A] (e.g. inference server .query).

        policy_factory, when given, builds a fresh per-episode policy
        (obs -> q-values for discrete envs, obs -> action for continuous)
        — recurrent policies carry (c, h) across the episode's queries,
        continuous ones route through the deterministic DPG actor.
        """
        self.cfg = cfg
        env_cfg = cfg.env
        if game is not None:
            if env_cfg.id == "atari57":
                # a per-game eval env for a multi-game net must keep
                # the shared 18-action legal set the net was sized for
                env_cfg = dataclasses.replace(env_cfg,
                                              full_action_set=True)
            env_cfg = dataclasses.replace(env_cfg, id=game)
        if env_cfg.kind in ("atari", "synthetic_atari"):
            env_cfg = dataclasses.replace(env_cfg, episodic_life=False,
                                          clip_rewards=False)
        seed = (cfg.seed + 977_231) if seed is None else seed
        self.env = make_env(env_cfg, seed=seed)
        self.query = query_fn
        self.policy_factory = policy_factory
        self.eps = cfg.eval_eps
        self.rng = np.random.default_rng(seed)
        # eval_max_frames is specified in RAW env frames (the Atari
        # protocol's 108k = 30 min @ 60Hz) but the episode loop counts
        # AGENT steps; a skipped env consumes frame_skip raw frames per
        # step. Counting steps against the raw budget made the cap 4x
        # looser than documented — on slow-link hosts that blew the
        # whole final-eval deadline on one episode (round-5 suite run:
        # a trained game recorded eval=null and was discarded).
        self._frames_per_step = (
            env_cfg.frame_skip
            if env_cfg.kind in ("atari", "synthetic_atari") else 1)

    def run_episode(self, max_frames: int = 108_000,
                    stop_event=None,
                    deadline: float | None = None) -> float | None:
        """One episode; returns the unclipped episode return, or None if
        stop_event fired / the wall-clock deadline passed mid-episode
        (the partial return is meaningless)."""
        policy = (self.policy_factory() if self.policy_factory is not None
                  else self.query)
        discrete = self.env.spec.discrete
        obs = self.env.reset()
        ep_return = 0.0
        for _ in range(max(max_frames // self._frames_per_step, 1)):
            if stop_event is not None and stop_event.is_set():
                return None
            if deadline is not None and time.monotonic() > deadline:
                return None
            if not discrete:
                action = np.asarray(policy(obs))  # deterministic mu(s)
            else:
                # always query (recurrent policies must advance their
                # state every step), then eps-explore on top
                q = policy(obs)
                if self.rng.random() < self.eps:
                    action = int(
                        self.rng.integers(self.env.spec.num_actions))
                else:
                    action = int(np.argmax(q))
            obs, reward, done, info = self.env.step(action)
            ep_return += info.get("raw_reward", reward)
            if done:
                # prefer the env's own unclipped accounting when present
                return float(info.get("episode_return", ep_return))
        return ep_return

    def run(self, episodes: int, max_frames: int = 108_000,
            stop_event=None, deadline_s: float | None = None) -> dict | None:
        """Aggregate stats over episodes; None if cancelled before any
        episode completed. deadline_s bounds the whole evaluation's
        wall-clock (needed at shutdown, where an unbounded greedy policy
        could otherwise block the driver for minutes)."""
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        returns = []
        for _ in range(episodes):
            r = self.run_episode(max_frames, stop_event=stop_event,
                                 deadline=deadline)
            if r is None:
                break
            returns.append(r)
        if not returns:
            return None
        return {
            "episodes": len(returns),
            "mean_return": float(np.mean(returns)),
            "median_return": float(np.median(returns)),
            "min_return": float(np.min(returns)),
            "max_return": float(np.max(returns)),
        }


def run_eval_measured(worker: "EvalWorker", episodes: int, server,
                      stop_event=None,
                      deadline_s: float | None = None,
                      max_frames: int = 108_000
                      ) -> tuple[dict | None, int]:
    """Run worker.run while polling the shared inference server's
    queue depth at ~20Hz; returns (result, max depth seen DURING the
    eval). The during-eval max is the back-pressure the eval induces
    on concurrent actors — a post-eval snapshot mostly reads 0 because
    actors drain the queue the moment the eval stops querying
    (round-3 advisor finding on server_queue_depth)."""
    import threading

    depth = {"max": int(server.queue_depth)}
    done = threading.Event()

    def poll():
        while not done.wait(0.05):
            depth["max"] = max(depth["max"], int(server.queue_depth))

    t = threading.Thread(target=poll, name="eval-depth-poll", daemon=True)
    t.start()
    try:
        res = worker.run(episodes, max_frames=max_frames,
                         stop_event=stop_event, deadline_s=deadline_s)
    finally:
        done.set()
        t.join(timeout=1.0)
    return res, depth["max"]


ATARI57_GAMES: tuple[str, ...] = tuple(sorted(ATARI_HUMAN_RANDOM))


def eval_game_rotation(cfg: RunConfig) -> tuple[bool, tuple[str, ...]]:
    """Whether a run's periodic eval should rotate through the suite,
    and the game list. Multi-game runs (env id='atari57') must rotate:
    a fixed eval worker would silently measure only the alphabetically-
    first game every time. ONE predicate for both drivers — the
    rotation rule diverging between them is exactly the bug it fixes."""
    rotate = (cfg.env.id == "atari57"
              and cfg.env.kind in ("atari", "synthetic_atari"))
    return rotate, ATARI57_GAMES


class RollingSuiteScore:
    """Rolling per-game score table for the multi-game eval rotation.

    The rotation evaluates ONE game per eval event, so a full suite
    view previously needed an offline `--eval-only` pass over all 57
    (round-3 verdict weak #7). This keeps the latest unclipped return
    per game and exposes a rolling backend-marked median-HNS over the
    games seen so far — the same honesty split as evaluate_suite: the
    unqualified key never appears for synthetic backends, and the
    rolling key is additionally marked `rolling_` because it medians
    only the games evaluated so far this run."""

    def __init__(self, cfg: RunConfig):
        from ape_x_dqn_tpu.envs.atari import atari_backend

        self._backend = atari_backend(cfg.env.kind)
        self._scores: dict[str, float] = {}

    def update(self, game: str, mean_return: float) -> dict:
        """Record a game's latest eval; returns metric fields to log."""
        self._scores[game] = float(mean_return)
        known = {g: s for g, s in self._scores.items()
                 if g in ATARI_HUMAN_RANDOM}
        key = ("rolling_median_hns" if self._backend == "ale"
               else "rolling_median_hns_synthetic")
        out = {"eval_games_seen": len(self._scores)}
        if known:
            out[key] = median_hns(known)
        return out

    @property
    def scores(self) -> dict[str, float]:
        return dict(self._scores)


def final_eval_game(cfg: RunConfig) -> str | None:
    """The game for a driver's guaranteed end-of-run fallback eval.
    Multi-game (rotating) configs must not fall back to an unmarked
    default worker — that silently measures the alphabetically-first
    game (round-3 advisor finding). ONE helper for both drivers, for
    the same reason eval_game_rotation is shared."""
    rotate, games = eval_game_rotation(cfg)
    return games[0] if rotate else None


def make_eval_policy_factory(family: str, lstm_size: int,
                             query_fn: Callable) -> Callable | None:
    """Per-episode eval policy builder per model family (shared by
    ApexDriver's eval loop and the standalone suite runner).

    Recurrent policies carry fresh (c, h) across one episode's queries;
    continuous policies return the deterministic action mu(s); plain
    Q-nets need no factory (EvalWorker queries directly).
    """
    if family == "dpg":
        return lambda: lambda obs: query_fn(obs)["a"]
    if family != "r2d2":
        return None

    def factory():
        state = {"c": np.zeros(lstm_size, np.float32),
                 "h": np.zeros(lstm_size, np.float32)}

        def policy(obs):
            out = query_fn({"obs": obs, "c": state["c"], "h": state["h"]})
            state["c"], state["h"] = out["c"], out["h"]
            return out["q"]

        return policy

    return factory


def evaluate_suite(cfg: RunConfig, query_fn: Callable,
                   games: Iterable[str] | None = None,
                   episodes_per_game: int | None = None,
                   max_frames: int = 108_000,
                   policy_factory: Callable | None = None) -> dict:
    """Per-game greedy scores -> median human-normalized score.

    The Atari-57 harness (SURVEY.md §2.1 config 3): loops the suite,
    evaluates each game with the shared query_fn, and aggregates the
    north-star metric. Returns {"scores": {game: mean}, "hns":
    {game: hns}, "backends": {game: "ale"|"synthetic"}, and EITHER
    "median_hns" (every game ran on the real ALE) OR
    "median_hns_synthetic" (any game ran the in-image catch stand-in).

    The split key is deliberate: in an image without `ale_py`, make_env
    silently substitutes SyntheticAtari for every game, and an unmarked
    "median_hns" from that path would look exactly like the north-star
    number while measuring a catch game. The real key only ever appears
    when the real backend produced it.
    """
    from ape_x_dqn_tpu.envs.atari import atari_backend

    games = tuple(games) if games is not None else ATARI57_GAMES
    # at least one episode: worker.run(0) returns None, and a suite
    # score of None is useless (configs legitimately carry
    # eval_episodes=0 to disable the TRAINING-time eval loop)
    episodes = max(episodes_per_game or cfg.eval_episodes, 1)
    backend = atari_backend(cfg.env.kind)
    scores: dict[str, float] = {}
    for game in games:
        worker = EvalWorker(cfg, query_fn, game=game,
                            policy_factory=policy_factory)
        scores[game] = worker.run(episodes, max_frames)["mean_return"]
    known = {g: s for g, s in scores.items() if g in ATARI_HUMAN_RANDOM}
    from ape_x_dqn_tpu.utils.metrics import human_normalized_score
    out = {
        "scores": scores,
        "hns": {g: human_normalized_score(g, s) for g, s in known.items()},
        "backends": {g: backend for g in scores},
    }
    key = "median_hns" if backend == "ale" else "median_hns_synthetic"
    out[key] = median_hns(known)
    return out


def run_suite_eval(cfg: RunConfig, games: Iterable[str] | None = None,
                   episodes_per_game: int | None = None,
                   checkpoint_dir: str | None = None,
                   max_frames: int = 108_000) -> dict:
    """Standalone evaluation entry (CLI --eval-only): build the net,
    restore the latest checkpoint's params, and run greedy episodes —
    the full HNS suite for Atari configs, the config's own env
    otherwise. No learner, no actors, no training state.
    """
    import jax

    from ape_x_dqn_tpu.envs import make_env
    from ape_x_dqn_tpu.models import build_network
    from ape_x_dqn_tpu.runtime.family import (
        family_of, family_setup, server_apply_fn)

    if games is not None and cfg.env.kind not in ("atari",
                                                  "synthetic_atari"):
        # an explicit --games list builds per-game Atari envs, whose
        # 84x84x4 observations cannot feed a network sized for this
        # config's own env — fail with a clear message instead of an
        # opaque downstream shape mismatch
        raise ValueError(
            f"--games is only valid for Atari configs (env.kind 'atari' "
            f"or 'synthetic_atari'), got kind={cfg.env.kind!r}")
    family = family_of(cfg)
    probe = make_env(cfg.env, seed=cfg.seed)
    spec = probe.spec
    net = build_network(cfg.network, spec)
    params = family_setup(cfg, spec, net, probe.reset()).params
    if family == "dpg":
        params = {"actor": params[0], "critic": params[1]}
    restored_step = None
    if checkpoint_dir:
        from ape_x_dqn_tpu.utils.checkpoint import CheckpointManager
        mngr = CheckpointManager(checkpoint_dir)
        restored_step = mngr.latest_step()
        if restored_step is not None:
            # raw restore (no template): we only need the param leaves,
            # and the saved tree holds the full TrainState minus replay
            raw = mngr.restore(restored_step)
            if family == "dpg":
                params = {"actor": raw["actor_params"],
                          "critic": raw["critic_params"]}
            else:
                params = raw["params"]
        mngr.close()

    fn = jax.jit(server_apply_fn(family, net))

    def query(inp):
        batched = jax.tree.map(lambda x: np.asarray(x)[None], inp)
        return jax.tree.map(lambda x: np.asarray(x)[0],
                            fn(params, batched))

    factory = make_eval_policy_factory(family, cfg.network.lstm_size,
                                       query)
    if games is None and cfg.env.kind not in ("atari", "synthetic_atari"):
        worker = EvalWorker(cfg, query, policy_factory=factory)
        out = worker.run(max(episodes_per_game or cfg.eval_episodes, 1),
                         max_frames)
    else:
        out = evaluate_suite(cfg, query, games=games,
                             episodes_per_game=episodes_per_game,
                             max_frames=max_frames,
                             policy_factory=factory)
    out["restored_step"] = restored_step
    return out
