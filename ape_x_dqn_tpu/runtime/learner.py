"""The learner: sample -> loss -> update -> priority write-back, one jit.

This is the reference's hot loop (SURVEY.md §3.3) rebuilt TPU-first: the
reference fuses forward/backward/optimizer in CUDA and keeps its sum-tree
on the host; here the *entire* cycle — stratified sum-tree sampling,
batch gather from HBM storage, n-step double-DQN Huber loss, optimizer
update, priority write-back, and periodic target sync — is one XLA graph
with the learner state donated (no host round-trips, no copies).

`make_dqn_learner` also exposes `train_many`, a `lax.scan` over K steps,
so the device runs unattended for K grad-steps per dispatch — this is
what the benchmark (bench.py) measures.

Replay ingest (`add`) is a separate donated jit: the actor/ingest thread
feeds device-resident storage while the learner thread owns training.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ape_x_dqn_tpu.ops.losses import TransitionBatch, make_dqn_loss
from ape_x_dqn_tpu.replay.prioritized import ReplayState


class TrainState(NamedTuple):
    params: Any
    target_params: Any
    opt_state: Any
    replay: ReplayState
    rng: jax.Array
    step: jax.Array  # int32 grad-step counter


def transition_item_spec(obs_shape, obs_dtype) -> dict:
    """Item pytree spec for one flat n-step transition (discrete actions)."""
    return {
        "obs": jax.ShapeDtypeStruct(obs_shape, obs_dtype),
        "action": jax.ShapeDtypeStruct((), jnp.int32),
        "reward": jax.ShapeDtypeStruct((), jnp.float32),
        "next_obs": jax.ShapeDtypeStruct(obs_shape, obs_dtype),
        "discount": jax.ShapeDtypeStruct((), jnp.float32),
    }


def make_optimizer(lcfg) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(lcfg.max_grad_norm),
        optax.adam(lcfg.lr, eps=lcfg.adam_eps),
    )


class DQNLearner:
    """Builds the jitted endpoints for a flat-transition DQN learner."""

    def __init__(self, net_apply: Callable, replay, lcfg,
                 optimizer: optax.GradientTransformation | None = None):
        self.net_apply = net_apply
        self.replay = replay
        self.lcfg = lcfg
        self.optimizer = optimizer or make_optimizer(lcfg)
        self.loss_fn = make_dqn_loss(
            net_apply, double=lcfg.double_dqn, huber_delta=lcfg.huber_delta,
            rescale=lcfg.value_rescale)

    # -- state ------------------------------------------------------------

    def init(self, params: Any, replay_state: ReplayState,
             rng: jax.Array) -> TrainState:
        return TrainState(
            params=params,
            # real copies: params and target_params are donated together,
            # so they must not alias the same device buffers
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=self.optimizer.init(params),
            replay=replay_state,
            rng=rng,
            step=jnp.int32(0))

    # -- core step (pure) -------------------------------------------------

    def _train_step(self, state: TrainState) -> tuple[TrainState, dict]:
        rng, sk = jax.random.split(state.rng)
        items, idx, is_w = self.replay.sample(
            state.replay, sk, self.lcfg.batch_size)
        batch = TransitionBatch(
            obs=items["obs"], actions=items["action"],
            rewards=items["reward"], next_obs=items["next_obs"],
            discounts=items["discount"])
        (loss, aux), grads = jax.value_and_grad(
            self.loss_fn, has_aux=True)(
            state.params, state.target_params, batch, is_w)
        updates, opt_state = self.optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        replay_state = self.replay.update_priorities(
            state.replay, idx, aux["td_abs"])
        step = state.step + 1
        # hard target sync every K steps, branchless (SURVEY.md §3.3)
        sync = (step % self.lcfg.target_sync_every == 0)
        target_params = jax.tree.map(
            lambda t, p: jnp.where(sync, p, t), state.target_params, params)
        metrics = {
            "loss": loss,
            "q_mean": aux["q_mean"],
            "td_abs_mean": aux["td_abs"].mean(),
            "grad_norm": optax.global_norm(grads),
        }
        new_state = TrainState(params, target_params, opt_state,
                               replay_state, rng, step)
        return new_state, metrics

    # -- jitted endpoints --------------------------------------------------

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state: TrainState):
        return self._train_step(state)

    @partial(jax.jit, static_argnums=(0, 2), donate_argnums=1)
    def train_many(self, state: TrainState, n: int):
        """n grad-steps in one dispatch via lax.scan (bench hot path)."""
        def body(s, _):
            s, m = self._train_step(s)
            return s, m
        state, metrics = jax.lax.scan(body, state, None, length=n)
        return state, jax.tree.map(lambda x: x[-1], metrics)

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add(self, state: TrainState, items: Any,
            td_abs: jax.Array) -> TrainState:
        return state._replace(
            replay=self.replay.add(state.replay, items, td_abs))

    def publish_params(self, state: TrainState) -> Any:
        """Independent param copy for the inference server — the train/add
        jits donate the TrainState, so aliased buffers would be deleted
        under the server's feet."""
        return jax.tree.map(jnp.copy, state.params)
