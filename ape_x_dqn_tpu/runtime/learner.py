"""The learner: sample -> loss -> update -> priority write-back, one jit.

This is the reference's hot loop (SURVEY.md §3.3) rebuilt TPU-first: the
reference fuses forward/backward/optimizer in CUDA and keeps its sum-tree
on the host; here the *entire* cycle — stratified sum-tree sampling,
batch gather from HBM storage, n-step double-DQN Huber loss, optimizer
update, priority write-back, and periodic target sync — is one XLA graph
with the learner state donated (no host round-trips, no copies).

`make_dqn_learner` also exposes `train_many`, a `lax.scan` over K steps,
so the device runs unattended for K grad-steps per dispatch — this is
what the benchmark (bench.py) measures.

Replay ingest (`add`) is a separate donated jit: the actor/ingest thread
feeds device-resident storage while the learner thread owns training.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ape_x_dqn_tpu.obs import learning as learn_obs
from ape_x_dqn_tpu.ops.losses import TransitionBatch, make_dqn_loss
from ape_x_dqn_tpu.replay.prioritized import ReplayState


class TrainState(NamedTuple):
    params: Any
    target_params: Any
    opt_state: Any
    replay: ReplayState
    rng: jax.Array
    step: jax.Array  # int32 grad-step counter


def transition_item_spec(obs_shape, obs_dtype) -> dict:
    """Item pytree spec for one flat n-step transition (discrete actions)."""
    return {
        "obs": jax.ShapeDtypeStruct(obs_shape, obs_dtype),
        "action": jax.ShapeDtypeStruct((), jnp.int32),
        "reward": jax.ShapeDtypeStruct((), jnp.float32),
        "next_obs": jax.ShapeDtypeStruct(obs_shape, obs_dtype),
        "discount": jax.ShapeDtypeStruct((), jnp.float32),
    }


def make_optimizer(lcfg) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(lcfg.max_grad_norm),
        optax.adam(lcfg.lr, eps=lcfg.adam_eps),
    )


class SingleChipLearner:
    """Shared single-chip learner machinery: state init, the exact
    per-step path, the K-batch relaxation, the train_many scan, the
    ingest add, and param publication.

    Subclasses provide `self.replay`, `self.lcfg`, `self.optimizer`
    and define `_sgd_step(params, target_params, opt_state, step,
    items, is_w) -> (params, target_params, opt_state, step, td_abs,
    metrics)` — the only family-specific piece (batch construction +
    loss). The K-batch semantics (interleaved strata, per-chunk IS
    renorm, one write-back, remainder-first metrics) therefore cannot
    drift between the flat-DQN and sequence learners.

    The K-batch cycle itself is split into two pure stages —
    _sample_stage (stratified K*B descent + gather + chunked IS
    weights) and _learn_stage (K SGD steps + one write-back + target
    sync) — composed back-to-back by the fused path and pipelined
    one-deep by the double-buffered path (sample_prefetch), which both
    the sequence and dist learners inherit.
    """

    # -- state ------------------------------------------------------------

    def init(self, params: Any, replay_state: ReplayState,
             rng: jax.Array) -> TrainState:
        return TrainState(
            params=params,
            # real copies: params and target_params are donated together,
            # so they must not alias the same device buffers
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=self.optimizer.init(params),
            replay=replay_state,
            rng=rng,
            step=jnp.int32(0))

    # -- core step (pure) -------------------------------------------------

    def _sgd_step(self, params, target_params, opt_state, step,
                  items, is_w):
        raise NotImplementedError  # family-specific: batch + loss

    def _train_step(self, state: TrainState) -> tuple[TrainState, dict]:
        rng, sk = jax.random.split(state.rng)
        items, idx, is_w = self.replay.sample(
            state.replay, sk, self.lcfg.batch_size)
        params, target_params, opt_state, step, td_abs, metrics = \
            self._sgd_step(state.params, state.target_params,
                           state.opt_state, state.step, items, is_w)
        # fused path: draw and write-back see the same tree, so the
        # priority-staleness delta is identically 0 (pri_then=None)
        metrics["diag"] = {**metrics.get("diag", {}),
                           **learn_obs.replay_health(
                               self.replay, state.replay, idx, None)}
        replay_state = self.replay.update_priorities(
            state.replay, idx, td_abs)
        new_state = TrainState(params, target_params, opt_state,
                               replay_state, rng, step)
        return new_state, metrics

    def _sample_stage(self, replay_state: ReplayState, sk: jax.Array,
                      k: int):
        """Pure SAMPLE stage of the (split) K-batch cycle: one
        stratified K*B tree descent + storage gather + IS weights,
        already chunked for the K SGD steps. Reads only the replay
        state (via `replay.sample_state`, which never touches the write
        cursor), so a prefetched call commutes with an in-flight
        priority write-back — the double-buffering contract.

        -> (items_k [K, B, ...] pytree, idx_k [K, B], is_w_k [K, B],
            pri_k [K, B] descent-time leaf priorities — the staleness
            reference _learn_stage compares against at write-back time;
            appended LAST so positional readers of the tuple's stable
            prefix, e.g. single_process.py's `sample[1]`, are unmoved)
        """
        b = self.lcfg.batch_size
        items, idx, is_w = self.replay.sample_state(replay_state, sk,
                                                    k * b)
        pri = self.replay.leaf_priorities(replay_state, idx)

        # stratum i of the K*B descent covers cumulative-mass slice
        # [i, i+1)/(K*B) over leaves in ring-insertion order, so chunk
        # j must take the INTERLEAVED strata {j, j+K, j+2K, ...} to
        # span the full priority range — a contiguous reshape(k, b)
        # would hand each chunk one age-correlated 1/K slice of the
        # replay (oldest quarter, ..., newest quarter)
        def chunked(x):
            return x.reshape(b, k, *x.shape[1:]).swapaxes(0, 1)

        items_k = jax.tree.map(chunked, items)
        idx_k = chunked(idx)
        # sample() max-normalized over the K*B pool; renormalizing per
        # chunk recovers the exact per-step IS convention
        is_w_k = chunked(is_w)
        is_w_k = is_w_k / jnp.maximum(
            is_w_k.max(axis=1, keepdims=True), 1e-12)
        return items_k, idx_k, is_w_k, chunked(pri)

    def _learn_stage(self, state: TrainState, sample,
                     k: int) -> tuple[TrainState, dict]:
        """Pure LEARN stage: K SGD steps over an already-drawn sample
        + ONE priority write-back + target sync. `state.rng` must
        already be advanced past the draw that produced `sample`.

        The K chunks run as a STATIC unrolled loop, not lax.scan: K is
        small (4-8) and measured on CPU a scanned conv body ran ~17x
        slower than the identical straight-line code (855 vs 51
        ms/step — scan's carried buffers defeat in-place aliasing
        there), while unrolled code also gives XLA's scheduler the
        whole window to overlap."""
        b = self.lcfg.batch_size
        items_k, idx_k, is_w_k, pri_k = sample
        params, target_params, opt_state, step = (
            state.params, state.target_params, state.opt_state,
            state.step)
        td_parts = []
        metrics = None
        for j in range(k):
            it = jax.tree.map(lambda x: x[j], items_k)
            params, target_params, opt_state, step, td_abs, metrics = \
                self._sgd_step(params, target_params, opt_state, step,
                               it, is_w_k[j])
            td_parts.append(td_abs)
        # write-back-time replay health: state.replay's tree is what
        # the sampler would see NOW, pri_k is what it saw at descent
        # time — their delta is the measured priority staleness the
        # prefetch/K-batch relaxations accept (ROADMAP item 3)
        metrics["diag"] = {**metrics.get("diag", {}),
                           **learn_obs.replay_health(
                               self.replay, state.replay, idx_k, pri_k)}
        # td_parts[j] pairs with idx_k[j] (chunk order), so flatten
        # idx_k the same way for the single write-back
        replay_state = self.replay.update_state(
            state.replay, idx_k.reshape(k * b),
            jnp.concatenate(td_parts))
        new_state = TrainState(params, target_params, opt_state,
                               replay_state, state.rng, step)
        return new_state, metrics

    def _train_step_k(self, state: TrainState,
                      k: int) -> tuple[TrainState, dict]:
        """K grad-steps from ONE stratified sample + ONE priority
        write-back (the K-batch relaxation, LearnerConfig.sample_chunk).

        Chunk j+1 trains on priorities that predate chunk j's TD errors
        — the same staleness the reference's async host-side replay
        server exhibits between its sampler and learner. The payoff:
        the K SGD steps carry no tree dependency between them, so XLA
        overlaps the single big descent/gather/write-back with K steps
        of MXU work instead of serializing tree<->loss every step.

        Composed from the split _sample_stage/_learn_stage so the fused
        path and the double-buffered path (sample_prefetch) cannot
        drift."""
        rng, sk = jax.random.split(state.rng)
        sample = self._sample_stage(state.replay, sk, k)
        return self._learn_stage(state._replace(rng=rng), sample, k)

    # -- jitted endpoints --------------------------------------------------

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state: TrainState):
        return self._train_step(state)

    @partial(jax.jit, static_argnums=(0, 2), donate_argnums=1)
    def train_step_k(self, state: TrainState, k: int):
        """One K-batch macro-step WITHOUT the outer train_many scan.
        The inner scan carries only (params, targets, opt, step) — on
        backends where lax.scan cannot alias a large carried buffer
        in place (CPU), train_many's outer scan copies the whole
        replay storage every iteration; this endpoint avoids that
        (the single-process driver uses it for the K-batch path)."""
        return self._train_step_k(state, k)

    @partial(jax.jit, static_argnums=(0, 2))
    def sample_k(self, state: TrainState, k: int):
        """Standalone SAMPLE dispatch for the host-side double-buffer
        pipeline (single_process.py): draw the NEXT macro-step's
        chunked sample from the current tree. Deliberately NOT donated
        — the caller keeps `state` alive for the learn_k that trains on
        the PREVIOUS draw. -> (sample, advanced rng)."""
        rng, sk = jax.random.split(state.rng)
        return self._sample_stage(state.replay, sk, k), rng

    @partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
    def learn_k(self, state: TrainState, sample, k: int):
        """Standalone LEARN dispatch: K SGD steps + write-back on a
        sample drawn earlier by sample_k (possibly against a tree that
        an `add` or a previous write-back has since changed — the
        accepted async-replay staleness). state.rng must be the rng
        sample_k returned. Only the state is donated — the sample's
        buffers match no output shape (XLA would warn them unusable)
        and are freed when the caller drops its reference."""
        return self._learn_stage(state, sample, k)

    @partial(jax.jit, static_argnums=(0, 2), donate_argnums=1)
    def train_many(self, state: TrainState, n: int):
        """n grad-steps in one dispatch via lax.scan (bench hot path).
        With sample_chunk=K>1, runs n//K K-batch macro-steps (plus
        exact single steps for any remainder) — same grad-step count
        either way. With sample_prefetch, the macro-step scan runs
        double-buffered (see _train_many_prefetch)."""
        k = getattr(self.lcfg, "sample_chunk", 1)

        def body(s, _):
            s, m = self._train_step(s)
            return s, m

        if getattr(self.lcfg, "sample_prefetch", False):
            return self._train_many_prefetch(state, n, max(k, 1), body)

        if k <= 1:
            state, metrics = jax.lax.scan(body, state, None, length=n)
            return state, jax.tree.map(lambda x: x[-1], metrics)

        def body_k(s, _):
            s, m = self._train_step_k(s, k)
            return s, m

        # exact singles for the remainder run FIRST so the returned
        # (last-step) metrics come from the K-batch macro-steps that do
        # the bulk of the dispatch's work — remainder-last returned only
        # the singles' metrics, hiding K-batch pathologies from the
        # driver log exactly where they'd show (round-4 verdict weak #7)
        metrics = None
        if n % k:
            state, metrics = jax.lax.scan(body, state, None,
                                          length=n % k)
        if n // k:
            state, metrics = jax.lax.scan(body_k, state, None,
                                          length=n // k)
        return state, jax.tree.map(lambda x: x[-1], metrics)

    def _train_many_prefetch(self, state: TrainState, n: int, k: int,
                             body):
        """Double-buffered macro-step pipeline (the tentpole,
        LearnerConfig.sample_prefetch): inside the scan body, the NEXT
        macro-step's sample is drawn from the tree BEFORE this
        macro-step's K SGD steps and priority write-back run. The draw
        and the SGD/write-back then share no data dependency, so XLA's
        scheduler is free to overlap the next tree descent + storage
        gather with the current backward passes — the overlap the fused
        body only achieves within one macro-step.

        Staleness contract: the sample for macro-step i+1 sees
        priorities that predate macro-step i's write-back — one
        dispatch of lag, the same kind the K-batch relaxation already
        accepts within a macro-step and the reference's async
        host-side sampler exhibits always. The first macro-step trains
        on a fresh (prologue) draw, so a single-macro-step dispatch is
        bit-identical in params to train_step_k; the final prefetched
        sample is discarded (one extra K*B descent per dispatch,
        amortized over n//k macro-steps)."""
        metrics = None
        if n % k:
            state, metrics = jax.lax.scan(body, state, None,
                                          length=n % k)
        if n // k:
            rng, sk = jax.random.split(state.rng)
            pending = self._sample_stage(state.replay, sk, k)
            state = state._replace(rng=rng)

            def body_pf(carry, _):
                s, pend = carry
                rng, sk = jax.random.split(s.rng)
                # drawn BEFORE _learn_stage's write-back: no data
                # dependency with the K SGD steps below
                nxt = self._sample_stage(s.replay, sk, k)
                s, m = self._learn_stage(s._replace(rng=rng), pend, k)
                return (s, nxt), m

            (state, _), metrics = jax.lax.scan(
                body_pf, (state, pending), None, length=n // k)
        return state, jax.tree.map(lambda x: x[-1], metrics)

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add(self, state: TrainState, items: Any,
            td_abs: jax.Array) -> TrainState:
        return state._replace(
            replay=self.replay.add(state.replay, items, td_abs))

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add_many(self, state: TrainState, items: Any,
                 td_abs: jax.Array) -> TrainState:
        """Coalesced ingest: items [g, B, ...], td_abs [g, B] — g staged
        blocks fused into ONE donated dispatch, so the driver takes
        _state_lock once per group instead of once per block and a burst
        of ingest stops interleaving small add dispatches with
        train_many (runtime/ingest.py).

        UNROLLED Python loop over the static g axis, not lax.scan: a
        scan carrying the replay storage re-materializes the full
        storage per iteration on the CPU backend (PERF.md "CPU scan
        pathology"); the unrolled chain keeps each add's in-place DUS
        ring write aliasing on every backend. g is small
        (ingest_coalesce), so trace/compile cost is negligible.
        """
        rs = state.replay
        for j in range(td_abs.shape[0]):
            rs = self.replay.add(
                rs, jax.tree.map(lambda x, j=j: x[j], items), td_abs[j])
        return state._replace(replay=rs)

    # -- tiered cold store endpoints (runtime/driver.py eviction cycle) ----

    @partial(jax.jit, static_argnums=(0, 2))
    def evict_region(self, state: TrainState, block: int):
        """-> (start, staging-layout items, stored leaf priorities) of
        the ring's lowest-priority-mass `block`-unit region. NOT
        donated: the driver fetches the result to host (ColdStore.put)
        before add_at overwrites the region in place."""
        start = self.replay.evict_plan(state.replay, block)
        items, pri = self.replay.read_region(state.replay, start, block)
        return start, items, pri

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add_at(self, state: TrainState, items: Any, td_abs: jax.Array,
               start: jax.Array) -> TrainState:
        """Directed ingest add: overwrite the evict_region start instead
        of the FIFO cursor (cold tier on + ring full; the default path
        never calls this)."""
        return state._replace(
            replay=self.replay.add_at(state.replay, items, td_abs, start))

    def publish_params(self, state: TrainState) -> Any:
        """Independent param copy for the inference server — the train/add
        jits donate the TrainState, so aliased buffers would be deleted
        under the server's feet."""
        return jax.tree.map(jnp.copy, state.params)


class DQNLearner(SingleChipLearner):
    """Jitted endpoints for the flat-transition DQN learner."""

    def __init__(self, net_apply: Callable, replay, lcfg,
                 optimizer: optax.GradientTransformation | None = None):
        self.net_apply = net_apply
        self.replay = replay
        self.lcfg = lcfg
        self.optimizer = optimizer or make_optimizer(lcfg)
        self.loss_fn = make_dqn_loss(
            net_apply, double=lcfg.double_dqn, huber_delta=lcfg.huber_delta,
            rescale=lcfg.value_rescale)

    def _sgd_step(self, params, target_params, opt_state, step,
                  items, is_w):
        """One loss/grad/optimizer/target-sync update on an already-
        sampled batch (shared by the exact per-step path and the
        K-batch relaxation)."""
        batch = TransitionBatch(
            obs=items["obs"], actions=items["action"],
            rewards=items["reward"], next_obs=items["next_obs"],
            discounts=items["discount"])
        (loss, aux), grads = jax.value_and_grad(
            self.loss_fn, has_aux=True)(
            params, target_params, batch, is_w)
        updates, opt_state = self.optimizer.update(
            grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        step = step + 1
        # hard target sync every K steps, branchless (SURVEY.md §3.3)
        sync = (step % self.lcfg.target_sync_every == 0)
        target_params = jax.tree.map(
            lambda t, p: jnp.where(sync, p, t), target_params, params)
        metrics = {
            "loss": loss,
            "q_mean": aux["q_mean"],
            "td_abs_mean": aux["td_abs"].mean(),
            "grad_norm": optax.global_norm(grads),
            # learning-health scalars (obs/learning.py); rides the
            # metrics pytree through every scan, read at existing
            # host sync points only
            "diag": learn_obs.sgd_diag(aux, is_w, grads, updates,
                                       params),
        }
        return params, target_params, opt_state, step, aux["td_abs"], \
            metrics
