"""R2D2 sequence learner: sample -> unroll -> update -> priorities, one jit.

The recurrent counterpart of runtime/learner.DQNLearner (SURVEY.md §3.4,
config 4): sequences with stored LSTM state are items in the generic
device-resident prioritized replay, and one donated XLA graph fuses
stratified sequence sampling, the burn-in unroll, the n-step double-DQN
sequence loss with value rescaling, the optimizer update, the eta-mix
priority write-back, and the periodic target sync. The LSTM unroll is a
`lax.scan` inside the jit (models/lstm_q.py), so the whole train step is
a single device dispatch regardless of sequence length.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from ape_x_dqn_tpu.obs import learning as learn_obs
from ape_x_dqn_tpu.ops.losses import make_r2d2_loss
from ape_x_dqn_tpu.replay.sequence import batch_to_sequence_batch
from ape_x_dqn_tpu.runtime.learner import (SingleChipLearner, TrainState,
                                           make_optimizer)


class SequenceLearner(SingleChipLearner):
    """Jitted endpoints for the R2D2 sequence-replay learner.

    Reuses TrainState (the replay field holds sequence items,
    replay/sequence.sequence_item_spec) and inherits ALL step/K-batch/
    train_many/add machinery from SingleChipLearner — only the
    sequence-batch construction + R2D2 loss live here, so the K-batch
    semantics cannot drift from the flat-DQN learner's (round-4
    verdict missing #5).
    """

    def __init__(self, net_apply_seq: Callable, replay, lcfg, rcfg,
                 optimizer: optax.GradientTransformation | None = None):
        """net_apply_seq(params, obs[B,T,...], (c,h)) -> (q[B,T,A], state)."""
        self.net_apply_seq = net_apply_seq
        self.replay = replay
        self.lcfg = lcfg
        self.optimizer = optimizer or make_optimizer(lcfg)
        self.loss_fn = make_r2d2_loss(
            net_apply_seq, burn_in=rcfg.burn_in, n_step=lcfg.n_step,
            gamma=lcfg.gamma, huber_delta=lcfg.huber_delta,
            double=lcfg.double_dqn, rescale=lcfg.value_rescale,
            priority_eta=rcfg.priority_eta)

    def _sgd_step(self, params, target_params, opt_state, step,
                  items, is_w):
        """One unroll/loss/optimizer/target-sync update on an already-
        sampled sequence batch (shared by the exact per-step path and
        the K-batch relaxation). Returns the eta-mixed per-sequence
        |TD| priorities (aux['td_abs'])."""
        batch = batch_to_sequence_batch(items)
        (loss, aux), grads = jax.value_and_grad(
            self.loss_fn, has_aux=True)(
            params, target_params, batch, is_w)
        updates, opt_state = self.optimizer.update(
            grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        step = step + 1
        sync = (step % self.lcfg.target_sync_every == 0)
        target_params = jax.tree.map(
            lambda t, p: jnp.where(sync, p, t), target_params, params)
        metrics = {
            "loss": loss,
            "q_mean": aux["q_mean"],
            "td_abs_mean": aux["td_abs"].mean(),
            "valid_frac": aux["valid_frac"],
            "grad_norm": optax.global_norm(grads),
            # learning-health scalars; td quantiles here are over the
            # eta-mixed per-sequence priorities (the write-back signal)
            "diag": learn_obs.sgd_diag(aux, is_w, grads, updates,
                                       params),
        }
        return params, target_params, opt_state, step, aux["td_abs"], \
            metrics
