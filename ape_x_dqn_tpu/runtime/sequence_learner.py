"""R2D2 sequence learner: sample -> unroll -> update -> priorities, one jit.

The recurrent counterpart of runtime/learner.DQNLearner (SURVEY.md §3.4,
config 4): sequences with stored LSTM state are items in the generic
device-resident prioritized replay, and one donated XLA graph fuses
stratified sequence sampling, the burn-in unroll, the n-step double-DQN
sequence loss with value rescaling, the optimizer update, the eta-mix
priority write-back, and the periodic target sync. The LSTM unroll is a
`lax.scan` inside the jit (models/lstm_q.py), so the whole train step is
a single device dispatch regardless of sequence length.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from ape_x_dqn_tpu.ops.losses import make_r2d2_loss
from ape_x_dqn_tpu.replay.sequence import batch_to_sequence_batch
from ape_x_dqn_tpu.runtime.learner import TrainState, make_optimizer


class SequenceLearner:
    """Jitted endpoints for the R2D2 sequence-replay learner.

    Reuses TrainState: the replay field holds sequence items
    (replay/sequence.sequence_item_spec) instead of flat transitions.
    """

    def __init__(self, net_apply_seq: Callable, replay, lcfg, rcfg,
                 optimizer: optax.GradientTransformation | None = None):
        """net_apply_seq(params, obs[B,T,...], (c,h)) -> (q[B,T,A], state)."""
        if getattr(lcfg, "sample_chunk", 1) > 1:
            # fail loudly instead of silently training exact: the
            # K-batch relaxation is implemented for the flat-transition
            # learners (runtime/learner.py) and the dist learners
            # (parallel/dist_learner.py); sequence-replay learning
            # parity for it is unvalidated, so this learner does not
            # accept the config
            raise ValueError(
                "learner.sample_chunk > 1 is not implemented by the "
                "single-chip SequenceLearner — set sample_chunk=1 "
                "(the r2d2 preset default)")
        self.net_apply_seq = net_apply_seq
        self.replay = replay
        self.lcfg = lcfg
        self.optimizer = optimizer or make_optimizer(lcfg)
        self.loss_fn = make_r2d2_loss(
            net_apply_seq, burn_in=rcfg.burn_in, n_step=lcfg.n_step,
            gamma=lcfg.gamma, huber_delta=lcfg.huber_delta,
            double=lcfg.double_dqn, rescale=lcfg.value_rescale,
            priority_eta=rcfg.priority_eta)

    # -- state ------------------------------------------------------------

    def init(self, params: Any, replay_state, rng: jax.Array) -> TrainState:
        return TrainState(
            params=params,
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=self.optimizer.init(params),
            replay=replay_state,
            rng=rng,
            step=jnp.int32(0))

    # -- core step (pure) -------------------------------------------------

    def _train_step(self, state: TrainState) -> tuple[TrainState, dict]:
        rng, sk = jax.random.split(state.rng)
        items, idx, is_w = self.replay.sample(
            state.replay, sk, self.lcfg.batch_size)
        batch = batch_to_sequence_batch(items)
        (loss, aux), grads = jax.value_and_grad(
            self.loss_fn, has_aux=True)(
            state.params, state.target_params, batch, is_w)
        updates, opt_state = self.optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        # aux["td_abs"] already carries the eta-mixed sequence priority
        replay_state = self.replay.update_priorities(
            state.replay, idx, aux["td_abs"])
        step = state.step + 1
        sync = (step % self.lcfg.target_sync_every == 0)
        target_params = jax.tree.map(
            lambda t, p: jnp.where(sync, p, t), state.target_params, params)
        metrics = {
            "loss": loss,
            "q_mean": aux["q_mean"],
            "td_abs_mean": aux["td_abs"].mean(),
            "valid_frac": aux["valid_frac"],
            "grad_norm": optax.global_norm(grads),
        }
        new_state = TrainState(params, target_params, opt_state,
                               replay_state, rng, step)
        return new_state, metrics

    # -- jitted endpoints --------------------------------------------------

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state: TrainState):
        return self._train_step(state)

    @partial(jax.jit, static_argnums=(0, 2), donate_argnums=1)
    def train_many(self, state: TrainState, n: int):
        """n grad-steps in one dispatch via lax.scan (driver hot loop)."""
        def body(s, _):
            s, m = self._train_step(s)
            return s, m
        state, metrics = jax.lax.scan(body, state, None, length=n)
        return state, jax.tree.map(lambda x: x[-1], metrics)

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add(self, state: TrainState, items: Any,
            td_abs: jax.Array) -> TrainState:
        return state._replace(
            replay=self.replay.add(state.replay, items, td_abs))

    def publish_params(self, state: TrainState) -> Any:
        """Donation-safe param copy for the inference server."""
        return jax.tree.map(jnp.copy, state.params)
