"""Model-family dispatch shared by the driver and remote actor hosts.

A RunConfig's network kind selects one of three runtime families —
flat-DQN ("dqn"), recurrent R2D2 ("r2d2"), continuous Ape-X DPG
("dpg") — which differ in the inference-server protocol (plain Q-values
vs stateful {obs,c,h} vs {a,q} actor-critic), the actor class, and the
AOT-warmup example. ApexDriver (runtime/driver.py) and run_actor_host
(runtime/actor_host.py) must agree on all three, so the dispatch lives
here once.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.replay.frame_ring import (frame_ring_mode,
                                             frame_segment_spec)
from ape_x_dqn_tpu.replay.sequence import (sequence_frame_mode,
                                           sequence_item_spec)
from ape_x_dqn_tpu.runtime.actor import (
    Actor, ContinuousActor, RecurrentActor)
from ape_x_dqn_tpu.utils.rng import component_key


def family_of(cfg: RunConfig) -> str:
    return {"lstm_q": "r2d2", "dpg": "dpg"}.get(cfg.network.kind, "dqn")


def actor_class(family: str, vector: bool = False) -> type:
    """Actor implementation per family. vector=True selects the
    K-envs-per-thread vectorized actors (runtime/vector_actor.py),
    whose query contract is the server's `query_batch` (the recurrent
    variant ships {obs, c, h} pytrees with a leading [K] axis)."""
    if vector:
        from ape_x_dqn_tpu.runtime.vector_actor import (
            ContinuousVectorActor, RecurrentVectorActor, VectorActor)
        return {"r2d2": RecurrentVectorActor,
                "dpg": ContinuousVectorActor}.get(family, VectorActor)
    return {"r2d2": RecurrentActor, "dpg": ContinuousActor}.get(
        family, Actor)


def server_apply_fn(family: str, net: Any) -> Callable:
    """The batched forward the inference server jits, per family.

    - dqn:  obs [B, ...]          -> q [B, A]
    - r2d2: {obs, c, h}           -> {q, c, h}   (stateful step)
    - dpg:  obs [B, ...]          -> {a: mu(s), q: Q(s, mu(s))}
      (params are the {actor, critic} dict publish_params produces)
    """
    if family == "r2d2":
        def apply_rec(p, inp):
            q, (c, h) = net.apply(p, inp["obs"], (inp["c"], inp["h"]),
                                  method=net.step)
            return {"q": q, "c": c, "h": h}
        return apply_rec
    if family == "dpg":
        actor_net, critic_net = net

        def apply_dpg(p, obs):
            a = actor_net.apply(p["actor"], obs)
            q = critic_net.apply(p["critic"], obs, a)
            return {"a": a, "q": q}
        return apply_dpg
    return lambda p, obs: net.apply(p, obs)


def warmup_example(family: str, cfg: RunConfig, spec: Any) -> Any:
    """One server request pytree (no batch dim) for AOT warmup —
    shapes/dtypes only, content irrelevant."""
    obs = np.zeros(spec.obs_shape, spec.obs_dtype)
    if family == "r2d2":
        z = np.zeros(cfg.network.lstm_size, np.float32)
        return {"obs": obs, "c": z, "h": z}
    return obs


class FamilySetup(NamedTuple):
    """Per-family initial params, replay item layout, and ingest
    staging geometry — one source of truth for ApexDriver and
    MultihostApexDriver (they must agree with each other and with the
    actors shipping the items)."""
    params: Any
    item_spec: dict
    frame_mode: bool     # dqn family storing single-frame segments
    stage_chunk: int     # staging units per [dp-row] ingest block
    unit_items: int      # transitions per staging unit (fill counting)


def family_setup(cfg: RunConfig, spec: Any, net: Any,
                 obs0: np.ndarray) -> FamilySetup:
    """Initialize params and pick the replay item layout + staging
    chunk for cfg's family.

    frame_ring storage selects single-frame pixel layouts: for the
    flat-dqn family it swaps the item spec to whole frame segments
    (and the driver swaps the replay class); for r2d2 it only changes
    the sequence item content (single frames, stacks rebuilt in the
    learner jit) — same replay, same staging. DPG obs are
    low-dimensional, so frame_ring is rejected there.

    Staging units are transitions (flat), frame segments (frame mode),
    or whole sequences (r2d2) — for r2d2 the chunk scales ingest_batch
    down by seq_length because ingest_batch counts TRANSITIONS, and a
    [dp, ingest_batch] block of SEQUENCES would hold
    dp*ingest_batch*seq_length env steps and starve the learner
    waiting for the first add.
    """
    from ape_x_dqn_tpu.runtime.dpg_learner import continuous_item_spec
    from ape_x_dqn_tpu.runtime.learner import transition_item_spec

    family = family_of(cfg)
    if family == "r2d2":
        z = jnp.zeros((1, cfg.network.lstm_size), jnp.float32)
        params = net.init(component_key(cfg.seed, "net_init"),
                          obs0[None, None], (z, z))
        seq_frame_mode = sequence_frame_mode(cfg.replay.storage,
                                             spec.obs_shape)
        if cfg.replay.storage == "frame_ring" and not seq_frame_mode:
            raise ValueError(
                f"frame_ring sequence storage needs [H, W, stack] "
                f"pixel obs, got {spec.obs_shape}; set "
                f"replay.storage='flat' for vector observations")
        item_spec = sequence_item_spec(
            spec.obs_shape, spec.obs_dtype, cfg.replay.seq_length,
            cfg.network.lstm_size, frame_mode=seq_frame_mode)
        return FamilySetup(
            params, item_spec, False,
            max(cfg.actors.ingest_batch // cfg.replay.seq_length, 1), 1)
    if family == "dpg":
        if cfg.replay.storage == "frame_ring":
            raise NotImplementedError(
                "frame_ring storage is for pixel families (dqn/r2d2); "
                "use storage='flat' for dpg")
        actor_net, critic_net = net
        a0 = jnp.zeros((1, spec.action_dim), jnp.float32)
        params = (
            actor_net.init(component_key(cfg.seed, "actor_init"),
                           obs0[None]),
            critic_net.init(component_key(cfg.seed, "critic_init"),
                            obs0[None], a0))
        item_spec = continuous_item_spec(spec.obs_shape, spec.obs_dtype,
                                         spec.action_dim)
        return FamilySetup(params, item_spec, False,
                           max(cfg.actors.ingest_batch, 1), 1)
    # flat dqn
    params = net.init(component_key(cfg.seed, "net_init"), obs0[None])
    if cfg.replay.storage == "frame_ring":
        if cfg.replay.kind != "prioritized":
            raise NotImplementedError(
                "flat-family frame_ring storage requires prioritized "
                "replay")
        if not frame_ring_mode(cfg.replay.storage, spec.obs_shape):
            raise ValueError(
                f"frame_ring storage needs [H, W, stack] pixel obs, "
                f"got {spec.obs_shape}; set replay.storage='flat' for "
                f"vector observations")
        item_spec = frame_segment_spec(
            cfg.replay.seg_transitions, cfg.learner.n_step,
            spec.obs_shape, spec.obs_dtype)
        return FamilySetup(params, item_spec, True,
                           max(cfg.replay.segs_per_add, 1),
                           cfg.replay.seg_transitions)
    item_spec = transition_item_spec(spec.obs_shape, spec.obs_dtype)
    return FamilySetup(params, item_spec, False,
                       max(cfg.actors.ingest_batch, 1), 1)
