"""Model-family dispatch shared by the driver and remote actor hosts.

A RunConfig's network kind selects one of three runtime families —
flat-DQN ("dqn"), recurrent R2D2 ("r2d2"), continuous Ape-X DPG
("dpg") — which differ in the inference-server protocol (plain Q-values
vs stateful {obs,c,h} vs {a,q} actor-critic), the actor class, and the
AOT-warmup example. ApexDriver (runtime/driver.py) and run_actor_host
(runtime/actor_host.py) must agree on all three, so the dispatch lives
here once.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.runtime.actor import (
    Actor, ContinuousActor, RecurrentActor)


def family_of(cfg: RunConfig) -> str:
    return {"lstm_q": "r2d2", "dpg": "dpg"}.get(cfg.network.kind, "dqn")


def actor_class(family: str) -> type[Actor]:
    return {"r2d2": RecurrentActor, "dpg": ContinuousActor}.get(
        family, Actor)


def server_apply_fn(family: str, net: Any) -> Callable:
    """The batched forward the inference server jits, per family.

    - dqn:  obs [B, ...]          -> q [B, A]
    - r2d2: {obs, c, h}           -> {q, c, h}   (stateful step)
    - dpg:  obs [B, ...]          -> {a: mu(s), q: Q(s, mu(s))}
      (params are the {actor, critic} dict publish_params produces)
    """
    if family == "r2d2":
        def apply_rec(p, inp):
            q, (c, h) = net.apply(p, inp["obs"], (inp["c"], inp["h"]),
                                  method=net.step)
            return {"q": q, "c": c, "h": h}
        return apply_rec
    if family == "dpg":
        actor_net, critic_net = net

        def apply_dpg(p, obs):
            a = actor_net.apply(p["actor"], obs)
            q = critic_net.apply(p["critic"], obs, a)
            return {"a": a, "q": q}
        return apply_dpg
    return lambda p, obs: net.apply(p, obs)


def warmup_example(family: str, cfg: RunConfig, spec: Any) -> Any:
    """One server request pytree (no batch dim) for AOT warmup —
    shapes/dtypes only, content irrelevant."""
    obs = np.zeros(spec.obs_shape, spec.obs_dtype)
    if family == "r2d2":
        z = np.zeros(cfg.network.lstm_size, np.float32)
        return {"obs": obs, "c": z, "h": z}
    return obs
