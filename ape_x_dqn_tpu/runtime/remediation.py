"""Fleet remediation plane: the policy engine that closes the
monitor -> actuator loop (ROADMAP item 4).

Every sensor and every actuator in this codebase predates this module:
PR 7 built supervised restart / quarantine, PR 8 the attributed
`perf_degradation` events, PR 10 `learning_degradation`, PR 13 the
serve-SLO gauges plus priority shedding and `set_backpressure`. What
was missing is the connection — the monitors were warn-only and the
actuators manually or statically triggered. This engine runs inside
the driver's existing supervisor tick and maps attributed degradation
to BOUNDED actions:

    sensor                          rule              actuator
    ------------------------------  ----------------  -----------------
    stale local actor heartbeat     actor-wedge       restart_actor
    stale remote peer heartbeat     peer-stall        quarantine_peer
    perf_degradation events (peer)  peer-perf         quarantine_peer
    serve queue depth vs SLO        queue-slo         set_backpressure
    ingest drop pressure            ingest-pressure   pause/resume_actor
    learning_degradation events     learn-health      set_priority

Bounded means:
- hysteresis: gauge rules need `hysteresis_ticks` CONSECUTIVE
  agreeing supervisor ticks before an actuator moves, and again before
  it moves back — a sensor flapping breach/clear every tick holds a
  streak of +-1 forever and never trips anything;
- event windows: event rules need `event_threshold` attributed events
  on one target inside `event_window_s` — one noisy sample is not a
  policy decision;
- per-target cooldown: the same remedy is not re-applied to the same
  target within `cooldown_s` (a re-wedging actor falls back to the
  driver's own escalation ladder, which ends in quarantine);
- a global actions/minute token bucket for non-safety actions. SAFETY
  actions (restarting a wedged local slot, quarantining a stalled
  peer) bypass the bucket: suppressing them would leave a stale
  heartbeat for the watchdog to escalate into a run-fatal StallError —
  strictly worse than acting. They are still cooldown-limited and
  fully recorded.

Every decision is attributed in the run JSONL (`remediation` events
naming rule, target, action, outcome) and counted via remediation_*
instruments (obs/report.py INSTRUMENTS). Modes:
- "off": the driver never constructs the engine; the supervisor path
  is bitwise the pre-remediation one.
- "observe": the full decision pipeline runs and emits (outcome
  "observed"), but NO actuator is ever called — the dry run that
  builds trust before "enforce" is turned on.
- "enforce": actuators are called; outcome "applied" / "skipped"
  (actuator reported not-applicable) / "failed:<ExcName>" (actuator
  raised — never propagated into the supervisor tick).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

from ape_x_dqn_tpu.obs.health import make_lock


@dataclass
class Actuators:
    """The bounded actions the engine may take, as injected callables
    (the driver wires its own methods in; tests wire fakes). A missing
    callable makes the corresponding rules decide "unwired" — the
    engine degrades per-actuator, never crashes. A callable returning
    False means "looked, not applicable" (outcome "skipped")."""

    restart_actor: Callable[[int, float], Any] | None = None
    quarantine_peer: Callable[[str, float], Any] | None = None
    pause_actor: Callable[[int], Any] | None = None
    resume_actor: Callable[[int], Any] | None = None
    set_backpressure: Callable[[bool], Any] | None = None
    set_priority: Callable[[str, int], Any] | None = None


class RemediationEngine:
    """Declarative rule engine; one instance per driver, ticked from
    `_supervise_tick`. Thread-safe: `note_perf` / `note_learn` arrive
    from monitor fire paths on other threads. The engine lock is never
    held across an actuator call (actuators take the driver lock)."""

    def __init__(self, cfg, obs, metrics, actuators: Actuators,
                 default_class: int = 1,
                 clock: Callable[[], float] | None = None) -> None:
        self.cfg = cfg
        self.mode = cfg.mode
        self._obs = obs
        self._metrics = metrics
        self._act = actuators
        self._default_class = default_class
        self._clock = clock or time.monotonic
        self._lock = make_lock("remediation.engine")
        now = self._clock()
        # (target, label) -> time of last non-cooldown decision
        self._last_action: dict[tuple[str, str], float] = {}  # guarded-by: _lock
        # (target, label) -> time a budget-suppression was last EMITTED
        # (suppression repeats silently inside one cooldown window)
        self._last_suppress: dict[tuple[str, str], float] = {}  # guarded-by: _lock
        self._tokens = float(cfg.budget_per_min)  # guarded-by: _lock
        self._tokens_t = now  # guarded-by: _lock
        # (rule, target) -> recent event times, pruned to event_window_s
        self._events: dict[tuple[str, str], deque] = {}  # guarded-by: _lock
        # rule -> signed consecutive-tick streak (+breach / -clear)
        self._streaks: dict[str, int] = {}  # guarded-by: _lock
        self._bp_on = False  # guarded-by: _lock
        self._paused_at: dict[int, float] = {}  # guarded-by: _lock
        self._boosted: set[str] = set()  # guarded-by: _lock
        self._learn_last: dict[str, float] = {}  # guarded-by: _lock
        self._counts: dict[str, int] = {}  # guarded-by: _lock
        self._recent: deque = deque(maxlen=64)  # guarded-by: _lock

    # -- sensors: safety events (stale heartbeats) ----------------------

    def remediate_stale_actor(self, slot: int, staleness_s: float,
                              step: int = 0) -> bool:
        """A LOCAL actor thread went silent past the watchdog timeout.
        Returns True only when the restart actuator actually ran — the
        driver then skips its default path; any other outcome
        (observed / cooldown / failed / unwired) falls back to the
        pre-remediation supervisor, so a wedged slot is never left for
        check_stalled() to escalate."""
        out = self._decide("actor-wedge", f"actor-{slot}",
                           "restart_actor", step,
                           args=(slot, staleness_s), safety=True,
                           value=staleness_s)
        return out == "applied"

    def remediate_stale_peer(self, name: str, staleness_s: float,
                             step: int = 0) -> bool:
        """A REMOTE peer's re-beaten heartbeat went stale. Same
        True-means-handled contract as remediate_stale_actor."""
        out = self._decide("peer-stall", name, "quarantine_peer", step,
                           args=(name, staleness_s), safety=True,
                           value=staleness_s)
        return out == "applied"

    # -- sensors: attributed degradation events -------------------------

    def note_perf(self, name: str, value: float, baseline: float,
                  step: int = 0, peer: str = "") -> None:
        """PerfMonitor fire listener (obs/profiling.py). Only
        peer-attributed degradations have a bounded remedy (quarantine
        the degraded peer); local learner/ingest rate sags stay
        warn-only — there is no safe automatic action on the learner."""
        if self.mode == "off" or not peer:
            return
        if self._note_event("peer-perf", peer):
            self._decide("peer-perf", peer, "quarantine_peer", step,
                         args=(peer, 0.0), value=value,
                         baseline=baseline)

    def note_learn(self, rule: str, value: float, baseline: float,
                   step: int = 0, tenant: str = "") -> None:
        """LearnMonitor fire listener (obs/learning.py). Sustained
        learning-health sag on a tenant re-tempers its serving
        priority to the top class (its inference stops being shed
        first); tick() restores the default class after
        release_after_s of quiet."""
        if self.mode == "off" or not tenant:
            return
        now = self._clock()
        with self._lock:
            self._learn_last[tenant] = now
        if self._note_event("learn-health", tenant):
            out = self._decide("learn-health", tenant, "set_priority",
                               step, args=(tenant, 0),
                               label="boost_priority", value=value,
                               baseline=baseline)
            if out in ("applied", "observed"):
                with self._lock:
                    self._boosted.add(tenant)

    # -- the per-tick gauge rules ---------------------------------------

    def tick(self, sensors: dict, step: int = 0) -> None:
        """One supervisor-tick evaluation over gauge sensors. The
        driver builds `sensors` fresh each tick: queue_depth /
        queue_slo / backpressure (serving tier), ingest_dropped_delta,
        running_slots / paused_slots (local actor fleet)."""
        if self.mode == "off":
            return
        now = self._clock()
        self._tick_queue(sensors, step)
        self._tick_ingest(sensors, step, now)
        self._tick_releases(step, now)
        with self._lock:
            self._refill_locked(now)
            tokens = self._tokens
        self._obs.gauge("remediation_budget_headroom", round(tokens, 2))
        self._obs.gauge("remediation_mode",
                        2.0 if self.mode == "enforce" else 1.0)

    def _tick_queue(self, sensors: dict, step: int) -> None:
        depth = sensors.get("queue_depth")
        slo = sensors.get("queue_slo")
        if depth is None or not slo:
            return
        with self._lock:
            s = self._streak_locked("queue-slo", depth > slo)
            engaged = self._bp_on
        # in enforce mode trust the tier's real flag when reported (the
        # tier's own admission controller also moves it); the dry-run
        # state machine stands in everywhere else
        if self.mode == "enforce" and "backpressure" in sensors:
            engaged = bool(sensors["backpressure"])
        h = self.cfg.hysteresis_ticks
        if s >= h and not engaged:
            out = self._decide("queue-slo", "serving",
                               "set_backpressure", step, args=(True,),
                               label="engage_backpressure",
                               value=depth, baseline=slo)
            if out in ("applied", "observed"):
                with self._lock:
                    self._bp_on = True
        elif -s >= h and engaged:
            out = self._decide("queue-slo", "serving",
                               "set_backpressure", step, args=(False,),
                               label="release_backpressure",
                               value=depth, baseline=slo)
            if out in ("applied", "observed"):
                with self._lock:
                    self._bp_on = False

    def _tick_ingest(self, sensors: dict, step: int,
                     now: float) -> None:
        drops = sensors.get("ingest_dropped_delta")
        if drops is None:
            return
        running = sorted(sensors.get("running_slots") or ())
        paused = sorted(sensors.get("paused_slots") or ())
        with self._lock:
            s = self._streak_locked("ingest-pressure", drops > 0)
        h = self.cfg.hysteresis_ticks
        if s >= h and len(running) > max(self.cfg.min_actors, 0):
            slot = running[-1]  # downscale from the top of the schedule
            out = self._decide("ingest-pressure", f"actor-{slot}",
                               "pause_actor", step, args=(slot,),
                               value=drops)
            if out in ("applied", "observed"):
                with self._lock:
                    self._paused_at[slot] = now
        elif -s >= h and paused:
            slot = paused[0]
            out = self._decide("ingest-pressure", f"actor-{slot}",
                               "resume_actor", step, args=(slot,))
            if out in ("applied", "observed"):
                with self._lock:
                    self._paused_at.pop(slot, None)

    def _tick_releases(self, step: int, now: float) -> None:
        """Unwind engaged remedies after release_after_s of quiet:
        boosted tenant priorities revert to the default class, and a
        paused slot whose pressure signal went away (or stopped being
        reported) resumes on timeout even if the clear-streak path
        never fires."""
        rel = self.cfg.release_after_s
        with self._lock:
            restore = [t for t in self._boosted
                       if now - self._learn_last.get(t, now) >= rel]
            stale_pause = [i for i, t0 in self._paused_at.items()
                           if now - t0 >= rel]
        for tenant in restore:
            out = self._decide("learn-health", tenant, "set_priority",
                               step, args=(tenant, self._default_class),
                               label="restore_priority")
            if out in ("applied", "observed"):
                with self._lock:
                    self._boosted.discard(tenant)
        for slot in stale_pause:
            out = self._decide("ingest-pressure", f"actor-{slot}",
                               "resume_actor", step, args=(slot,))
            if out in ("applied", "observed"):
                with self._lock:
                    self._paused_at.pop(slot, None)

    # -- the decision core ----------------------------------------------

    def _decide(self, rule: str, target: str, action: str, step: int,
                args: tuple = (), label: str | None = None,
                safety: bool = False, value=None,
                baseline=None) -> str:
        """Gate one would-be action through mode, per-target cooldown
        and (non-safety) the global budget; emit the attributed event
        and counters; in enforce mode, run the actuator."""
        label = label or action
        now = self._clock()
        outcome: str | None
        emit = True
        with self._lock:
            key = (target, label)
            if now - self._last_action.get(key, float("-inf")) \
                    < self.cfg.cooldown_s:
                # the rate limiter doing its job is bookkept, not an
                # event — a persisting breach would otherwise spam one
                # JSONL line per supervisor tick
                self._bump_locked(rule, target, label, "cooldown")
                return "cooldown"
            self._refill_locked(now)
            if not safety and self._tokens < 1.0:
                outcome = "suppressed:budget"
                # visible at most once per cooldown window per target
                emit = now - self._last_suppress.get(
                    key, float("-inf")) >= self.cfg.cooldown_s
                if emit:
                    self._last_suppress[key] = now
            else:
                if not safety:
                    self._tokens -= 1.0
                self._last_action[key] = now
                outcome = ("observed" if self.mode == "observe"
                           else None)
        if outcome is None:  # enforce: act, outside the engine lock
            outcome = self._apply(action, args)
        self._emit(rule, target, label, outcome, step, value, baseline,
                   emit=emit)
        return outcome

    def _apply(self, action: str, args: tuple) -> str:
        """Enforce-mode actuator dispatch. The literal call sites here
        are what tools/apexlint's remediation-accounting checker
        audits: every actuator invocation is co-located with its
        remediation_* counter bump."""
        act = self._act
        try:
            if action == "restart_actor" \
                    and act.restart_actor is not None:
                out = act.restart_actor(*args)
            elif action == "quarantine_peer" \
                    and act.quarantine_peer is not None:
                out = act.quarantine_peer(*args)
            elif action == "pause_actor" \
                    and act.pause_actor is not None:
                out = act.pause_actor(*args)
            elif action == "resume_actor" \
                    and act.resume_actor is not None:
                out = act.resume_actor(*args)
            elif action == "set_backpressure" \
                    and act.set_backpressure is not None:
                out = act.set_backpressure(*args)
            elif action == "set_priority" \
                    and act.set_priority is not None:
                out = act.set_priority(*args)
            else:
                return "unwired"
        except Exception as e:  # noqa: BLE001 - never crash the tick
            self._obs.count("remediation_failed")
            return f"failed:{type(e).__name__}"
        if out is False:
            return "skipped"
        self._obs.count("remediation_actions")
        return "applied"

    def _emit(self, rule: str, target: str, label: str, outcome: str,
              step: int, value, baseline, emit: bool = True) -> None:
        with self._lock:
            self._bump_locked(rule, target, label, outcome)
        if not emit:
            return
        if outcome == "observed":
            self._obs.count("remediation_observed")
        elif outcome.startswith("suppressed"):
            self._obs.count("remediation_suppressed")
        # applied / failed counters are bumped at the actuator call
        # site in _apply (the accounting the lint checker pins there)
        if self._metrics is None:
            return
        kw: dict[str, Any] = {"remediation": rule,
                              "remediation_target": target,
                              "remediation_action": label,
                              "remediation_outcome": outcome}
        if value is not None:
            kw["remediation_value"] = round(float(value), 6)
        if baseline is not None:
            kw["remediation_baseline"] = round(float(baseline), 6)
        self._metrics.log(step, **kw)

    # -- internals -------------------------------------------------------

    def _note_event(self, rule: str, target: str) -> bool:
        """Record one attributed event; True when the (rule, target)
        pair crossed event_threshold inside the sliding window."""
        now = self._clock()
        with self._lock:
            dq = self._events.setdefault((rule, target),
                                         deque(maxlen=32))
            dq.append(now)
            while dq and now - dq[0] > self.cfg.event_window_s:
                dq.popleft()
            return len(dq) >= self.cfg.event_threshold

    def _streak_locked(self, rule: str, breach: bool) -> int:
        s = self._streaks.get(rule, 0)
        if breach:
            s = s + 1 if s > 0 else 1
        else:
            s = s - 1 if s < 0 else -1
        self._streaks[rule] = s  # apexlint: unguarded(caller holds _lock)
        return s

    def _refill_locked(self, now: float) -> None:
        rate = self.cfg.budget_per_min / 60.0
        self._tokens = min(float(self.cfg.budget_per_min),  # apexlint: unguarded(caller holds _lock)
                           self._tokens
                           + (now - self._tokens_t) * rate)
        self._tokens_t = now  # apexlint: unguarded(caller holds _lock)

    def _bump_locked(self, rule: str, target: str, label: str,
                     outcome: str) -> None:
        base = outcome.split(":", 1)[0]
        self._counts[base] = self._counts.get(base, 0) + 1  # apexlint: unguarded(caller holds _lock)
        self._recent.append((rule, target, label, outcome))

    def summary(self) -> dict:
        """Final accounting for the driver's result dict."""
        with self._lock:
            by_rule: dict[str, int] = {}
            for rule, _t, _l, out in self._recent:
                if out.split(":", 1)[0] in ("applied", "observed"):
                    by_rule[rule] = by_rule.get(rule, 0) + 1
            return {"mode": self.mode,
                    "counts": dict(self._counts),
                    "decided_by_rule": by_rule,
                    "budget_tokens": round(self._tokens, 2),
                    "recent": [list(r) for r in self._recent]}
