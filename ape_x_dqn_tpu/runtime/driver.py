"""Ape-X orchestration: actors + inference server + ingest + learner.

The reference spawns replay/learner/actor *processes* glued by gRPC
(SURVEY.md §3.1); here the single-host runtime uses threads around the
device-resident replay — the TPU does all heavy work (batched inference,
the fused learner jit), so Python threads only shuttle numpy batches and
are not a bottleneck; the process/host boundary lives behind the
Transport interface (comm/), which multi-host deployments swap for the
socket transport over DCN.

Threads:
- N actor threads: env stepping + priority bookkeeping (runtime/actor.py)
- 1 ingest thread: transport -> learner.add (device ring + sum-tree)
- 1 learner thread: train_step loop + periodic param publication
- eval worker (runtime/evaluation.py) runs greedy episodes on demand
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from collections import deque
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.comm.socket_transport import batch_rows
from ape_x_dqn_tpu.comm.transport import LoopbackTransport
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.models import build_network
from ape_x_dqn_tpu.obs.core import build_obs
from ape_x_dqn_tpu.obs.fleet import MAX_SPAN_IDS, FleetAggregator
from ape_x_dqn_tpu.obs.health import make_lock
from ape_x_dqn_tpu.parallel.dist_learner import (
    DistDQNLearner, DistSequenceLearner)
from ape_x_dqn_tpu.parallel.inference_server import (
    BatchedInferenceServer, MultiPolicyInferenceServer, build_serving_tier)
from ape_x_dqn_tpu.parallel.mesh import make_mesh
from ape_x_dqn_tpu.replay.cold_store import ColdStore
from ape_x_dqn_tpu.replay.frame_ring import FrameRingReplay
from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
from ape_x_dqn_tpu.runtime.family import (
    actor_class, family_of, family_setup, server_apply_fn,
    warmup_example)
from ape_x_dqn_tpu.runtime.dpg_learner import DPGLearner
from ape_x_dqn_tpu.runtime.evaluation import (
    EvalWorker, make_eval_policy_factory)
from ape_x_dqn_tpu.runtime.ingest import IngestStager
from ape_x_dqn_tpu.runtime.learner import DQNLearner
from ape_x_dqn_tpu.runtime.remediation import (
    Actuators, RemediationEngine)
from ape_x_dqn_tpu.runtime.sequence_learner import SequenceLearner
from ape_x_dqn_tpu.runtime.single_process import build_replay
from ape_x_dqn_tpu.utils.checkpoint import CheckpointManager
from ape_x_dqn_tpu.utils.hbm import check_hbm_fits
from ape_x_dqn_tpu.utils.metrics import (
    Metrics, Throughput, log_run_header)
from ape_x_dqn_tpu.utils.misc import next_pow2
from ape_x_dqn_tpu.utils.rng import component_key

# reusable no-op context for the unprofiled (default) ship path
_NULL_CM = contextlib.nullcontext()


def build_prioritized_replay(cfg: RunConfig, spec, capacity: int,
                             frame_mode: bool):
    """Prioritized replay at `capacity` (single-chip total or per-dp
    shard) in the configured storage layout. Shared by ApexDriver and
    the multihost driver."""
    r = cfg.replay
    if frame_mode:
        return FrameRingReplay(
            capacity=capacity, seg_transitions=r.seg_transitions,
            n_step=cfg.learner.n_step,
            obs_shape=spec.obs_shape, obs_dtype=spec.obs_dtype,
            alpha=r.alpha, beta=r.beta, eps=r.eps)
    return PrioritizedReplay(capacity=capacity, alpha=r.alpha,
                             beta=r.beta, eps=r.eps)


class ApexDriver:
    def __init__(self, cfg: RunConfig, metrics: Metrics | None = None,
                 transport=None):
        """transport: a comm Transport for experience ingest + param
        distribution; defaults to in-process LoopbackTransport. Pass a
        comm.socket_transport.SocketIngestServer to also accept remote
        actor hosts over DCN."""
        self.cfg = cfg
        self.metrics = metrics or Metrics()
        # observability facade (obs/): NULL_OBS unless cfg.obs.enabled,
        # so every span/beat below is a no-op method call when off
        self.obs = build_obs(getattr(cfg, "obs", None), self.metrics)
        probe_env = make_env(cfg.env, seed=cfg.seed)
        self.spec = probe_env.spec
        self.net = build_network(cfg.network, self.spec)
        obs0 = probe_env.reset()
        # model family: flat-transition DQN, stored-state sequences (R2D2),
        # or continuous-control actor-critic (Ape-X DPG). family_setup
        # owns params init + replay item layout + staging geometry
        # (shared with the multihost driver).
        self.family = family_of(cfg)
        setup = family_setup(cfg, self.spec, self.net, obs0)
        params, item_spec = setup.params, setup.item_spec
        self._frame_mode = setup.frame_mode
        self._item_keys = tuple(item_spec.keys())
        self.dp = cfg.parallel.dp
        self.is_dist = cfg.parallel.dp * cfg.parallel.tp > 1
        # early, loud HBM fits-check: the replay + model state must fit
        # the device BEFORE any allocation happens (utils/hbm.py; round-4
        # verdict missing #3 — a preset that outsizes its chip should
        # fail with a budget table, not an allocator abort mid-run)
        check_hbm_fits(
            cfg, self.spec.obs_shape, self.spec.obs_dtype,
            param_count=sum(int(np.prod(l.shape))
                            for l in jax.tree.leaves(params)))
        if self.is_dist and self.family == "dpg":
            raise NotImplementedError(
                "the distributed learner covers the DQN and R2D2 "
                "families; DPG nets are small — run dp=tp=1")
        if self.is_dist:
            # Multi-chip learner (SURVEY.md §7 step 7): replay shards +
            # batch shards + gradient psum over the (dp, tp) mesh; ingest
            # round-robins actor staging units across the dp replay
            # shards (dist_learner.py contract: items arrive [dp, B, ...]).
            # R2D2's "sequence" replay is the same prioritized machinery
            # with whole sequences as items.
            assert cfg.replay.kind in ("prioritized", "sequence"), \
                "distributed learner requires prioritized replay " \
                "(kind='prioritized', or kind='sequence' for R2D2)"
            self.mesh = make_mesh(dp=cfg.parallel.dp, tp=cfg.parallel.tp)
            shard_cap = next_pow2(max(cfg.replay.capacity // self.dp, 2))
            self.replay = self._build_prioritized(shard_cap)
            if self.family == "r2d2":
                self.learner = DistSequenceLearner(
                    lambda p, o, s: self.net.apply(p, o, s),
                    self.replay, cfg.learner, cfg.replay, self.mesh)
            else:
                self.learner = DistDQNLearner(self.net.apply, self.replay,
                                              cfg.learner, self.mesh)
            self.state = self.learner.init(  # guarded-by: _state_lock
                params, item_spec, component_key(cfg.seed, "learner"))
            self.capacity = shard_cap * self.dp
            # publish_params already returns an independent replicated
            # copy; handing it to the server directly keeps params on
            # device through the warm-up phase (no host round-trip)
            server_params = self.learner.publish_params(self.state)
        else:
            self.replay = (self._build_prioritized(
                               next_pow2(cfg.replay.capacity))
                           if self._frame_mode else build_replay(cfg.replay))
            lkey = component_key(cfg.seed, "learner")
            if self.family == "r2d2":
                self.learner = SequenceLearner(
                    lambda p, o, s: self.net.apply(p, o, s),
                    self.replay, cfg.learner, cfg.replay)
                self.state = self.learner.init(
                    params, self.replay.init(item_spec), lkey)
            elif self.family == "dpg":
                actor_net, critic_net = self.net
                self.learner = DPGLearner(
                    actor_net.apply, critic_net.apply, self.replay,
                    cfg.learner)
                self.state = self.learner.init(
                    params[0], params[1], self.replay.init(item_spec), lkey)
            else:
                self.learner = DQNLearner(self.net.apply, self.replay,
                                          cfg.learner)
                self.state = self.learner.init(
                    params, self.replay.init(item_spec), lkey)
            self.capacity = self.replay.capacity
            # The learner jits donate the TrainState (learner.py
            # train_step/add, donate_argnums=1), which deletes the donated
            # param buffers — the server must own an independent copy or
            # its first forward after an ingest raises "Array has been
            # deleted" on TPU. publish_params copies.
            server_params = self.learner.publish_params(self.state)
        server_mesh = self.mesh if (self.is_dist
                                    and cfg.inference.shard_over_mesh) \
            else None
        # cfg.serving.multi_tenant swaps the single-policy server for the
        # serving tier; this driver's policy registers under env.id and
        # self.server stays signature-compatible (a TenantClient), so the
        # actor/eval/param-publish paths below are tenancy-oblivious.
        # Co-tenants (rotation heads, eval policies) register into
        # self.serving alongside it.
        self.serving: MultiPolicyInferenceServer | None = None
        if cfg.serving.multi_tenant:
            self.serving = build_serving_tier(
                cfg.serving,
                max_batch=cfg.inference.max_batch,
                deadline_ms=cfg.inference.deadline_ms,
                mesh=server_mesh,
                obs=self.obs)
            self.server = self.serving.register_policy(
                cfg.env.id, self._server_apply_fn(), server_params,
                family=self.family, priority=cfg.serving.default_class)
        else:
            self.server = BatchedInferenceServer(
                self._server_apply_fn(),
                server_params,
                max_batch=cfg.inference.max_batch,
                deadline_ms=cfg.inference.deadline_ms,
                mesh=server_mesh,
                obs=self.obs)
        self.transport = transport if transport is not None \
            else LoopbackTransport()
        # fleet telemetry plane (obs/fleet.py): with obs on and a
        # telemetry-capable transport, remote peers' snapshot frames
        # merge into this run's JSONL under peer/<id>/ keys and their
        # heartbeats feed the stall watchdog below
        self.fleet: FleetAggregator | None = None
        if self.obs.enabled:
            agg = FleetAggregator(self.obs)
            if agg.install(self.transport):
                self.fleet = agg
        # forensics plane (obs/blackbox.py): the driver's flight
        # recorder dumps on crash/atexit/SIGUSR2, and every dump
        # carries the fleet's retained per-peer telemetry frames — the
        # black box of last resort for peers that died without one
        self.obs.blackbox.set_peer(f"driver-{os.getpid()}")
        if self.fleet is not None:
            self.obs.blackbox.add_context_provider(
                lambda: {"peer_frames": self.fleet.retained_frames()})
        self.obs.blackbox.install()
        # initial publication so remote actor hosts can bootstrap before
        # the learner's first publish_every boundary (they block on
        # get_params); both sides only read these buffers
        self.transport.publish_params(server_params, 0)
        self.stop_event = threading.Event()
        # shared-counter lock (actor/ingest/learner/eval threads all
        # stamp progress here); _state_lock serializes train-state
        # swaps against checkpoint writes. Writes to the annotated
        # attributes outside `with self.<lock>:` are apexlint failures.
        self._lock = make_lock("driver._lock")
        self._state_lock = make_lock("driver._state_lock")
        self.episode_returns: deque[float] = deque(maxlen=200)  # guarded-by: _lock
        self.frames = Throughput(window_s=30.0)
        self.grad_steps = Throughput(window_s=30.0)
        # rows actually landed in replay (post-drop, post-coalesce) —
        # the perf-regression engine's local ingest baseline
        self.ingest_rows = Throughput(window_s=30.0)
        # sampled block_until_ready windows on the ingest ship path are
        # OFF by default: the zero-copy stager's whole point is decode/
        # transfer overlap, and an every-dispatch sync would serialize it
        ocfg = getattr(cfg, "obs", None)
        self._ship_window_every = (
            max(getattr(ocfg, "profile_window_every", 16), 1)
            if getattr(ocfg, "profile_windows", False) else 0)
        self._ship_seq = 0  # ingest thread only
        self._frames_total = 0  # guarded-by: _lock
        self._grad_steps_total = 0
        self.actor_errors: list[tuple[int, Exception]] = []  # guarded-by: _lock
        self.actor_restarts: list[tuple[int, str]] = []  # guarded-by: _lock
        self.loop_errors: list[tuple[str, Exception]] = []  # guarded-by: _lock
        # fleet supervisor state (run()'s poll loop consumes heartbeat
        # staleness instead of raising for every silent component):
        # each actor SLOT has its own stop event + thread generation so
        # a wedged worker can be superseded in place — the old thread,
        # if it ever un-wedges, sees its generation's event set and
        # exits instead of double-producing
        self._slot_stops: dict[int, threading.Event] = {}  # guarded-by: _lock
        self._slot_threads: dict[int, threading.Thread] = {}  # guarded-by: _lock
        self._slot_budget: dict[int, int] = {}  # guarded-by: _lock
        self._slot_actor_obj: dict[int, Any] = {}  # guarded-by: _lock
        # frames produced by FINISHED attempts of the slot's current
        # generation (crash-restarts); the live attempt's count lives on
        # the actor object itself
        self._slot_done: dict[int, int] = {}  # guarded-by: _lock
        self._slot_restarts: dict[int, int] = {}  # guarded-by: _lock
        self._quarantined: set[int] = set()  # guarded-by: _lock
        self._peer_quarantined: set[str] = set()  # guarded-by: _lock
        # remediation-paused slots: slot -> remaining frame budget (the
        # ingest-pressure autoscale rule parks the slot; resume respawns
        # it with this budget). Distinct from _quarantined: paused is
        # reversible and healthy, quarantined is exhausted.
        self._slot_paused: dict[int, int] = {}  # guarded-by: _lock
        # last transport+stage drop total the remediation sensor saw
        # (supervisor tick thread only — no lock needed)
        self._remed_dropped_seen = 0
        # fleet remediation plane (runtime/remediation.py, ROADMAP item
        # 4): the policy engine closing the monitor->actuator loop
        # inside _supervise_tick. mode="off" (the default) never
        # constructs it — the supervisor path stays bitwise the
        # pre-remediation one. Actuators are this driver's own bounded
        # methods; the monitors' fire listeners feed the event rules.
        self.remediation: RemediationEngine | None = None
        rcfg = getattr(cfg, "remediation", None)
        if rcfg is not None and rcfg.mode != "off":
            self.remediation = RemediationEngine(
                rcfg, obs=self.obs, metrics=self.metrics,
                actuators=Actuators(
                    restart_actor=self._supervise_actor,
                    quarantine_peer=self._quarantine_peer,
                    pause_actor=self._pause_actor_slot,
                    resume_actor=self._resume_actor_slot,
                    set_backpressure=self._remediation_backpressure,
                    set_priority=self._remediation_set_priority),
                default_class=cfg.serving.default_class)
            if getattr(self.obs, "perf", None) is not None:
                self.obs.perf.add_listener(self.remediation.note_perf)
            if getattr(self.obs, "learn", None) is not None:
                self.obs.learn.add_listener(self.remediation.note_learn)
        self._ingested_batches = 0  # guarded-by: _lock
        # host-side mirror of replay fill so the learner hot loop never
        # blocks on a device->host read of state.replay.size (round-1
        # verdict "weak" #4: that sync serialized every iteration)
        self._replay_filled = 0  # guarded-by: _lock
        # ingest staging: staging units accumulate host-side until a full
        # fixed-size block ships to the device in one add — [dp, chunk]
        # on the mesh, [chunk] single-chip. Fixed block shapes matter:
        # actors ship ragged batch sizes, and every distinct size would
        # compile a fresh add graph (20-40s each on TPU). A staging unit
        # is one transition (flat storage) or one whole frame segment of
        # seg_transitions transitions (frame-ring storage).
        self._stage: list[dict] = []
        self._stage_n = 0
        self._stage_chunk = setup.stage_chunk
        self._unit_items = setup.unit_items
        self._stage_dropped = 0
        # the same staged drops attributed per dp shard: unit i of a
        # would-be [dp, chunk] block lands on shard i // stage_chunk
        # (the round-robin split _ship_staged reshapes into), so the
        # closure sum(_stage_dropped_per_shard) == _stage_dropped holds
        # in every denomination (pinned by tests/test_ingest.py)
        self._stage_dropped_per_shard = np.zeros(self.dp, np.int64)
        # roofline stage vocabulary: the dist learner's fused dispatch
        # attributes under its own stage so a mesh run's gauges are
        # distinguishable from single-chip "train" (obs/profiling.py)
        self._train_stage = "train_dist" if self.is_dist else "train"
        self._item_spec = item_spec
        # zero-copy pipelined staging (runtime/ingest.py): wire batches
        # decode directly into preallocated [coalesce*block] buffers,
        # double-buffered against the async host->device transfer, and
        # full buffers ship as ONE coalesced add_many dispatch.
        # ingest_zero_copy=False restores the legacy list-append +
        # concatenate-per-flush staging (compat escape hatch).
        self._stager: IngestStager | None = None
        if getattr(cfg.replay, "ingest_zero_copy", True):
            ptail = (cfg.replay.seg_transitions,) if self._frame_mode \
                else ()
            self._stager = IngestStager(
                item_spec, ptail,
                block_units=self.dp * self._stage_chunk,
                coalesce=getattr(cfg.replay, "ingest_coalesce", 4),
                buffers=getattr(cfg.replay, "stage_buffers", 2),
                ship=self._ship_staged)
        # tiered cold store (replay/cold_store.py; ROADMAP item 3):
        # host-RAM compressed segments behind the ring, default OFF.
        # With the tier on and the ring full, every ship evicts the
        # ring's lowest-priority-mass region to the cold store and the
        # idle refill tick recalls the highest-mass cold segments back
        # through the stager. All cold counters are transition-
        # denominated and touched by the ingest thread only; the pinned
        # closure `evicted == stored + dropped` is tested in
        # tests/test_ingest.py (door outcomes — displacements of
        # already-stored segments are the store's own counter).
        self._cold: ColdStore | None = None
        self._disk = None        # disk-spill rung (replay/disk_store.py)
        # apexlint: closure(_cold_evicted == _cold_stored + _cold_dropped)
        self._cold_evicted = 0   # ingest thread only
        self._cold_stored = 0    # ingest thread only
        self._cold_dropped = 0   # ingest thread only
        self._cold_recalled = 0  # ingest thread only
        # the same door outcomes attributed per dp shard (the dist
        # eviction swap runs per shard, so the closure holds per shard:
        # evicted[d] == stored[d] + dropped[d] — the PR-9
        # ingest_dropped_per_shard idiom extended to the cold door)
        # apexlint: closure(_cold_evicted_per_shard == _cold_stored_per_shard + _cold_dropped_per_shard)
        self._cold_evicted_per_shard = np.zeros(self.dp, np.int64)
        self._cold_stored_per_shard = np.zeros(self.dp, np.int64)
        self._cold_dropped_per_shard = np.zeros(self.dp, np.int64)
        # last-seen store counters for delta-emitted obs ctrs
        self._cold_dropped_seen = 0
        self._cold_displaced_seen = 0
        self._disk_seen: dict = {}
        cold_cap = getattr(cfg.replay, "cold_tier_capacity", 0)
        if cold_cap > 0:
            if self.family != "dqn" or not getattr(
                    self.replay, "has_priorities", False):
                raise NotImplementedError(
                    "the cold tier needs prioritized flat/frame-ring "
                    "DQN replay (priority-mass eviction has no meaning "
                    "without a sum tree); set cold_tier_capacity=0 for "
                    f"family={self.family!r}, kind={cfg.replay.kind!r}")
            if self._stager is None:
                raise ValueError(
                    "the cold tier refills through the zero-copy ingest "
                    "stager — replay.ingest_zero_copy=False and "
                    "cold_tier_capacity > 0 are incompatible")
            disk_cap = getattr(cfg.replay, "cold_tier_disk_capacity", 0)
            if disk_cap > 0:
                from ape_x_dqn_tpu.replay.disk_store import DiskStore
                self._disk = DiskStore(
                    cfg.replay.cold_tier_disk_dir, disk_cap,
                    queue_depth=getattr(cfg.replay,
                                        "cold_tier_disk_queue", 16),
                    file_bytes=getattr(cfg.replay,
                                       "cold_tier_disk_file_bytes",
                                       64 * 1024 * 1024),
                    compact_frac=getattr(cfg.replay,
                                         "cold_tier_disk_compact_frac",
                                         0.5))
            self._cold = ColdStore(
                item_spec, cold_cap, unit_items=self._unit_items,
                ptail=ptail,
                compress_level=getattr(cfg.replay,
                                       "cold_tier_compress_level", 1),
                spill=self._disk)
        # profiler capture state: False = armed, True = tracing,
        # None = finished/disabled (single capture per run)
        self._profiling: bool | None = False if cfg.profile_dir else None
        self._profile_from = 0
        self.last_eval: dict | None = None  # guarded-by: _lock
        # checkpoint/resume (SURVEY.md §5): params/targets/opt/rng/step
        # always; replay contents too when cfg.checkpoint_replay (off by
        # default — large, and Ape-X tolerates refilling; opt in to skip
        # the min_fill stall and keep the replay distribution continuous)
        self.ckpt = (CheckpointManager(cfg.checkpoint_dir)
                     if cfg.checkpoint_dir else None)
        if self.ckpt is not None:
            self._maybe_restore()

    def _build_prioritized(self, capacity: int):
        return build_prioritized_replay(self.cfg, self.spec, capacity,
                                        self._frame_mode)

    # -- checkpoint / resume ----------------------------------------------

    @staticmethod
    def _dev_copy(x):
        # typed PRNG keys can't cross to numpy directly; store key data
        if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            return jnp.copy(jax.random.key_data(x))
        return jnp.copy(x)

    def _ckpt_payload(self, with_replay: bool | None = None) -> dict:
        """Host copy of the train state, donation-safe. Replay contents
        ride along only when cfg.checkpoint_replay (they dominate the
        payload size — see the config comment); restores override
        `with_replay` to follow what the checkpoint actually saved.

        Only a fast on-device jnp.copy happens under the state lock (an
        aliased buffer would be deleted by the next donating train/add
        jit); the device->host transfer for the Orbax write runs outside
        it so checkpointing never stalls the learner hot loop."""
        if with_replay is None:
            with_replay = self.cfg.checkpoint_replay
        skip = () if with_replay else ("replay",)
        with self._state_lock:
            dev = {k: jax.tree.map(self._dev_copy, v)
                   for k, v in self.state._asdict().items()
                   if k not in skip}
        return {k: jax.tree.map(np.asarray, v) for k, v in dev.items()}

    def _save_checkpoint(self, wait: bool = False) -> None:
        with self.obs.span("ckpt.save", step=self._grad_steps_total):
            self.ckpt.save(self._grad_steps_total, self._ckpt_payload(),
                           wait=wait)

    def _maybe_restore(self) -> None:
        if self.ckpt.latest_step() is None:
            return  # fresh start: skip building the (host-copy) template
        # the template must mirror what was SAVED, not the current
        # checkpoint_replay flag: a toggled flag would otherwise hand
        # Orbax a structure-mismatched template and brick resume. The
        # flag governs saves; restores follow the file (an old
        # replay-bearing checkpoint restores its contents even with the
        # flag now off). Unknowable metadata falls back to the flag.
        saved = self.ckpt.item_keys()
        with_replay = (("replay" in saved) if saved is not None
                       else self.cfg.checkpoint_replay)
        template = self._ckpt_payload(with_replay=with_replay)
        with self.obs.span("ckpt.restore"):
            restored = self.ckpt.restore(template=template)
        if restored is None:
            return
        # land each leaf back on device with the layout the learner state
        # already has (replicated/sharded alike), then resume the counter
        def put_leaf(x, ref):
            if jnp.issubdtype(ref.dtype, jax.dtypes.prng_key):
                x = jax.random.wrap_key_data(jnp.asarray(x))
            return jax.device_put(jnp.asarray(x), ref.sharding)

        with self._state_lock:
            put = {
                k: jax.tree.map(lambda x, ref: put_leaf(x, ref),
                                v, getattr(self.state, k))
                for k, v in restored.items()}
            self.state = self.state._replace(**put)
        self._grad_steps_total = int(np.asarray(restored["step"]))
        if "replay" in restored:
            # restored contents: the learner can resume training
            # immediately instead of re-paying the min_fill stall
            with self._lock:
                self._replay_filled = int(
                    np.sum(np.asarray(restored["replay"].size)))
        self._publish_params()

    # -- components --------------------------------------------------------

    def _server_apply_fn(self):
        """The batched forward the inference server jits (family.py)."""
        return server_apply_fn(self.family, self.net)

    def _make_eval_worker(self, game: str | None = None) -> EvalWorker:
        factory = make_eval_policy_factory(
            self.family, self.cfg.network.lstm_size, self.server.query)
        return EvalWorker(self.cfg, self.server.query, game=game,
                          policy_factory=factory)


    def _on_episode(self, actor_index: int, info: dict) -> None:
        with self._lock:
            self.episode_returns.append(float(info["episode_return"]))

    def _spawn_actor_slot(self, i: int, max_frames: int,
                          attempt0: int = 0) -> threading.Thread:
        """Start (or restart) actor slot i with its own generation stop
        event. The fleet supervisor supersedes a wedged slot by setting
        the OLD generation's event and spawning a new one; the global
        teardown sets every slot event (run()'s finally)."""
        ev = threading.Event()
        t = threading.Thread(target=self._actor_thread,
                             args=(i, max_frames, ev, attempt0),
                             name=f"actor-{i}", daemon=True)
        with self._lock:
            self._slot_stops[i] = ev
            self._slot_threads[i] = t
            self._slot_budget[i] = max_frames
            self._slot_done[i] = 0  # fresh generation, fresh accounting
        t.start()
        return t

    def _actor_threads(self) -> list[threading.Thread]:
        """Current-generation actor threads (superseded ones excluded)."""
        with self._lock:
            return list(self._slot_threads.values())

    def _actor_thread(self, i: int, max_frames: int,
                      slot_stop: threading.Event | None = None,
                      attempt0: int = 0) -> None:
        """Supervised actor slot: on a crash the actor is rebuilt (fresh
        env, n-step state, transport handle stay) and resumes the
        REMAINING frame budget, up to actors.max_restarts times —
        SURVEY.md §5 elastic recovery (actors are stateless-ish data
        producers; losing one's in-flight transitions is harmless).
        Exhausting the budget records the error, which fails the run
        report (actor_errors)."""
        stop = slot_stop if slot_stop is not None else self.stop_event
        vector = self.cfg.actors.envs_per_actor > 1
        actor_cls = actor_class(self.family, vector=vector)
        query = self.server.query_batch if vector else self.server.query
        remaining = max_frames
        restarts_left = self.cfg.actors.max_restarts
        # registered here (not in the actor) so a constructor/run that
        # wedges before its first beat is still attributable
        self.obs.register(f"actor-{i}")
        try:
            self._actor_attempts(i, actor_cls, query, remaining,
                                 restarts_left, attempt0, stop)
        finally:
            # a finished actor is not a stalled one — but only the slot's
            # CURRENT generation may clear the heartbeat (a superseded
            # thread un-wedging late must not blind the watchdog to its
            # live replacement)
            with self._lock:
                current = (slot_stop is None or self._slot_threads.get(i)
                           is threading.current_thread())
            if current:
                self.obs.clear(f"actor-{i}")

    def _actor_attempts(self, i, actor_cls, query, remaining,
                        restarts_left, attempt,
                        stop: threading.Event) -> None:
        while remaining > 0 and not stop.is_set():
            actor = None
            try:
                # salt the seed per attempt: an unsalted rebuild replays
                # the exact env + eps-greedy sequence already ingested —
                # re-shipping duplicate experience, and re-triggering any
                # trajectory-dependent crash until the budget burns out
                seed = (self.cfg.seed if attempt == 0
                        else self.cfg.seed + 7907 * attempt)
                actor = actor_cls(self.cfg, i, query,
                                  self.transport, seed=seed,
                                  episode_callback=self._on_episode,
                                  obs=self.obs)
                # the supervisor reads this actor's frame count when it
                # supersedes a wedged slot (remaining-budget estimate)
                with self._lock:
                    self._slot_actor_obj[i] = actor
                actor.run(remaining, stop)
                return  # frames counted at ingest
            except Exception as e:
                # frames the crashed actor already ingested stay counted;
                # only its unshipped tail is lost
                done = actor.frames if actor is not None else 0
                remaining -= done
                with self._lock:
                    # credit the attempt's frames to the slot ONLY if this
                    # thread is still the slot's current generation — a
                    # superseded thread crashing late must not corrupt its
                    # replacement's budget accounting
                    if self._slot_stops.get(i) is stop:
                        self._slot_done[i] = \
                            self._slot_done.get(i, 0) + done
                # a crash with no budget left (frames or restarts) is an
                # error, not a "recovered" restart — e.g. the final
                # force-ship failing after all frames were stepped
                if (restarts_left <= 0 or remaining <= 0
                        or stop.is_set()):
                    with self._lock:
                        self.actor_errors.append((i, e))
                    return
                restarts_left -= 1
                attempt += 1
                with self._lock:
                    self.actor_restarts.append((i, repr(e)))
                self.metrics.log(self._grad_steps_total, actor_restart=i)

    # -- fleet supervisor --------------------------------------------------

    _FATAL_COMPONENTS = ("learner", "ingest", "inference-server", "eval")

    def _supervise_tick(self) -> None:
        """One supervisory pass over heartbeat staleness, replacing the
        bare check_stalled() raise in run()'s poll loop.

        Partition of stale components (actors.supervise):
        - local actor slots (actor-N): restart in place with the
          remaining frame budget, up to actors.supervisor_max_restarts
          per slot; past the budget the slot is QUARANTINED (heartbeat
          cleared, actor_quarantines counter, attributed JSONL event)
          and the run continues degraded — a restart storm must never
          become a crash loop.
        - remote peers (telemetry heartbeats): quarantined + counted
          (peer_stall_events) — the peer's own host supervises its
          workers; this learner just stops waiting on it.
        - fatal locals (learner / ingest / inference-server / eval):
          fall through to check_stalled(), which raises the attributed
          StallError — a driver cannot restart its own learner.

        With the remediation plane on (cfg.remediation.mode != "off"),
        the engine ticks its gauge rules here and gets first claim on
        stale actors/peers: in enforce mode a handled (applied) target
        skips the default path — the engine's actuator IS the default
        path's method, now cooldown-limited and attributed; any other
        outcome (observed / cooldown / failed) falls through to the
        pre-remediation behavior, so a wedged slot is never left for
        check_stalled() to escalate into a run-fatal StallError."""
        obs = self.obs
        eng = self.remediation
        if eng is not None:
            eng.tick(self._remediation_sensors(),
                     step=self._grad_steps_total)
        if obs.watchdog is None:
            return
        if not getattr(self.cfg.actors, "supervise", False):
            obs.check_stalled()
            return
        for name, staleness, _note in obs.heartbeats.stale(
                obs.watchdog.timeout_s):
            slot = name[len("actor-"):] if name.startswith("actor-") else ""
            if slot.isdigit():
                if eng is not None and eng.remediate_stale_actor(
                        int(slot), staleness,
                        step=self._grad_steps_total):
                    continue
                self._supervise_actor(int(slot), staleness)
            elif name not in self._FATAL_COMPONENTS:
                if eng is not None and eng.remediate_stale_peer(
                        name, staleness, step=self._grad_steps_total):
                    continue
                self._quarantine_peer(name, staleness)
        # anything still stale is a fatal local component
        obs.check_stalled()

    def _supervise_actor(self, i: int, staleness: float) -> None:
        """Restart or quarantine one wedged LOCAL actor slot."""
        with self._lock:
            if i in self._quarantined:
                already = True
            else:
                already = False
                used = self._slot_restarts.get(i, 0)
                exhausted = used >= self.cfg.actors.supervisor_max_restarts
                if exhausted:
                    self._quarantined.add(i)
                    # drop the wedged thread from liveness bookkeeping:
                    # a quarantined slot must not keep run()'s
                    # any(is_alive) drain check true forever, or the
                    # degraded-but-terminating contract becomes a hang
                    self._slot_threads.pop(i, None)
                    old_ev = self._slot_stops.pop(i, None)
                else:
                    self._slot_restarts[i] = used + 1
                    old_ev = self._slot_stops.get(i)
                actor = self._slot_actor_obj.pop(i, None)
                budget = self._slot_budget.get(i, 0)
                done_prior = self._slot_done.get(i, 0)
        if already:
            # a superseded thread un-wedged long enough to beat again:
            # re-clear so the fallthrough check_stalled() can't convert
            # a quarantine into a fatal StallError
            self.obs.clear(f"actor-{i}")
            return
        if old_ev is not None:
            old_ev.set()  # superseded generation exits if it un-wedges
        if exhausted:
            self.obs.clear(f"actor-{i}")
            self.obs.count("actor_quarantines")
            self.metrics.log(self._grad_steps_total, actor_quarantined=i,
                             stall_staleness_s=round(staleness, 1))
            # archive the victim's ring: a quarantine is a terminal
            # verdict for the slot, so the evidence goes to disk now
            self.obs.blackbox.record("quarantine", component=f"actor-{i}",
                                     staleness_s=round(staleness, 1))
            self.obs.blackbox.dump("quarantine", component=f"actor-{i}",
                                   step=self._grad_steps_total)
            logging.getLogger(__name__).warning(
                "[fleet] actor slot %d exhausted its supervised-restart "
                "budget (%d) — quarantined; the run continues without it",
                i, self.cfg.actors.supervisor_max_restarts)
            return
        # remaining = generation budget minus EVERY frame the slot
        # already produced this generation: crash-restart attempts that
        # ended before this supersession (_slot_done) plus the wedged
        # current attempt's count
        done = done_prior
        if actor is not None:
            try:
                done += int(actor.frames)
            except (TypeError, ValueError, AttributeError):
                pass
        remaining = max(budget - done, 0)
        self.obs.count("supervisor_restarts")
        with self._lock:
            self.actor_restarts.append(
                (i, f"supervised: stalled {staleness:.1f}s"))
        self.metrics.log(self._grad_steps_total, supervisor_restart=i,
                         stall_staleness_s=round(staleness, 1))
        # every restart decision archives the ring as it stood when the
        # slot wedged — the postmortem bundler's per-incident evidence
        self.obs.blackbox.record("supervisor_restart",
                                 component=f"actor-{i}",
                                 staleness_s=round(staleness, 1))
        self.obs.blackbox.dump("supervisor_restart",
                               component=f"actor-{i}",
                               step=self._grad_steps_total)
        # re-arm the heartbeat NOW so the check_stalled() fallthrough in
        # this very tick doesn't still see the slot as stale
        self.obs.beat(f"actor-{i}", "supervised restart")
        if remaining > 0:
            # fresh seed salt stream for the superseded generation's
            # successor (offset past crash-restart salts)
            self._spawn_actor_slot(i, remaining,
                                   attempt0=100 + self._slot_restarts[i])
        else:
            self.obs.clear(f"actor-{i}")

    def _quarantine_peer(self, name: str, staleness: float) -> None:
        """A REMOTE component's telemetry heartbeat went stale: count
        it, attribute it in the JSONL, and clear the heartbeat so it
        cannot wedge this driver's watchdog — the peer's own host owns
        its recovery (actor_host --supervise); if it reconnects, its
        next telemetry frame re-registers the heartbeat."""
        with self._lock:
            first = name not in self._peer_quarantined
            self._peer_quarantined.add(name)
        self.obs.clear(name)
        self.obs.count("peer_stall_events")
        self.metrics.log(self._grad_steps_total, peer_stall=name,
                         stall_staleness_s=round(staleness, 1))
        if first:
            # the remote died without a local ring: dump OURS, which
            # carries its last retained telemetry frame (context
            # provider above) — its black box of last resort
            self.obs.blackbox.record("peer_stall", peer=name.split("/")[0],
                                     component=name,
                                     staleness_s=round(staleness, 1))
            self.obs.blackbox.dump("peer_stall", component=name,
                                   step=self._grad_steps_total)
            logging.getLogger(__name__).warning(
                "[fleet] remote component %r silent for %.1fs — "
                "quarantined from the stall watchdog (its host owns "
                "recovery); ingest continues from the remaining fleet",
                name, staleness)

    # -- remediation actuators + sensors (runtime/remediation.py) ----------

    def _pause_actor_slot(self, i: int) -> bool:
        """Ingest-pressure autoscale actuator: park one RUNNING actor
        slot by setting its generation stop event — the thread exits
        cooperatively at its next stop check and clears its own
        heartbeat (it stays the slot's current generation, so the
        watchdog never sees a stale ghost). The remaining frame budget
        is banked in _slot_paused for resume. Returns False when the
        slot has nothing to pause (dead, quarantined, already paused)."""
        with self._lock:
            ev = self._slot_stops.get(i)
            t = self._slot_threads.get(i)
            if (ev is None or t is None or not t.is_alive()
                    or i in self._quarantined or i in self._slot_paused):
                return False
            actor = self._slot_actor_obj.get(i)
            budget = self._slot_budget.get(i, 0)
            done = self._slot_done.get(i, 0)
        if actor is not None:
            try:
                done += int(actor.frames)
            except (TypeError, ValueError, AttributeError):
                pass
        remaining = max(budget - done, 0)
        ev.set()
        with self._lock:
            self._slot_paused[i] = remaining
        logging.getLogger(__name__).warning(
            "[fleet] remediation paused actor slot %d under ingest "
            "pressure (%d frames banked)", i, remaining)
        return True

    def _resume_actor_slot(self, i: int) -> bool:
        """Resume a remediation-paused slot with its banked frame
        budget (fresh generation, salted seed stream)."""
        with self._lock:
            remaining = self._slot_paused.pop(i, None)
            restarts = self._slot_restarts.get(i, 0)
            if remaining is None or i in self._quarantined:
                return False
        if remaining <= 0:
            return False  # budget already produced; slot is finished
        self._spawn_actor_slot(i, remaining, attempt0=200 + restarts)
        return True

    def _remediation_backpressure(self, engaged: bool) -> bool:
        """Queue-SLO actuator: nudge the serving tier's backpressure
        flag (same gauge + transport callback as the admission
        controller's own transitions; the controller keeps running and
        re-transitions if its depth-based hysteresis disagrees)."""
        if self.serving is None:
            return False
        return self.serving.force_backpressure(engaged)  # apexlint: unaccounted(counted centrally in RemediationEngine._apply)

    def _remediation_set_priority(self, tenant: str, cls: int) -> bool:
        """Learn-health actuator: re-temper THIS driver's tenant
        priority class. Only the tenant whose TenantClient this driver
        owns is re-temperable — co-tenants' clients belong to their
        registrants (their own drivers run their own engines)."""
        if self.serving is None \
                or getattr(self.server, "policy_id", None) != tenant:
            return False
        hi = self.cfg.serving.priority_classes - 1
        self.server.priority = min(max(int(cls), 0), hi)
        return True

    def _remediation_sensors(self) -> dict:
        """Fresh gauge-sensor snapshot for the engine's tick: serving
        queue depth vs SLO, ingest drop pressure (delta since the last
        tick), and the local slot population (supervisor-tick thread
        only — the delta bookkeeping needs no lock)."""
        s: dict[str, Any] = {}
        if self.serving is not None:
            s["queue_depth"] = self.serving.queue_depth
            s["queue_slo"] = self.cfg.serving.queue_slo_items
            s["backpressure"] = self.serving.backpressure_engaged
        with self._lock:
            running = [i for i, t in self._slot_threads.items()
                       if t.is_alive() and i not in self._quarantined
                       and i not in self._slot_paused]
            paused = list(self._slot_paused)
        dropped = (int(getattr(self.transport, "dropped", 0))
                   + self._stage_dropped)
        s["ingest_dropped_delta"] = dropped - self._remed_dropped_seen
        self._remed_dropped_seen = dropped
        s["running_slots"] = running
        s["paused_slots"] = paused
        return s

    def _min_fill(self) -> int:
        return min(self.cfg.replay.min_fill, self.capacity // 2)

    def _ingest_loop(self) -> None:
        try:
            self._ingest_loop_inner()
        except Exception as e:
            with self._lock:
                self.loop_errors.append(("ingest", e))

    def _ingest_loop_inner(self) -> None:
        self.obs.register("ingest")
        try:
            while not self.stop_event.is_set():
                self.obs.beat("ingest")
                batch = self.transport.recv_experience(timeout=0.1)
                if batch is None:
                    # queue ran dry: ship any complete staged blocks so
                    # coalescing costs bounded latency (<= the 0.1s poll)
                    # instead of holding a partial group hostage behind
                    # a slow actor stream
                    if self._stager is not None:
                        self._stager.drain()
                    # idle bandwidth goes to cold recalls: high-mass
                    # cold segments restage through the same stager
                    self._cold_refill_tick()
                    continue
                n = batch_rows(batch)
                self._ingest_one(batch, n)
            # ship any staged full blocks; the partial tail is dropped
            # and counted (single-chip and mesh alike — _flush_stage)
            self._flush_stage(force=True)
        finally:
            self.obs.clear("ingest")

    def _ingest_one(self, batch: dict, n: int) -> None:
        # sequence batches carry fewer items than env frames; actors ship
        # the true frame count alongside (flat batches: frames == items)
        frames = int(batch.get("frames", n))
        # cross-process correlation (obs/fleet.StampingTransport): a
        # stamped batch's learner-side staging gets its own span sharing
        # the origin's batch_id, so the trace reconstructs the
        # actor->wire->staging->add journey; the tag rides the stager
        # into the replay.add dispatch that carries it
        bid = batch.get("batch_id")
        if bid is None:
            self._stage_one(batch, n)
        else:
            peer = str(batch.get("peer", ""))
            with self.obs.span("ingest.batch", batch_id=int(bid),
                               peer=peer, rows=n):
                self._stage_one(batch, n, tag=(peer, int(bid)))
        # wire codec accounting: WireBatch knows both its wire size and
        # its decoded size (header-only); dict batches came in locally
        # and have no wire footprint to report
        wire = getattr(batch, "wire_nbytes", 0)
        if wire:
            self.obs.gauge("wire_compression_ratio",
                           batch.raw_nbytes / wire)
        self.frames.add(frames)
        with self._lock:
            self._frames_total += frames
            self._ingested_batches += 1
        self._emit_shm_gauges()
        self._emit_param_gauges()

    def _emit_shm_gauges(self) -> None:
        """Shared-memory transport instruments (ingest thread only —
        the delta bookkeeping needs no lock). Counters delta-emit so
        report --check sees torn slots / TCP fallbacks the moment they
        start; the inflight gauge is the ring-lease population."""
        tp = self.transport
        if not getattr(tp, "shm_rings", None) and \
                not getattr(tp, "shm_doorbells", 0):
            return
        if not hasattr(self, "_shm_seen"):
            self._shm_seen = {"shm_doorbells": 0, "shm_torn_slots": 0,
                              "shm_fallbacks": 0}
        # literal metric names (not a name loop): the obs-names checker
        # matches emission sites to INSTRUMENTS rows by string literal
        d = int(tp.shm_doorbells) - self._shm_seen["shm_doorbells"]
        if d:
            self.obs.count("shm_doorbells", d)
            self._shm_seen["shm_doorbells"] += d
        d = int(tp.shm_torn_slots) - self._shm_seen["shm_torn_slots"]
        if d:
            self.obs.count("shm_torn_slots", d)
            self._shm_seen["shm_torn_slots"] += d
        d = int(tp.shm_fallbacks) - self._shm_seen["shm_fallbacks"]
        if d:
            self.obs.count("shm_fallbacks", d)
            self._shm_seen["shm_fallbacks"] += d
        self.obs.gauge("shm_slots_inflight",
                       float(tp.shm_slots_inflight))

    def _emit_param_gauges(self) -> None:
        """Param-plane codec instruments (ingest thread only — same
        delta-bookkeeping discipline as _emit_shm_gauges). The ratio
        gauge carries the never-inflate floor: report --check flags any
        sample below 1.0, which a correct encoder can never produce."""
        tp = self.transport
        if not getattr(tp, "param_pushes", 0) and \
                not getattr(tp, "param_bytes_out", 0):
            return
        if not hasattr(self, "_param_seen"):
            self._param_seen = {"param_bytes_out": 0, "param_resyncs": 0,
                                "param_push_queue_drops": 0}
        # literal metric names (not a name loop): the obs-names checker
        # matches emission sites to INSTRUMENTS rows by string literal
        d = int(tp.param_bytes_out) - self._param_seen["param_bytes_out"]
        if d:
            self.obs.count("param_bytes_out", d)
            self._param_seen["param_bytes_out"] += d
        d = int(tp.param_resyncs) - self._param_seen["param_resyncs"]
        if d:
            self.obs.count("param_resyncs", d)
            self._param_seen["param_resyncs"] += d
        drops = sum(tp.param_push_queue_drops.values())
        d = drops - self._param_seen["param_push_queue_drops"]
        if d:
            self.obs.count("param_push_queue_drops", d)
            self._param_seen["param_push_queue_drops"] += d
        ratio = float(tp.param_compression_ratio)
        if ratio > 0.0:
            self.obs.gauge("param_compression_ratio", ratio)

    def _stage_one(self, batch: dict, n: int, tag=None) -> None:
        if self._stager is not None:
            self._stager.put(batch, tag=tag)
            # below min_fill the learner is stalled waiting on replay:
            # ship complete blocks eagerly (warmed g=1 graph) instead of
            # letting coalescing delay the first train dispatch by up to
            # a full buffer — steady-state keeps the coalesced cadence
            if self._replay_filled < self._min_fill():
                self._stager.drain()
            self.obs.gauge("ingest_staging_occupancy",
                           self._stager.occupancy())
            self.obs.gauge("ingest_decode_ms",
                           self._stager.last_put_decode_ms)
            self.obs.gauge("ingest_ship_ms",
                           self._stager.last_ship_ms)
        else:
            rel = getattr(batch, "release", None)
            if rel is not None:
                # shm slot batch on the legacy (stagerless) path: the
                # deferred concatenate in _flush_stage would pin the
                # ring slot for an unbounded stay in self._stage, so
                # materialize the rows now and free the slot
                batch = {k: np.asarray(batch[k]).copy()
                         for k in self._item_keys + ("priorities",)}
                rel()
            self._stage.append(batch)
            self._stage_n += n
            self._flush_stage()

    def _ship_staged(self, views: dict, g: int) -> list:
        """Ship g coalesced staged blocks (IngestStager callback): async
        device_put straight out of the contiguous staging memory, then
        ONE donated add dispatch under _state_lock. Returns the device
        handles so the stager can overlap the NEXT buffer's decode with
        this transfer and only block when about to reuse the memory.
        g == 1 uses the warmed single-block `add` graph (idle drains);
        g == coalesce uses the warmed `add_many` — exactly two graphs."""
        count = g * self.dp * self._stage_chunk
        if self._cold is not None and self._replay_filled >= self.capacity:
            # ring full + tier on: every ship becomes an eviction swap
            return self._ship_staged_cold(views, g)
        if self.is_dist:
            shape = (g, self.dp, self._stage_chunk) if g > 1 \
                else (self.dp, self._stage_chunk)
            sharding = self.learner._group_sharding if g > 1 \
                else self.learner._dp_sharding

            def put(v):
                return jax.device_put(v.reshape(shape + v.shape[1:]),
                                      sharding)
        else:
            shape = (g, self._stage_chunk) if g > 1 \
                else (self._stage_chunk,)

            def put(v):
                return jax.device_put(v.reshape(shape + v.shape[1:]))
        staged = {k: put(v) for k, v in views.items()}
        pris = staged.pop("priorities")
        handles = list(staged.values()) + [pris]
        # correlation tail: the origin batch_ids staged into this
        # dispatch (truncated — attribution, not an exhaustive ledger)
        span_args: dict = {"units": count}
        tags = self._stager.shipping_tags if self._stager is not None \
            else ()
        if tags:
            span_args["batch_ids"] = [t[1] for t in tags[:MAX_SPAN_IDS]]
        # 1-in-N profiled ship (ObsConfig.profile_windows): bracket the
        # dispatch with block_until_ready so the "ingest" roofline stage
        # sees device time, not enqueue time. Off by default — syncing
        # here defeats the stager's decode/transfer overlap
        self._ship_seq += 1
        windowed = (self._ship_window_every
                    and self._ship_seq % self._ship_window_every == 0)
        win = (self.obs.stage_window("ingest", count) if windowed
               else _NULL_CM)
        with win:
            with self._state_lock:
                with self.obs.span("replay.add", **span_args):
                    if g > 1:
                        self.state = self.learner.add_many(self.state,
                                                           staged, pris)
                    else:
                        self.state = self.learner.add(self.state, staged,
                                                      pris)
            if windowed:
                jax.block_until_ready(self.state.replay)
        self.ingest_rows.add(count * self._unit_items)
        with self._lock:
            self._replay_filled = min(
                self._replay_filled + count * self._unit_items,
                self.capacity)
        self.obs.gauge("ingest_coalesce_width", g)
        return handles

    def _ship_staged_cold(self, views: dict, g: int) -> list:
        """Eviction-swap ship (cold tier on, ring full): per staged
        block, the jitted evict_region picks the ring's lowest-
        priority-mass region and reads it out in staging layout; the
        region is fetched to host (a sync — the directed add_at aliases
        those buffers in place a line later), compressed into the
        ColdStore, and the fresh block overwrites exactly that region
        via add_at. Blocks are swapped one at a time (not the coalesced
        add_many) because each one's eviction plan must see the tree
        the previous swap produced. On the mesh each shard runs its own
        plan: evict_region returns [dp] starts / [dp, chunk, ...]
        regions, each shard's region goes through the door as its own
        segment, and the door outcomes are attributed per shard so the
        closure evicted[d] == stored[d] + dropped[d] holds exactly."""
        chunk = self._stage_chunk
        handles = []
        for j in range(g):
            block = {k: v[j * chunk * self.dp:(j + 1) * chunk * self.dp]
                     for k, v in views.items()}
            if self.is_dist:
                staged = {k: jax.device_put(
                    v.reshape((self.dp, chunk) + v.shape[1:]),
                    self.learner._dp_sharding)
                    for k, v in block.items()}
            else:
                staged = {k: jax.device_put(v) for k, v in block.items()}
            pris = staged.pop("priorities")
            with self._state_lock:
                with self.obs.span("replay.evict",
                                   units=chunk * self.dp):
                    start, ev_items, ev_pri = self.learner.evict_region(
                        self.state, chunk)
                    # host fetch BEFORE the donated overwrite deletes
                    # the region's device buffers
                    ev_host = {k: np.asarray(v)
                               for k, v in ev_items.items()}
                    ev_pri = np.asarray(ev_pri)
                    self.state = self.learner.add_at(self.state, staged,
                                                     pris, start)
            if self.is_dist:
                for d in range(self.dp):
                    pri_d = ev_pri[d]
                    live = int((pri_d > 0).sum())
                    self._cold_evicted += live
                    self._cold_evicted_per_shard[d] += live
                    status = self._cold.put(
                        {k: v[d] for k, v in ev_host.items()},
                        pri_d, live)
                    if status == "stored":
                        self._cold_stored += live
                        self._cold_stored_per_shard[d] += live
                    else:
                        self._cold_dropped += live
                        self._cold_dropped_per_shard[d] += live
                    self.obs.count("cold_evictions")
            else:
                live = int((ev_pri > 0).sum())
                self._cold_evicted += live
                self._cold_evicted_per_shard[0] += live
                if self._cold.put(ev_host, ev_pri, live) == "stored":
                    self._cold_stored += live
                    self._cold_stored_per_shard[0] += live
                else:
                    self._cold_dropped += live
                    self._cold_dropped_per_shard[0] += live
                self.obs.count("cold_evictions")
            handles += list(staged.values()) + [pris]
        self.ingest_rows.add(g * chunk * self.dp * self._unit_items)
        # _replay_filled stays at capacity: eviction swaps slots 1:1
        self.obs.gauge("ingest_coalesce_width", g)
        self._emit_cold_gauges()
        return handles

    def _cold_refill_tick(self) -> None:
        """Idle-time recall (ingest thread, queue dry): pop up to
        cold_tier_refill of the highest-priority-mass cold segments,
        invert their stored sum-tree leaf values back to |td| (the add
        path re-applies (|td|+eps)^alpha at write time), and restage
        them through the normal stager so recalled data rides the same
        one-copy staging->add path as fresh actor experience."""
        if self._cold is None:
            return
        # bound the restage burst to what the active staging buffer can
        # absorb without shipping: a recalled/promoted segment is at
        # most one stage_chunk of units (the eviction block), so `room`
        # segments fit without forcing a synchronous mid-idle dispatch
        room = self._stager.free_units() // max(1, self._stage_chunk)
        k = min(getattr(self.cfg.replay, "cold_tier_refill", 1), room)
        did = False
        if k > 0 and len(self._cold):
            alpha, eps = self.replay.alpha, self.replay.eps
            for batch in self._cold.recall(k):
                pri = np.asarray(batch["priorities"], np.float32)
                td = np.maximum(pri ** (1.0 / alpha) - eps, 0.0) \
                    .astype(np.float32)
                batch = dict(batch, priorities=td)
                self._stager.put(batch)
                self._cold_recalled += int((pri > 0).sum())
                self.obs.count("cold_recalls")
            did = True
        # disk promotions AFTER recalls: the heaviest disk segments
        # climb back through the RAM door (put_segment — its displaced
        # victims spill back down), gated on the door's current floor
        # so a promotion never bounces (replay/disk_store.py)
        if self._disk is not None:
            kd = getattr(self.cfg.replay, "cold_tier_disk_promote", 1)
            if kd > 0:
                floor = self._cold.displacement_floor()
                for seg in self._disk.promote(kd, floor):
                    self._cold.put_segment(seg)
                    did = True
        if did:
            self._emit_cold_gauges()

    def _emit_cold_gauges(self) -> None:
        cold = self._cold
        self.obs.gauge("cold_segments", float(len(cold)))
        self.obs.gauge("cold_bytes", float(cold.bytes_compressed))
        self.obs.gauge("cold_compression_ratio",
                       cold.compression_ratio())
        # door outcomes as delta-emitted ctrs: report --check warns
        # when drops outrun displacements (store thrashing — the signal
        # the disk rung exists to absorb)
        d = cold.dropped - self._cold_dropped_seen
        if d:
            self.obs.count("cold_dropped", d)
            self._cold_dropped_seen = cold.dropped
        d = cold.displaced - self._cold_displaced_seen
        if d:
            self.obs.count("cold_displaced", d)
            self._cold_displaced_seen = cold.displaced
        if self._disk is None:
            return
        s = self._disk.stats()
        self.obs.gauge("cold_disk_segments", float(s["segments"]))
        self.obs.gauge("cold_disk_transitions", float(s["transitions"]))
        self.obs.gauge("cold_disk_bytes", float(s["bytes"]))

        def delta(key: str) -> int:
            d = s[key] - self._disk_seen.get(key, 0)
            if d:
                self._disk_seen[key] = s[key]
            return d

        # literal metric names (not a name loop): the obs-names checker
        # matches emission sites to INSTRUMENTS rows by string literal
        d = delta("spilled")
        if d:
            self.obs.count("cold_disk_spills", d)
        d = delta("promoted")
        if d:
            self.obs.count("cold_disk_promotions", d)
        d = delta("queue_full")
        if d:
            self.obs.count("cold_disk_queue_full", d)
        d = delta("io_errors")
        if d:
            self.obs.count("cold_disk_errors", d)

    def _add_block(self, take: dict, count: int) -> None:
        """count is in staging units; priorities reshape like items (they
        carry a trailing [seg_transitions] axis in frame-ring mode)."""
        if self.is_dist:
            shard = lambda v: jnp.asarray(v).reshape(
                self.dp, self._stage_chunk, *v.shape[1:])
            items = {k: shard(v) for k, v in take.items()
                     if k != "priorities"}
            pris = shard(take["priorities"])
        else:
            items = {k: jnp.asarray(v) for k, v in take.items()
                     if k != "priorities"}
            pris = jnp.asarray(take["priorities"])
        with self._state_lock:
            with self.obs.span("replay.add", units=count):
                self.state = self.learner.add(self.state, items, pris)
        self.ingest_rows.add(count * self._unit_items)
        with self._lock:
            self._replay_filled = min(
                self._replay_filled + count * self._unit_items,
                self.capacity)

    def _flush_stage(self, force: bool = False) -> None:
        """Ship staged transitions to the learner in fixed-size blocks —
        [dp, chunk] on the mesh (consecutive chunks round-robin across
        shards, keeping priority masses balanced for the dist IS-weight
        approximation), [chunk] single-chip. Fixed shapes keep the add
        jit at exactly one compiled graph."""
        if self._stager is not None:
            # zero-copy path: complete blocks ship through the stager;
            # at force-flush the sub-block tail is DROPPED and counted
            # in the SAME three denominations as the legacy path below
            # (the accounting is pinned by tests/test_ingest.py)
            self._stager.drain()
            tail = self._stager.tail_units()
            if force and tail:
                if self._frame_mode:
                    # live transitions per staged unit, then folded to
                    # shards — segments carry dead episode-tail pads
                    live = (self._stager.tail_view("next_off") > 0
                            ).sum(axis=-1)
                    per_shard = self._tail_shard_counts(live)
                elif self.family == "r2d2":
                    per_shard = np.asarray(
                        self._stager.tail_shard_units(self.dp),
                        np.int64) * self.cfg.replay.seq_length
                else:
                    per_shard = np.asarray(
                        self._stager.tail_shard_units(self.dp), np.int64)
                    with self._lock:
                        self._frames_total -= tail
                self._stage_dropped += int(per_shard.sum())
                self._stage_dropped_per_shard += per_shard
                self._stager.discard_tail()
            return
        block = self.dp * self._stage_chunk
        while self._stage_n >= block:
            fields = {
                k: np.concatenate([np.asarray(b[k]) for b in self._stage])
                for k in self._item_keys + ("priorities",)}
            take = {k: v[:block] for k, v in fields.items()}
            rest = {k: v[block:] for k, v in fields.items()}
            self._stage = [rest] if rest["priorities"].shape[0] else []
            self._stage_n -= block
            self._add_block(take, block)
        if force and self._stage_n:
            # the partial tail block is DROPPED (counted), single-chip
            # and mesh alike, matching the lossy-tolerant transport
            # semantics. Single-chip used to ship it as one ragged add,
            # but that compiles a brand-new XLA graph (20-40s on TPU,
            # tens of seconds on a busy CPU host) during DRIVER
            # TEARDOWN to save under one block of transitions the
            # learner is about to stop sampling anyway — and an
            # in-teardown compile was on the stack of a rare LLVM
            # segfault observed in the round-5 CI soak. Ape-X tolerates
            # far larger losses at every actor crash.
            if self._frame_mode:
                # count LIVE transitions (segments carry dead episode-
                # tail pads), and leave _frames_total alone: env-frame
                # counts ride ingest messages separately in frame mode
                # and those frames were genuinely consumed
                live = np.concatenate(
                    [(np.asarray(b["next_off"]) > 0).sum(axis=-1)
                     for b in self._stage])
                per_shard = self._tail_shard_counts(live)
            elif self.family == "r2d2":
                # units are sequences; env frames also ride ingest
                # messages separately here, so _frames_total stays.
                # The drop stat is transition-denominated: seq_length
                # per sequence (an upper bound — overlapping
                # sequences double-count their shared steps)
                per_shard = self._tail_shard_counts(np.full(
                    self._stage_n, self.cfg.replay.seq_length, np.int64))
            else:
                # flat mode: 1 unit = 1 env frame, keep the frames
                # counter reconciled with what actually reached replay
                per_shard = self._tail_shard_counts(
                    np.ones(self._stage_n, np.int64))
                with self._lock:
                    self._frames_total -= self._stage_n
            self._stage_dropped += int(per_shard.sum())
            self._stage_dropped_per_shard += per_shard
            self._stage = []
            self._stage_n = 0

    def _tail_shard_counts(self, per_unit) -> np.ndarray:
        """Fold unit-indexed drop counts into per-shard totals: staged
        unit i of a (would-be) [dp, stage_chunk] block belongs to shard
        i // stage_chunk — the same C-order round-robin reshape
        _ship_staged puts on the mesh. The tail is always shorter than
        one block (whole blocks ship before any drop), so the index
        never overflows dp."""
        out = np.zeros(self.dp, np.int64)
        for i, n in enumerate(np.asarray(per_unit, np.int64)):
            out[i // self._stage_chunk] += int(n)
        return out

    def _warmup(self) -> None:
        """AOT-compile the hot jits before any thread starts.

        The first train_step/train_many dispatch otherwise holds
        _state_lock through a 20-40s XLA compile (TPU; tens of seconds
        on a busy CPU test host), during which ingest cannot add and the
        bounded transport queue drops most of the experience stream.
        jit.lower(...).compile() populates the call cache without
        executing — donation markers don't consume the live state.
        """
        learner = self.learner
        cls = type(learner)
        chunk = max(min(self.cfg.learner.train_chunk,
                        self.cfg.learner.publish_every), 1)
        # priorities carry a trailing [seg_transitions] axis per staged
        # frame segment; flat staging units are single transitions
        ptail = (self.cfg.replay.seg_transitions,) if self._frame_mode \
            else ()
        if self.is_dist:
            example = jax.tree.map(
                lambda t: jnp.zeros((self.dp, self._stage_chunk) + t.shape,
                                    t.dtype), self._item_spec)
            pris = jnp.zeros((self.dp, self._stage_chunk) + ptail,
                             jnp.float32)
        else:
            example = jax.tree.map(
                lambda t: jnp.zeros((self._stage_chunk,) + t.shape,
                                    t.dtype), self._item_spec)
            pris = jnp.zeros((self._stage_chunk,) + ptail, jnp.float32)
        c_add = cls.add.lower(learner, self.state, example,
                              pris).compile()
        c_step = cls.train_step.lower(learner, self.state).compile()
        self.obs.log_compiled("add", c_add)
        self.obs.log_compiled("train_step", c_step)
        if self._cold is not None:
            # the eviction-swap path's two graphs: a first-dispatch
            # compile here would otherwise hold _state_lock mid-ship
            # exactly when the ring first fills. Dist add_at takes a
            # [dp] start vector (per-shard directed writes)
            start0 = (jnp.zeros((self.dp,), jnp.int32) if self.is_dist
                      else jnp.int32(0))
            c_ev = cls.evict_region.lower(
                learner, self.state, self._stage_chunk).compile()
            c_addat = cls.add_at.lower(learner, self.state, example,
                                       pris, start0).compile()
            self.obs.log_compiled("evict_region", c_ev)
            self.obs.log_compiled("add_at", c_addat)
        if self._stager is not None and self._stager.coalesce > 1:
            # coalesced ingest groups [g, ...block shape] — the other
            # add graph the zero-copy stager dispatches (full buffers)
            g = self._stager.coalesce
            gexample = jax.tree.map(
                lambda t: jnp.zeros((g,) + t.shape, t.dtype), example)
            gpris = jnp.zeros((g,) + pris.shape, jnp.float32)
            c_addm = cls.add_many.lower(learner, self.state, gexample,
                                        gpris).compile()
            self.obs.log_compiled("add_many", c_addm)
        if chunk > 1:
            c_many = cls.train_many.lower(learner, self.state,
                                          chunk).compile()
            self.obs.log_compiled("train_many", c_many)
            self.obs.stage_attach(self._train_stage, chunk,
                                  compiled=c_many)
        else:
            self.obs.stage_attach(self._train_stage, 1, compiled=c_step)
        # roofline attribution (obs/profiling.py): the warmed executables
        # already carry cost_analysis — attach them so the learner-loop
        # stage windows and the sampled ingest windows can turn wall time
        # into MFU / HBM-bandwidth fractions. One "ingest" step == one
        # staging unit, so bytes scale with the coalesce width shipped
        self.obs.stage_attach("ingest", self.dp * self._stage_chunk,
                              compiled=c_add)
        # the inference server's first forward compile otherwise exceeds
        # the actor query timeout on TPU (observed live); vector actors
        # hit the envs_per_actor bucket on their very first query. A
        # remote-only learner (0 local actors, eval off) never queries
        # its own server — skip the bucket ladder's minutes of compiles
        if (self.cfg.actors.num_actors > 0 or self.cfg.eval_every_steps > 0
                or self.cfg.eval_episodes > 0):
            self.server.warmup(
                warmup_example(self.family, self.cfg, self.spec),
                extra_sizes=(self.cfg.actors.envs_per_actor,))

    def _learner_loop(self, max_grad_steps: int) -> None:
        self.obs.register("learner")
        try:
            self._learner_loop_inner(max_grad_steps)
        except Exception as e:
            with self._lock:
                self.loop_errors.append(("learner", e))
        finally:
            self.obs.clear("learner")
            # an exception mid-capture must still flush the trace (and
            # release the process-wide profiler for any later run)
            if self._profiling:
                jax.profiler.stop_trace()
                self._profiling = None

    def _publish_params(self) -> None:
        # copy/reshard under the state lock: a concurrent add() or
        # train dispatch would donate the very buffers being published.
        # Dist publication is a tp all-gather + replication over ICI
        # (SURVEY.md §2.3 item 3); single-chip learners copy.
        with self._state_lock:
            with self.obs.span("learner.publish_params"):
                pub = self.learner.publish_params(self.state)
        self.server.update_params(pub, self._grad_steps_total)
        # remote actor hosts pull the same copy through the transport's
        # param channel (socket_transport serves it over DCN)
        self.transport.publish_params(pub, self._grad_steps_total)

    def _maybe_profile(self) -> None:
        """Trace the first profile_steps learner dispatches after min-fill
        (SURVEY.md §5 tracing): start/stop bracket the real hot loop —
        train_many dispatches, ingest adds racing them, publish copies —
        so the capture shows the actual interleaving, not a synthetic
        microbenchmark. Called only once the loop is about to dispatch
        (the min-fill/pacing `continue`s above the call site gate it)."""
        if not self.cfg.profile_dir or self._profiling is None:
            return
        if not self._profiling:
            jax.profiler.start_trace(self.cfg.profile_dir)
            self._profile_from = self._grad_steps_total
            self._profiling = True
        elif self._profiling and (self._grad_steps_total - self._profile_from
                                  >= self.cfg.profile_steps):
            jax.profiler.stop_trace()
            self._profiling = None  # done: never restart
            self.metrics.log(self._grad_steps_total,
                             profile_trace=self.cfg.profile_dir)

    def _learner_loop_inner(self, max_grad_steps: int) -> None:
        publish_every = self.cfg.learner.publish_every
        # a chunk larger than the publish cadence would snap to 1 forever
        chunk = max(min(self.cfg.learner.train_chunk, publish_every), 1)
        last_log = 0
        last_ckpt = self._grad_steps_total
        cap = self.cfg.learner.steps_per_frame_cap
        sync_every = self.cfg.learner.target_sync_every
        while (not self.stop_event.is_set()
               and self._grad_steps_total < max_grad_steps):
            self.obs.beat("learner")
            with self._lock:
                filled = self._replay_filled
                frames = self._frames_total
            if filled < self._min_fill():
                time.sleep(0.05)
                continue
            if cap is not None and self._grad_steps_total >= cap * frames:
                time.sleep(0.01)  # pacing: let actors catch up
                continue
            self._maybe_profile()
            self.obs.maybe_profile(self._grad_steps_total)
            # fuse up to `chunk` grad-steps into one device dispatch
            # (lax.scan in learner.train_many) without overshooting the
            # step target; k is snapped to {chunk, 1} so exactly two XLA
            # graphs exist in the hot loop. Publication fires on BOUNDARY
            # CROSSINGS rather than exact multiples: forcing the step
            # counter onto publish_every multiples degraded ~40% of
            # dispatches to single steps whenever publish_every was not
            # a chunk multiple, each paying a full host->device dispatch
            # round-trip — measured live at ~70 grad-steps/s vs ~300+
            # with whole chunks (publish cadence is a staleness knob;
            # a few steps late is equivalent)
            done = self._grad_steps_total
            k = chunk if chunk <= max_grad_steps - done else 1
            with self._state_lock:
                # the stage window rides the span's existing
                # block_until_ready sync point — no extra sync is added
                # for the roofline gauges on the fused train path
                with self.obs.stage_window(self._train_stage, k):
                    with self.obs.span("learner.train", k=k):
                        if k > 1:
                            self.state, m = self.learner.train_many(
                                self.state, k)
                        else:
                            self.state, m = self.learner.train_step(
                                self.state)
                        if self.obs.enabled:
                            # honest host timing under async dispatch;
                            # only paid when observability is on
                            m = jax.block_until_ready(m)
            self._grad_steps_total += k
            self.grad_steps.add(k)
            self.obs.set_learner_step(self._grad_steps_total)
            # sampling + priority write-back + (boundary permitting) the
            # target sync are fused inside the train jit: mark, don't span
            self.obs.mark("replay.sample", fused_into="learner.train")
            self.obs.mark("replay.priority_update",
                          fused_into="learner.train")
            if done // sync_every != self._grad_steps_total // sync_every:
                self.obs.mark("learner.target_sync",
                              fused_into="learner.train")
            if done // publish_every != self._grad_steps_total // publish_every:
                self._publish_params()
            if (self.ckpt is not None and self._grad_steps_total - last_ckpt
                    >= self.cfg.checkpoint_every):
                self._save_checkpoint()
                last_ckpt = self._grad_steps_total
            if self._grad_steps_total - last_log >= 100:
                last_log = self._grad_steps_total
                # ONE explicit fused fetch of the metrics tree at the
                # log boundary (1-in-100 dispatches): the float() reads
                # below would otherwise each pay their own scattered
                # device->host sync when obs is off (found by
                # apexlint's host-sync checker)
                m = jax.device_get(m)  # apexlint: host-sync(log boundary, 1/100 dispatches, single fused fetch)
                with self._lock:
                    avg_ret = (float(np.mean(self.episode_returns))
                               if self.episode_returns else 0.0)
                    replay_size = self._replay_filled
                extra = {}
                # DCN wire budget, when the transport accounts it
                # (socket ingest): lets a soak attribute the link's
                # MB/s between experience in and param pulls out
                for attr, key in (("bytes_in", "ingest_bytes_in"),
                                  ("bytes_out", "param_bytes_out")):
                    v = getattr(self.transport, attr, None)
                    if v is not None:
                        extra[key] = v
                self.metrics.log(
                    self._grad_steps_total,
                    loss=float(m["loss"]), q_mean=float(m["q_mean"]),
                    frames=self._frames_total,
                    frames_per_s=self.frames.rate(),
                    grad_steps_per_s=self.grad_steps.rate(),
                    avg_return=avg_ret,
                    replay_size=replay_size,
                    ingest_dropped=self.transport.dropped,
                    **extra)
                if "td_abs_mean" in m:
                    self.obs.observe("td_abs", float(m["td_abs_mean"]))
                self.obs.gauge("replay_occupancy", replay_size)
                if self.obs.enabled and "diag" in m:
                    # learning-health plane: m is host-side after the
                    # fused device_get above, so these reads add no
                    # device round-trips; tenant = env family
                    self.obs.learn_health(
                        m["diag"], float(m["loss"]),
                        step=self._grad_steps_total,
                        tenant=self.cfg.env.id)
                if self.is_dist:
                    # lockstep ingest fills every shard equally, so the
                    # live bounds come from the host fill mirror (no
                    # device fetch on the hot loop); any future
                    # non-lockstep ingest shows up as divergence in the
                    # bench lane's true per-shard stats (shard_stats)
                    from ape_x_dqn_tpu.obs.profiling import (
                        publish_multichip)
                    fill = replay_size / max(self.capacity, 1)
                    publish_multichip(self.obs, fill_min=fill,
                                      fill_max=fill)
                # perf-regression engine: feed the rolling throughput
                # windows their local baselines (warn-only; peer-scoped
                # baselines arrive via the fleet telemetry frames)
                self.obs.perf_rate("grad_steps_per_s",
                                   self.grad_steps.rate(),
                                   step=self._grad_steps_total)
                self.obs.perf_rate("env_fps", self.frames.rate(),
                                   step=self._grad_steps_total)
                self.obs.perf_rate("ingest_rows_per_s",
                                   self.ingest_rows.rate(),
                                   step=self._grad_steps_total)
                self.obs.publish(self._grad_steps_total)
        # NOTE: a capture still open here (short run ending inside the
        # profile window) is closed by _learner_loop's finally

    def _eval_loop(self) -> None:
        """Greedy-eval at every eval_every_steps grad-step boundary
        (SURVEY.md §2.2 'Eval worker'); shares the inference server."""
        try:
            from ape_x_dqn_tpu.runtime.evaluation import (
                RollingSuiteScore, eval_game_rotation, run_eval_measured)
            every = self.cfg.eval_every_steps
            rotate, games = eval_game_rotation(self.cfg)
            worker = None if rotate else self._make_eval_worker()
            rolling = RollingSuiteScore(self.cfg) if rotate else None
            next_at = every
            eval_i = 0
            while not self.stop_event.wait(0.2):
                if self._grad_steps_total < next_at:
                    continue
                game = None
                if rotate:
                    game = games[eval_i % len(games)]
                    worker = self._make_eval_worker(game=game)
                    eval_i += 1
                t_eval = time.monotonic()
                try:
                    res, depth_max = run_eval_measured(
                        worker, self.cfg.eval_episodes, self.server,
                        stop_event=self.stop_event,
                        max_frames=self.cfg.eval_max_frames)
                except TimeoutError as e:
                    # a transient server stall must not kill the eval
                    # thread for the rest of the run (a 57-game
                    # rotation died 14 games in when one query timed
                    # out — round-5 live rotation); log, skip this
                    # rotation slot, keep rotating
                    self.metrics.log(self._grad_steps_total,
                                     eval_game=game or self.cfg.env.id,
                                     eval_error=repr(e))
                    next_at = (self._grad_steps_total // every + 1) * every
                    continue
                if res is None:  # cancelled mid-eval at shutdown
                    break
                with self._lock:
                    self.last_eval = res
                # eval shares the actors' inference server: wall time +
                # the MAX queue depth polled while the eval ran surface
                # the back-pressure it induced (round-2 verdict weak #7;
                # round-3 advisor: a post-eval snapshot reads ~0)
                # rotation: a rolling per-game table + backend-marked
                # rolling median over games seen so far (round-3
                # verdict weak #7: one-game-per-event scans gave no
                # suite view between --eval-only passes)
                roll = (rolling.update(game, res["mean_return"])
                        if rolling is not None and game else {})
                self.metrics.log(self._grad_steps_total,
                                 avg_eval_return=res["mean_return"],
                                 eval_episodes=res["episodes"],
                                 eval_game=game or self.cfg.env.id,
                                 eval_wall_s=time.monotonic() - t_eval,
                                 server_queue_depth_max=depth_max,
                                 **roll)
                next_at = (self._grad_steps_total // every + 1) * every
        except Exception as e:
            with self._lock:
                self.loop_errors.append(("eval", e))

    # -- run ---------------------------------------------------------------

    def run(self, total_env_frames: int | None = None,
            max_grad_steps: int = 10**9,
            wall_clock_limit_s: float | None = None) -> dict:
        total = total_env_frames or self.cfg.total_env_frames
        per_actor = total // max(self.cfg.actors.num_actors, 1)
        # self-describing JSONL: sampling semantics + storage layout
        # ride the stream itself (utils/metrics.log_run_header)
        log_run_header(self.metrics, self.cfg, self._grad_steps_total)
        try:
            self._warmup()
        except (AttributeError, NotImplementedError) as e:
            # AOT lowering genuinely unavailable on this backend/learner:
            # first dispatches compile lazily (and hold _state_lock while
            # they do). Anything else — shape mismatches, compile OOM —
            # is a real bug that must surface, not a degraded start.
            self.metrics.log(0, warmup_skipped=repr(e))
        ingest = threading.Thread(target=self._ingest_loop, name="ingest",
                                  daemon=True)
        learner = threading.Thread(target=self._learner_loop,
                                   args=(max_grad_steps,), name="learner",
                                   daemon=True)
        evaluator = (threading.Thread(target=self._eval_loop, name="eval",
                                      daemon=True)
                     if self.cfg.eval_every_steps > 0 else None)
        t0 = time.monotonic()
        ingest.start()
        learner.start()
        if evaluator is not None:
            evaluator.start()
        for i in range(self.cfg.actors.num_actors):
            self._spawn_actor_slot(i, per_actor)
        saw_remote = False
        try:
            prev_stuck_at = -1  # _ingested_batches at last stuck sighting
            while True:
                # attributed stall handling instead of a silent hang:
                # the poll loop is the one thread guaranteed alive while
                # a worker wedges. The supervisor tick restarts /
                # quarantines recoverable components (local actor
                # slots, remote peers) and raises the watchdog's
                # StallError only for fatal locals — the finally-
                # teardown below still runs on that path
                self._supervise_tick()
                if (wall_clock_limit_s is not None
                        and time.monotonic() - t0 > wall_clock_limit_s):
                    break
                if self._grad_steps_total >= max_grad_steps:
                    break
                if not (learner.is_alive() and ingest.is_alive()):
                    break  # crashed loop: error recorded in loop_errors
                # remote actor hosts (socket transport): the learner must
                # outlive its local actors while remotes are connected,
                # still booting (boot grace for a remote-only learner —
                # actor-host JAX startup takes ~10s+), or only just
                # disconnected (quiesced() debounce). The boot grace
                # ends ONLY on ever_connected (latched by the first
                # EXPERIENCE message): a producer that came and went
                # inside a compile window is correctly seen (so the
                # grace doesn't pin the loop), while a param-only
                # probe — monitoring, or a host that died waiting for
                # params — must NOT end it (observed live: a 5s probe
                # flipped saw_remote and the learner self-terminated
                # 88s into a 300s grace)
                if hasattr(self.transport, "active_connections"):
                    if getattr(self.transport, "ever_connected", False):
                        saw_remote = True
                    booting = (not saw_remote
                               and self.cfg.actors.num_actors == 0
                               and time.monotonic() - t0
                               < self.cfg.actors.remote_boot_grace_s)
                    remote_quiet = (self.transport.quiesced()
                                    if hasattr(self.transport, "quiesced")
                                    else self.transport.active_connections
                                    == 0)
                    if booting or not remote_quiet:
                        time.sleep(0.2)
                        continue
                if not any(t.is_alive() for t in self._actor_threads()):
                    # actors finished: drain pending experience, then let
                    # the learner reach a finite grad-step target — UNLESS
                    # it can never make progress (replay stuck below
                    # min_fill with nothing left to ingest), in which case
                    # spinning forever helps nobody
                    if self.transport.pending == 0:
                        with self._lock:
                            size = self._replay_filled
                            ingested = self._ingested_batches
                            frames = self._frames_total
                        cap = self.cfg.learner.steps_per_frame_cap
                        # no further progress possible: replay never
                        # reached min-fill, or the pacing cap binds and
                        # no more frames will ever arrive
                        stuck = size < self._min_fill() or (
                            cap is not None
                            and self._grad_steps_total >= cap * frames)
                        if max_grad_steps >= 10**9:
                            break
                        # require stuck on two consecutive polls with no
                        # ingest in between: the final batch may be
                        # mid-add (popped from the queue, add not done)
                        if stuck and ingested == prev_stuck_at:
                            break
                        prev_stuck_at = ingested if stuck else -1
                time.sleep(0.2)
        finally:
            self.stop_event.set()
            # per-slot generations stop on their own events; the global
            # event covers the ingest/learner/eval loops
            with self._lock:
                slot_events = list(self._slot_stops.values())
            for ev in slot_events:
                ev.set()
            for t in self._actor_threads():
                t.join(timeout=5)
            learner.join(timeout=10)
            ingest.join(timeout=5)
            if evaluator is not None:
                evaluator.join(timeout=10)
            # end-of-training eval: short runs can finish inside one eval
            # poll interval (and eval_every_steps=0 disables the periodic
            # thread entirely), so guarantee at least one greedy
            # evaluation while the inference server is still up
            if (self.cfg.eval_episodes > 0 and self.last_eval is None
                    and self._grad_steps_total > 0
                    and not self.loop_errors):
                try:
                    from ape_x_dqn_tpu.runtime.evaluation import (
                        final_eval_game)
                    game = final_eval_game(self.cfg)
                    res = self._make_eval_worker(game=game).run(
                        self.cfg.eval_episodes,
                        max_frames=self.cfg.eval_max_frames,
                        deadline_s=self.cfg.final_eval_deadline_s)
                    if res is not None:
                        # the periodic eval thread's join above is
                        # timeout-bounded: it can still be mid-write
                        # when this teardown eval lands
                        with self._lock:
                            self.last_eval = res
                        self.metrics.log(self._grad_steps_total,
                                         avg_eval_return=res["mean_return"],
                                         eval_episodes=res["episodes"],
                                         eval_game=game or self.cfg.env.id)
                except Exception as e:
                    self.loop_errors.append(("final_eval", e))
            # final checkpoint so a killed run resumes where it stopped
            if self.ckpt is not None and self._grad_steps_total > 0:
                try:
                    self._save_checkpoint(wait=True)
                except Exception as e:
                    self.loop_errors.append(("checkpoint", e))
            self.server.stop()
            if self._disk is not None:
                # let queued spills land before the thread stops; a
                # hard kill here is exactly what the recovery scan is
                # for, so failures are logged, never raised
                try:
                    self._disk.drain(timeout=5.0)
                except TimeoutError as e:
                    # queued spills that never landed are lost segments
                    self.obs.count("cold_disk_errors")
                    self.loop_errors.append(("disk_drain", e))
                self._disk.close()
            # final snapshot + trace flush (idempotent: the stall path
            # already closed inside check_stalled before raising)
            self.obs.close(self._grad_steps_total)
        with self._lock:
            avg_ret = (float(np.mean(self.episode_returns))
                       if self.episode_returns else 0.0)
        out = {
            "frames": self._frames_total,
            "grad_steps": self._grad_steps_total,
            "avg_return": avg_ret,
            "episodes": len(self.episode_returns),
            "wall_s": time.monotonic() - t0,
            "server": self.server.stats,
            "ingest_dropped": self.transport.dropped + self._stage_dropped,
            # staged-drop attribution only: transport-queue drops happen
            # before the [dp, chunk] round-robin split exists
            "ingest_dropped_per_shard":
                self._stage_dropped_per_shard.tolist(),
            "actor_errors": list(self.actor_errors),
            "actor_restarts": list(self.actor_restarts),
            "actor_quarantines": sorted(self._quarantined),
            "supervisor_restarts": dict(self._slot_restarts),
            "loop_errors": list(self.loop_errors),
            "eval": self.last_eval,
        }
        if self.remediation is not None:
            out["remediation"] = self.remediation.summary()
        if self._cold is not None:
            # transition-denominated door closure:
            # evicted == stored + dropped (tests/test_ingest.py)
            out["cold_tier"] = {
                "evicted": self._cold_evicted,
                "stored": self._cold_stored,
                "dropped": self._cold_dropped,
                "recalled": self._cold_recalled,
                "displaced_segments": self._cold.displaced,
                "segments": len(self._cold),
                "transitions": self._cold.transitions,
                "bytes": self._cold.bytes_compressed,
                "compression_ratio": self._cold.compression_ratio(),
                # per-shard closure: evicted[d] == stored[d] +
                # dropped[d] for every shard (dp=1 single-chip)
                "evicted_per_shard":
                    self._cold_evicted_per_shard.tolist(),
                "stored_per_shard":
                    self._cold_stored_per_shard.tolist(),
                "dropped_per_shard":
                    self._cold_dropped_per_shard.tolist(),
            }
            if self._disk is not None:
                out["cold_tier"]["disk"] = self._disk.stats()
        if self.is_dist:
            # teardown-time per-shard fill/mass: the state is quiescent
            # (all loops joined above), so the device fetch is safe
            try:
                out["replay_shards"] = self.learner.shard_stats(self.state)
            except Exception:  # noqa: BLE001 - teardown stats are
                pass           # best-effort; never fail a finished run
        return out
