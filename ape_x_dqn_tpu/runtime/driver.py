"""Ape-X orchestration: actors + inference server + ingest + learner.

The reference spawns replay/learner/actor *processes* glued by gRPC
(SURVEY.md §3.1); here the single-host runtime uses threads around the
device-resident replay — the TPU does all heavy work (batched inference,
the fused learner jit), so Python threads only shuttle numpy batches and
are not a bottleneck; the process/host boundary lives behind the
Transport interface (comm/), which multi-host deployments swap for the
socket transport over DCN.

Threads:
- N actor threads: env stepping + priority bookkeeping (runtime/actor.py)
- 1 ingest thread: transport -> learner.add (device ring + sum-tree)
- 1 learner thread: train_step loop + periodic param publication
- eval worker (runtime/evaluation.py) runs greedy episodes on demand
"""

from __future__ import annotations

import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.configs import RunConfig
from ape_x_dqn_tpu.comm.transport import LoopbackTransport
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.models import build_network
from ape_x_dqn_tpu.parallel.inference_server import BatchedInferenceServer
from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
from ape_x_dqn_tpu.runtime.actor import Actor
from ape_x_dqn_tpu.runtime.evaluation import EvalWorker
from ape_x_dqn_tpu.runtime.learner import DQNLearner, transition_item_spec
from ape_x_dqn_tpu.runtime.single_process import build_replay
from ape_x_dqn_tpu.utils.metrics import Metrics, Throughput
from ape_x_dqn_tpu.utils.rng import component_key


class ApexDriver:
    def __init__(self, cfg: RunConfig, metrics: Metrics | None = None):
        self.cfg = cfg
        self.metrics = metrics or Metrics()
        probe_env = make_env(cfg.env, seed=cfg.seed)
        self.spec = probe_env.spec
        self.net = build_network(cfg.network, self.spec)
        obs0 = probe_env.reset()
        params = self.net.init(component_key(cfg.seed, "net_init"),
                               obs0[None])

        self.replay = build_replay(cfg.replay)
        self.learner = DQNLearner(self.net.apply, self.replay, cfg.learner)
        self.state = self.learner.init(
            params,
            self.replay.init(transition_item_spec(self.spec.obs_shape,
                                                  self.spec.obs_dtype)),
            component_key(cfg.seed, "learner"))

        # The learner jits donate the TrainState (learner.py train_step/add,
        # donate_argnums=1), which deletes the donated param buffers — the
        # server must own an independent copy or its first forward after an
        # ingest raises "Array has been deleted" on TPU.
        self.server = BatchedInferenceServer(
            lambda p, obs: self.net.apply(p, obs),
            jax.tree.map(jnp.copy, params),
            max_batch=cfg.inference.max_batch,
            deadline_ms=cfg.inference.deadline_ms)
        self.transport = LoopbackTransport()
        self.stop_event = threading.Event()
        self.episode_returns: deque[float] = deque(maxlen=200)
        self.frames = Throughput(window_s=30.0)
        self.grad_steps = Throughput(window_s=30.0)
        self._frames_total = 0
        self._grad_steps_total = 0
        self._lock = threading.Lock()
        self._state_lock = threading.Lock()
        self.actor_errors: list[tuple[int, Exception]] = []
        self.loop_errors: list[tuple[str, Exception]] = []  # ingest/learner
        self._ingested_batches = 0
        self.last_eval: dict | None = None

    # -- components --------------------------------------------------------

    def _on_episode(self, actor_index: int, info: dict) -> None:
        with self._lock:
            self.episode_returns.append(float(info["episode_return"]))

    def _actor_thread(self, i: int, max_frames: int) -> None:
        try:
            actor = Actor(self.cfg, i, self.server.query, self.transport,
                          episode_callback=self._on_episode)
            actor.run(max_frames, self.stop_event)  # frames counted at ingest
        except Exception as e:
            with self._lock:
                self.actor_errors.append((i, e))

    def _min_fill(self) -> int:
        return min(self.cfg.replay.min_fill, self.replay.capacity // 2)

    def _ingest_loop(self) -> None:
        try:
            self._ingest_loop_inner()
        except Exception as e:
            with self._lock:
                self.loop_errors.append(("ingest", e))

    def _ingest_loop_inner(self) -> None:
        while not self.stop_event.is_set():
            batch = self.transport.recv_experience(timeout=0.1)
            if batch is None:
                continue
            pris = jnp.asarray(batch["priorities"])
            items = {
                "obs": jnp.asarray(batch["obs"]),
                "action": jnp.asarray(batch["action"]),
                "reward": jnp.asarray(batch["reward"]),
                "next_obs": jnp.asarray(batch["next_obs"]),
                "discount": jnp.asarray(batch["discount"]),
            }
            with self._state_lock:
                self.state = self.learner.add(self.state, items, pris)
            n = int(pris.shape[0])
            self.frames.add(n)
            with self._lock:
                self._frames_total += n
                self._ingested_batches += 1

    def _learner_loop(self, max_grad_steps: int) -> None:
        try:
            self._learner_loop_inner(max_grad_steps)
        except Exception as e:
            with self._lock:
                self.loop_errors.append(("learner", e))

    def _learner_loop_inner(self, max_grad_steps: int) -> None:
        publish_every = self.cfg.learner.publish_every
        while (not self.stop_event.is_set()
               and self._grad_steps_total < max_grad_steps):
            with self._state_lock:
                size = int(self.state.replay.size)
            if size < self._min_fill():
                time.sleep(0.05)
                continue
            with self._state_lock:
                self.state, m = self.learner.train_step(self.state)
            self._grad_steps_total += 1
            self.grad_steps.add(1)
            if self._grad_steps_total % publish_every == 0:
                # copy under the state lock: a concurrent add() would donate
                # the very buffers being handed to the server
                with self._state_lock:
                    pub = jax.tree.map(jnp.copy, self.state.params)
                self.server.update_params(pub, self._grad_steps_total)
            if self._grad_steps_total % 100 == 0:
                with self._lock:
                    avg_ret = (float(np.mean(self.episode_returns))
                               if self.episode_returns else 0.0)
                self.metrics.log(
                    self._grad_steps_total,
                    loss=float(m["loss"]), q_mean=float(m["q_mean"]),
                    frames=self._frames_total,
                    frames_per_s=self.frames.rate(),
                    grad_steps_per_s=self.grad_steps.rate(),
                    avg_return=avg_ret,
                    replay_size=int(self.state.replay.size),
                    ingest_dropped=self.transport.dropped)

    def _eval_loop(self) -> None:
        """Greedy-eval at every eval_every_steps grad-step boundary
        (SURVEY.md §2.2 'Eval worker'); shares the inference server."""
        try:
            every = self.cfg.eval_every_steps
            worker = EvalWorker(self.cfg, self.server.query)
            next_at = every
            while not self.stop_event.wait(0.2):
                if self._grad_steps_total < next_at:
                    continue
                res = worker.run(self.cfg.eval_episodes,
                                 stop_event=self.stop_event)
                if res is None:  # cancelled mid-eval at shutdown
                    break
                with self._lock:
                    self.last_eval = res
                self.metrics.log(self._grad_steps_total,
                                 avg_eval_return=res["mean_return"],
                                 eval_episodes=res["episodes"])
                next_at = (self._grad_steps_total // every + 1) * every
        except Exception as e:
            with self._lock:
                self.loop_errors.append(("eval", e))

    # -- run ---------------------------------------------------------------

    def run(self, total_env_frames: int | None = None,
            max_grad_steps: int = 10**9,
            wall_clock_limit_s: float | None = None) -> dict:
        total = total_env_frames or self.cfg.total_env_frames
        per_actor = total // max(self.cfg.actors.num_actors, 1)
        threads = [
            threading.Thread(target=self._actor_thread, args=(i, per_actor),
                             name=f"actor-{i}", daemon=True)
            for i in range(self.cfg.actors.num_actors)
        ]
        ingest = threading.Thread(target=self._ingest_loop, name="ingest",
                                  daemon=True)
        learner = threading.Thread(target=self._learner_loop,
                                   args=(max_grad_steps,), name="learner",
                                   daemon=True)
        evaluator = (threading.Thread(target=self._eval_loop, name="eval",
                                      daemon=True)
                     if self.cfg.eval_every_steps > 0 else None)
        t0 = time.monotonic()
        ingest.start()
        learner.start()
        if evaluator is not None:
            evaluator.start()
        for t in threads:
            t.start()
        try:
            prev_stuck_at = -1  # _ingested_batches at last stuck sighting
            while True:
                if (wall_clock_limit_s is not None
                        and time.monotonic() - t0 > wall_clock_limit_s):
                    break
                if self._grad_steps_total >= max_grad_steps:
                    break
                if not (learner.is_alive() and ingest.is_alive()):
                    break  # crashed loop: error recorded in loop_errors
                if not any(t.is_alive() for t in threads):
                    # actors finished: drain pending experience, then let
                    # the learner reach a finite grad-step target — UNLESS
                    # it can never make progress (replay stuck below
                    # min_fill with nothing left to ingest), in which case
                    # spinning forever helps nobody
                    if self.transport.pending == 0:
                        with self._state_lock:
                            size = int(self.state.replay.size)
                        with self._lock:
                            ingested = self._ingested_batches
                        stuck = size < self._min_fill()
                        if max_grad_steps >= 10**9:
                            break
                        # require stuck on two consecutive polls with no
                        # ingest in between: the final batch may be
                        # mid-add (popped from the queue, add not done)
                        if stuck and ingested == prev_stuck_at:
                            break
                        prev_stuck_at = ingested if stuck else -1
                time.sleep(0.2)
        finally:
            self.stop_event.set()
            for t in threads:
                t.join(timeout=5)
            learner.join(timeout=10)
            ingest.join(timeout=5)
            if evaluator is not None:
                evaluator.join(timeout=10)
            # end-of-training eval: short runs can finish inside one eval
            # poll interval, so guarantee at least one greedy evaluation
            # while the inference server is still up
            if (evaluator is not None and self.last_eval is None
                    and self._grad_steps_total > 0
                    and not self.loop_errors):
                try:
                    res = EvalWorker(self.cfg, self.server.query).run(
                        self.cfg.eval_episodes)
                    if res is not None:
                        self.last_eval = res
                        self.metrics.log(self._grad_steps_total,
                                         avg_eval_return=res["mean_return"],
                                         eval_episodes=res["episodes"])
                except Exception as e:
                    self.loop_errors.append(("final_eval", e))
            self.server.stop()
        with self._lock:
            avg_ret = (float(np.mean(self.episode_returns))
                       if self.episode_returns else 0.0)
        return {
            "frames": self._frames_total,
            "grad_steps": self._grad_steps_total,
            "avg_return": avg_ret,
            "episodes": len(self.episode_returns),
            "wall_s": time.monotonic() - t0,
            "server": self.server.stats,
            "ingest_dropped": self.transport.dropped,
            "actor_errors": list(self.actor_errors),
            "loop_errors": list(self.loop_errors),
            "eval": self.last_eval,
        }
