"""Training losses, each designed to live inside a single learner jit.

Reference parity (SURVEY.md §3.3–§3.4, §2.2 "Double-DQN Huber loss"):
- n-step double-DQN Huber loss with importance-sampling weights — the
  reference's fused CUDA training step becomes one XLA graph here.
- R2D2 sequence loss: stored-state unroll, burn-in with a stop-gradient
  on the recurrent state, n-step targets inside the sequence, value
  rescaling, and the eta-mix max/mean sequence priority.
- Ape-X DPG critic/policy losses with Polyak targets.

All losses return (scalar_loss, aux) where aux carries the |TD| priorities
the learner writes back into the sum-tree.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ape_x_dqn_tpu.ops import value_rescale


def huber(x: jax.Array, delta: float = 1.0) -> jax.Array:
    """Huber of a residual (delegates to optax to keep one definition)."""
    return optax.losses.huber_loss(x, jnp.zeros_like(x), delta=delta)


class TransitionBatch(NamedTuple):
    """A batch of n-step transitions (time-collapsed, SURVEY.md §3.3).

    rewards are the already-accumulated n-step discounted returns R_n;
    discounts are gamma^n * (1 - terminal) for the bootstrap term.
    """

    obs: jax.Array        # [B, ...]
    actions: jax.Array    # [B] int32
    rewards: jax.Array    # [B] f32   (n-step return)
    next_obs: jax.Array   # [B, ...]  (s_{t+n})
    discounts: jax.Array  # [B] f32   (gamma^n, 0 at terminal)


def dqn_td_error(q_s: jax.Array, q_sp_online: jax.Array,
                 q_sp_target: jax.Array, batch: TransitionBatch,
                 double: bool = True,
                 rescale: bool = False) -> jax.Array:
    """Per-sample TD error for the (double) n-step DQN target."""
    q_sa = jnp.take_along_axis(
        q_s, batch.actions[:, None].astype(jnp.int32), axis=-1)[:, 0]
    if double:
        a_star = jnp.argmax(q_sp_online, axis=-1)
        q_boot = jnp.take_along_axis(
            q_sp_target, a_star[:, None], axis=-1)[:, 0]
    else:
        q_boot = jnp.max(q_sp_target, axis=-1)
    if rescale:
        target = value_rescale.h(
            batch.rewards + batch.discounts * value_rescale.h_inv(q_boot))
    else:
        target = batch.rewards + batch.discounts * q_boot
    return q_sa - jax.lax.stop_gradient(target)


def make_dqn_loss(net_apply: Callable, double: bool = True,
                  huber_delta: float = 1.0, rescale: bool = False):
    """Build loss(params, target_params, batch, is_weights) -> (loss, aux)."""

    def loss_fn(params: Any, target_params: Any, batch: TransitionBatch,
                is_weights: jax.Array):
        q_s = net_apply(params, batch.obs)
        q_sp_online = net_apply(params, batch.next_obs)
        q_sp_target = net_apply(target_params, batch.next_obs)
        td = dqn_td_error(q_s, q_sp_online, q_sp_target, batch,
                          double=double, rescale=rescale)
        per_sample = huber(td, huber_delta)
        loss = jnp.mean(is_weights * per_sample)
        # learning-health diagnostics (obs/learning.py): the online-max
        # vs target-net bootstrap gap is the overestimation Double-DQN
        # exists to shrink (van Hasselt 2016). XLA CSEs the argmax /
        # gather with dqn_td_error's identical internals.
        a_star = jnp.argmax(q_sp_online, axis=-1)
        boot_t = jnp.take_along_axis(
            q_sp_target, a_star[:, None], axis=-1)[:, 0]
        aux = {"td_abs": jnp.abs(td), "loss_per_sample": per_sample,
               "q_mean": q_s.mean(), "td_mean": td.mean(),
               "q_max": q_s.max(), "target_q_mean": boot_t.mean(),
               "q_gap": (jnp.max(q_sp_online, axis=-1) - boot_t).mean()}
        return loss, aux

    return loss_fn


# ---------------------------------------------------------------------------
# R2D2 sequence loss


class SequenceBatch(NamedTuple):
    """Fixed-length sequences with stored recurrent state (SURVEY.md §3.4)."""

    obs: jax.Array        # [B, L, ...]
    actions: jax.Array    # [B, L] int32
    rewards: jax.Array    # [B, L] f32 (per-step, undiscounted)
    terminals: jax.Array  # [B, L] f32 (1 at true terminal steps)
    mask: jax.Array       # [B, L] f32 (1 on valid steps; 0 on padding)
    init_state: tuple     # (c, h) each [B, H] — state before obs[:, 0]


def nstep_targets_in_sequence(rewards: jax.Array, terminals: jax.Array,
                              bootstrap: jax.Array, mask: jax.Array,
                              n_step: int, gamma: float,
                              rescale: bool) -> tuple[jax.Array, jax.Array]:
    """n-step targets at every t using values bootstrap[t+n] within [0, L).

    bootstrap[t] is the (already action-selected) bootstrap value estimate
    at time t in the *rescaled* space if rescale else raw. Positions whose
    t+n falls off the sequence end are reported invalid via the returned
    validity mask.
    """
    b, length = rewards.shape
    if rescale:
        bootstrap = value_rescale.h_inv(bootstrap)
    t_idx = jnp.arange(length)[None, :]
    ret = jnp.zeros((b, length))
    disc = jnp.ones((b, length))
    alive = jnp.ones((b, length))
    # static unroll over n (n is 3-5): R_n[t] = sum_k gamma^k r[t+k] * alive.
    # jnp.roll wraps, so every rolled quantity is masked to real in-range
    # data — wrapped rewards/terminals from the sequence head must never
    # leak into windows hanging off the tail.
    for k in range(n_step):
        m_k = (jnp.roll(mask, -k, axis=1)
               * (t_idx + k < length).astype(jnp.float32))
        ret = ret + disc * alive * jnp.roll(rewards, -k, axis=1) * m_k
        alive = alive * (1.0 - jnp.roll(terminals, -k, axis=1) * m_k)
        disc = disc * gamma
    boot_n = jnp.roll(bootstrap, -n_step, axis=1)
    target = ret + disc * alive * boot_n
    if rescale:
        target = value_rescale.h(target)
    # A position trains iff it is real data AND its target is fully
    # determined: either the bootstrap at t+n is real in-range data, or a
    # terminal inside [t, t+n) zeroed the bootstrap (alive == 0) and the
    # return is grounded — without the latter the last n transitions of
    # every episode (including the terminal-reward step) would never be
    # trained on while still serving as bootstrap values for earlier steps.
    mask_boot = jnp.roll(mask, -n_step, axis=1)
    boot_ok = (t_idx < length - n_step).astype(jnp.float32) * mask_boot
    terminated = 1.0 - alive
    valid = mask * jnp.clip(boot_ok + terminated, 0.0, 1.0)
    return target, valid


def make_r2d2_loss(net_apply_seq: Callable, burn_in: int, n_step: int,
                   gamma: float, huber_delta: float = 1.0,
                   double: bool = True, rescale: bool = True,
                   priority_eta: float = 0.9):
    """Build the R2D2 sequence loss.

    net_apply_seq(params, obs[B,T,...], state) -> (q[B,T,A], final_state)
    """

    def loss_fn(params: Any, target_params: Any, batch: SequenceBatch,
                is_weights: jax.Array):
        state0 = tuple(batch.init_state)
        if burn_in > 0:
            _, state_b = net_apply_seq(params, batch.obs[:, :burn_in],
                                       state0)
            state_b = jax.tree.map(jax.lax.stop_gradient, state_b)
            _, state_bt = net_apply_seq(target_params,
                                        batch.obs[:, :burn_in], state0)
        else:
            state_b = state0
            state_bt = state0
        obs_t = batch.obs[:, burn_in:]
        q_online, _ = net_apply_seq(params, obs_t, state_b)  # [B, T, A]
        q_target, _ = net_apply_seq(target_params, obs_t, state_bt)

        actions = batch.actions[:, burn_in:]
        rewards = batch.rewards[:, burn_in:]
        terminals = batch.terminals[:, burn_in:]
        mask = batch.mask[:, burn_in:]

        q_sa = jnp.take_along_axis(
            q_online, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]
        if double:
            a_star = jnp.argmax(q_online, axis=-1)
            boot = jnp.take_along_axis(
                q_target, a_star[..., None], axis=-1)[..., 0]
        else:
            boot = jnp.max(q_target, axis=-1)
        target, valid = nstep_targets_in_sequence(
            rewards, terminals, boot, mask, n_step, gamma, rescale)
        td = (q_sa - jax.lax.stop_gradient(target)) * valid
        per_step = huber(td, huber_delta)
        denom = jnp.maximum(valid.sum(axis=1), 1.0)
        per_seq = per_step.sum(axis=1) / denom
        loss = jnp.mean(is_weights * per_seq)

        td_abs = jnp.abs(td)
        max_td = td_abs.max(axis=1)
        mean_td = td_abs.sum(axis=1) / denom
        priorities = priority_eta * max_td + (1 - priority_eta) * mean_td
        # learning-health diagnostics: valid-masked means so padding
        # never dilutes the statistics (td is already valid-masked)
        vsum = jnp.maximum(valid.sum(), 1.0)
        aux = {"td_abs": priorities, "q_mean": q_sa.mean(),
               "valid_frac": valid.mean(),
               "td_mean": td.sum() / vsum,
               "q_max": q_online.max(),
               "target_q_mean": (target * valid).sum() / vsum,
               "q_gap": ((jnp.max(q_online, axis=-1) - boot)
                         * valid).sum() / vsum}
        return loss, aux

    return loss_fn


# ---------------------------------------------------------------------------
# Ape-X DPG losses


class ContinuousBatch(NamedTuple):
    obs: jax.Array        # [B, D]
    actions: jax.Array    # [B, A] f32
    rewards: jax.Array    # [B] f32 (n-step return)
    next_obs: jax.Array   # [B, D]
    discounts: jax.Array  # [B] f32


def make_dpg_losses(actor_apply: Callable, critic_apply: Callable):
    """Build (critic_loss, policy_loss) closures for Ape-X DPG."""

    def critic_loss(critic_params: Any, target_critic: Any,
                    target_actor: Any, batch: ContinuousBatch,
                    is_weights: jax.Array):
        a_next = actor_apply(target_actor, batch.next_obs)
        q_next = critic_apply(target_critic, batch.next_obs, a_next)
        target = batch.rewards + batch.discounts * q_next
        q = critic_apply(critic_params, batch.obs, batch.actions)
        td = q - jax.lax.stop_gradient(target)
        loss = jnp.mean(is_weights * 0.5 * td**2)
        # q_gap here is critic-vs-bootstrap bias (equals td_mean by
        # construction — there is no separate online-max estimate)
        return loss, {"td_abs": jnp.abs(td), "q_mean": q.mean(),
                      "td_mean": td.mean(), "q_max": q.max(),
                      "target_q_mean": target.mean(),
                      "q_gap": (q - target).mean()}

    def policy_loss(actor_params: Any, critic_params: Any,
                    batch: ContinuousBatch):
        a = actor_apply(actor_params, batch.obs)
        q = critic_apply(critic_params, batch.obs, a)
        return -jnp.mean(q), {"a_abs_mean": jnp.abs(a).mean()}

    return critic_loss, policy_loss
