"""R2D2 value-function rescaling (Kapturowski et al. 2019; SURVEY.md §3.4).

h(x) = sign(x) * (sqrt(|x| + 1) - 1) + eps * x
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

EPS = 1e-3


def h(x: jax.Array, eps: float = EPS) -> jax.Array:
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def h_inv(x: jax.Array, eps: float = EPS) -> jax.Array:
    """Exact closed-form inverse of h."""
    return jnp.sign(x) * (
        ((jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps)) - 1.0)
         / (2.0 * eps)) ** 2 - 1.0)
