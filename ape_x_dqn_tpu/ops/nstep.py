"""Host-side n-step return builder for actor loops.

Each actor env keeps a rolling window of its last n transitions and emits
an n-step transition (s_t, a_t, R_n, s_{t+n}, gamma^n*(1-terminal)) once
the window fills, flushing shortened tails at episode end (SURVEY.md §2.2
"n-step return builder"). Pure numpy — this runs on actor CPUs, not TPU.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

import numpy as np


@dataclass
class NStepTransition:
    obs: np.ndarray
    action: int | np.ndarray
    reward: float        # accumulated discounted n-step return
    next_obs: np.ndarray
    discount: float      # gamma^k * (1 - terminal), k = actual steps spanned
    aux: object = None   # caller payload from the FIRST step of the window
                         # (actors stash q_t(a_t) here for initial priorities)
    span: int = 0        # k: env steps between obs and next_obs (frame-ring
                         # shipping reconstructs next_obs as the stack `span`
                         # steps after obs — replay/frame_ring.py)


class NStepBuilder:
    def __init__(self, n_step: int, gamma: float):
        assert n_step >= 1
        self.n = n_step
        self.gamma = gamma
        self._window: deque = deque()

    def append(self, obs, action, reward: float, next_obs,
               terminal: bool, truncated: bool = False,
               aux=None) -> list[NStepTransition]:
        """Add one env step; returns 0+ completed n-step transitions.

        `terminal` is a bootstrapping-relevant episode end (discount -> 0);
        `truncated` ends the episode without zeroing the bootstrap
        (time-limit: flush with discount gamma^k).
        """
        self._window.append((obs, action, float(reward), aux))
        out: list[NStepTransition] = []
        if terminal or truncated:
            # flush the whole window through the episode end — including a
            # just-filled window, which must NOT bootstrap past a terminal
            bootstrap = 0.0 if terminal else 1.0
            while self._window:
                out.append(self._emit(next_obs, bootstrap))
                self._window.popleft()
        elif len(self._window) == self.n:
            out.append(self._emit(next_obs, 1.0))
            self._window.popleft()
        return out

    def _emit(self, next_obs, bootstrap: float) -> NStepTransition:
        ret = 0.0
        for k, (_, _, r, _) in enumerate(self._window):
            ret += (self.gamma**k) * r
        k_span = len(self._window)
        obs0, action0, _, aux0 = self._window[0]
        return NStepTransition(
            obs=obs0, action=action0, reward=ret, next_obs=next_obs,
            discount=(self.gamma**k_span) * bootstrap, aux=aux0,
            span=k_span)

    def reset(self) -> None:
        self._window.clear()
