"""Pallas TPU kernel: batched frame-row gather for the replay sample path.

The frame-ring sample (replay/frame_ring.py) reconstructs observation
stacks by gathering ~B*stack*2 single frames (512-sample batch -> 4096
rows of ~7KB = ~28MB) from the HBM frames ring. XLA lowers this to a
generic gather; this kernel expresses it as the canonical Pallas
embedding-lookup instead: the row indices are SCALAR-PREFETCHED
(pltpu.PrefetchScalarGridSpec), so the pipeline knows each grid step's
source block before it runs and streams row DMAs HBM->VMEM
double-buffered, one output row per grid step.

The kernel body is a pure copy — all the work is in the index map — so
correctness is trivially checkable against the jnp fallback
(`gather_rows_reference`).

MEASURED RESULT (one v5e chip, 4096 rows of 7KB from a 2.5GB ring; see
PERF.md): XLA's native gather wins by ~13x (0.023ms vs 0.31ms) — its
bulk gather is already DMA-optimal at these row sizes, while the
one-row-per-grid-step pipeline pays per-step overhead 4096 times. The
replay therefore keeps the plain jnp gather; this module stays as the
measured reference for the scalar-prefetch gather pattern (and the
integration point if a future op — e.g. a fused descent+gather — needs
custom DMA scheduling).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def gather_rows_reference(src: jax.Array, idx: jax.Array) -> jax.Array:
    """jnp fallback: src [N, H, W], idx [M] int32 -> [M, H, W]."""
    return src[idx]


def _copy_row_kernel(idx_ref, src_row, out_row):  # noqa: ARG001
    # idx_ref is consumed by the BlockSpec index maps; the body only
    # lands the selected row
    out_row[:] = src_row[:]


@partial(jax.jit, static_argnames=("interpret",))
def gather_rows_pallas(src: jax.Array, idx: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """Pallas row gather: src [N, H, W], idx [M] int32 -> [M, H, W].

    One grid step per output row; the source BlockSpec's index map reads
    the prefetched idx array, so Pallas's automatic pipelining overlaps
    the next row's DMA with the current copy (double buffering).
    interpret=True runs the kernel on CPU for tests.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = idx.shape[0]
    n, h, w = src.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m,),
        in_specs=[
            pl.BlockSpec((1, h, w), lambda i, idx_ref: (idx_ref[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, w), lambda i, idx_ref: (i, 0, 0)),
    )
    return pl.pallas_call(
        _copy_row_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, h, w), src.dtype),
        interpret=interpret,
    )(idx.astype(jnp.int32), src)
