"""Device-side sum-tree (segment tree) for prioritized replay.

The reference keeps its sum-tree on the host (SURVEY.md §2.2 "Prioritized
replay", §2.3 item 5); here it is a single `(2*capacity,)` float32 array
in HBM, with batched updates and stratified sampling running *inside* the
learner jit (BASELINE.json north_star: "the prioritized-replay sum-tree
and importance-sampling weights live in HBM with device-side sampling").

Layout: 1-indexed implicit binary tree. tree[1] is the root (total
priority), leaves live at tree[capacity + i] for i in [0, capacity).
Capacity must be a power of two so the descent depth is static.

TPU-first design notes:
- Updates recompute parents bottom-up: scatter leaf values, then per
  level gather both children and scatter their sum. Recomputation (not
  delta-accumulation) makes duplicate indices in one batch harmless,
  so no host-side dedup is ever needed.
- Sampling is a vectorized prefix-sum descent: log2(capacity) iterations
  of a batched gather — no data-dependent control flow, fully unrolled
  by XLA (static trip count).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def init(capacity: int) -> jax.Array:
    assert capacity > 0 and (capacity & (capacity - 1)) == 0, \
        "capacity must be a power of two"
    return jnp.zeros(2 * capacity, jnp.float32)


def capacity_of(tree: jax.Array) -> int:
    return tree.shape[0] // 2


def total(tree: jax.Array) -> jax.Array:
    return tree[1]


def leaves(tree: jax.Array) -> jax.Array:
    return tree[capacity_of(tree):]


def update(tree: jax.Array, leaf_idx: jax.Array,
           priorities: jax.Array) -> jax.Array:
    """Set priorities at leaf_idx ([B] int32) and repair ancestor sums."""
    cap = capacity_of(tree)
    depth = cap.bit_length() - 1  # log2(cap)
    node = leaf_idx.astype(jnp.int32) + cap
    tree = tree.at[node].set(priorities.astype(jnp.float32))
    for _ in range(depth):
        node = node >> 1
        child_sum = tree[2 * node] + tree[2 * node + 1]
        tree = tree.at[node].set(child_sum)
    return tree


def sample(tree: jax.Array, rng: jax.Array, batch: int,
           size: jax.Array | None = None) -> tuple[jax.Array, jax.Array]:
    """Stratified proportional sampling.

    Returns (leaf_idx [batch] int32, probs [batch] f32) where probs are
    normalized leaf probabilities p_i / total. Stratification: sample i
    draws uniformly from the i-th of `batch` equal slices of the total
    mass (variance reduction, as in standard PER implementations).

    `size` (int32, number of live leaves) clamps the descent's landing
    spot into the filled region: float32 rounding in the stratified u or
    the accumulated left-child sums can walk the descent one leaf past
    the live mass onto a zero-priority slot, and an all-zero tree would
    deterministically return the rightmost leaf. Probs are re-gathered
    after clamping so IS weights always describe the leaf actually
    returned.
    """
    cap = capacity_of(tree)
    depth = cap.bit_length() - 1
    tot = tree[1]
    u = (jnp.arange(batch, dtype=jnp.float32)
         + jax.random.uniform(rng, (batch,))) / batch * tot
    idx = jnp.ones(batch, jnp.int32)
    for _ in range(depth):
        left = tree[2 * idx]
        go_right = u >= left
        u = jnp.where(go_right, u - left, u)
        idx = 2 * idx + go_right.astype(jnp.int32)
    leaf = idx - cap
    if size is not None:
        leaf = jnp.minimum(leaf, jnp.maximum(size, 1) - 1)
    probs = tree[cap + leaf] / jnp.maximum(tot, 1e-12)
    return leaf, probs


@partial(jax.jit, static_argnums=(2,))
def sample_jit(tree, rng, batch):
    return sample(tree, rng, batch)


update_jit = jax.jit(update, donate_argnums=(0,))
