// Native data plane for the socket transport (comm/socket_transport.py).
//
// The reference moves experience between hosts through gRPC's C++ core
// (SURVEY.md §2.2 "Comm: gRPC"); the TPU-native equivalent keeps the
// wire hot path out of Python the same way: message assembly (gather
// many numpy buffers into one length-prefixed frame) and integrity
// checksums run in this compiled module, invoked via ctypes with
// zero-copy pointers. Python only decides WHAT to send; bytes move here.
//
// Build: g++ -O3 -shared -fPIC framing.cpp -o libapex_framing.so
// (done lazily by ape_x_dqn_tpu/comm/native.py and cached).

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

// CRC-32 (IEEE 802.3, reflected 0xEDB88320), slice-by-8, one pass.
// Matches zlib.crc32 so the Python fallback is wire-compatible. The
// byte-at-a-time table loop this replaces ran at ~190 MB/s — slower
// than the memcpy it was guarding, and the single largest cost on the
// shm slot path, which crcs the full RAW payload per post (the TCP
// path only crcs the post-deflate bytes). Slice-by-8 processes two
// 32-bit words per step through eight derived tables (~1.5 GB/s),
// putting the checksum back under the copy it protects.
static uint32_t CRC_TABLE[8][256];
static bool crc_init_done = false;

static void crc_init() {
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t c = i;
        for (int k = 0; k < 8; ++k)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        CRC_TABLE[0][i] = c;
    }
    // table[t][b] = crc of byte b followed by t zero bytes: lets one
    // step fold 8 input bytes with 8 independent lookups
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t c = CRC_TABLE[0][i];
        for (int t = 1; t < 8; ++t) {
            c = CRC_TABLE[0][c & 0xFFu] ^ (c >> 8);
            CRC_TABLE[t][i] = c;
        }
    }
    crc_init_done = true;
}

uint32_t apex_crc32(const uint8_t* buf, uint64_t len, uint32_t seed) {
    if (!crc_init_done) crc_init();
    uint32_t c = seed ^ 0xFFFFFFFFu;
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
    // the word folding below assumes little-endian lane order; the
    // byte loop after it is the (correct) big-endian fallback
    while (len >= 8) {
        uint32_t lo, hi;  // memcpy: unaligned-safe, aliasing-clean
        std::memcpy(&lo, buf, 4);
        std::memcpy(&hi, buf + 4, 4);
        lo ^= c;
        c = CRC_TABLE[7][lo & 0xFFu] ^ CRC_TABLE[6][(lo >> 8) & 0xFFu]
          ^ CRC_TABLE[5][(lo >> 16) & 0xFFu] ^ CRC_TABLE[4][lo >> 24]
          ^ CRC_TABLE[3][hi & 0xFFu] ^ CRC_TABLE[2][(hi >> 8) & 0xFFu]
          ^ CRC_TABLE[1][(hi >> 16) & 0xFFu] ^ CRC_TABLE[0][hi >> 24];
        buf += 8;
        len -= 8;
    }
#endif
    while (len--)
        c = CRC_TABLE[0][(c ^ *buf++) & 0xFFu] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// Gather n source buffers into dst as consecutive [u64 length][bytes]
// records, returning the total bytes written. dst must hold
// sum(lens) + 8*n bytes. Returns 0 on null input.
uint64_t apex_pack(uint8_t* dst, const uint8_t** srcs,
                   const uint64_t* lens, uint64_t n) {
    if (!dst || !srcs || !lens) return 0;
    uint64_t off = 0;
    for (uint64_t i = 0; i < n; ++i) {
        std::memcpy(dst + off, &lens[i], 8);
        off += 8;
        std::memcpy(dst + off, srcs[i], lens[i]);
        off += lens[i];
    }
    return off;
}

// Split a packed frame back into record offsets/lengths (the inverse of
// apex_pack's framing). offsets/lengths must hold max_records entries.
// Returns the number of records parsed, or (uint64_t)-1 on a malformed
// frame (record overruns the buffer).
uint64_t apex_unpack_offsets(const uint8_t* buf, uint64_t len,
                             uint64_t* offsets, uint64_t* lengths,
                             uint64_t max_records) {
    uint64_t off = 0, i = 0;
    while (off < len && i < max_records) {
        if (off + 8 > len) return (uint64_t)-1;
        uint64_t rec_len;
        std::memcpy(&rec_len, buf + off, 8);
        off += 8;
        if (off + rec_len > len) return (uint64_t)-1;
        offsets[i] = off;
        lengths[i] = rec_len;
        off += rec_len;
        ++i;
    }
    return (off == len) ? i : (uint64_t)-1;
}

// XOR one row against another, word-wise with a byte tail. The wire
// codec's delta transform (comm/socket_transport.py "delta-deflate"):
// temporally adjacent frame rows XOR to mostly-zero bytes, which
// deflate then collapses.
static inline void xor_row(uint8_t* dst, const uint8_t* a,
                           const uint8_t* b, uint64_t n) {
    uint64_t i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t wa, wb;
        std::memcpy(&wa, a + i, 8);
        std::memcpy(&wb, b + i, 8);
        wa ^= wb;
        std::memcpy(dst + i, &wa, 8);
    }
    for (; i < n; ++i) dst[i] = a[i] ^ b[i];
}

// Encode: dst row 0 = src row 0 (raw anchor); dst row i = src[i] ^
// src[i-1]. dst and src must not alias.
void apex_delta_encode(uint8_t* dst, const uint8_t* src, uint64_t rows,
                       uint64_t row_bytes) {
    if (!dst || !src || rows == 0) return;
    std::memcpy(dst, src, row_bytes);
    for (uint64_t r = 1; r < rows; ++r)
        xor_row(dst + r * row_bytes, src + r * row_bytes,
                src + (r - 1) * row_bytes, row_bytes);
}

// Decode IN PLACE: buf[i] ^= buf[i-1] for i = 1..rows-1 — the prefix
// undo that turns landed delta rows back into absolute rows directly in
// the preallocated staging block (row 0 must already be absolute; the
// caller XORs the continuation row in when a batch splits across
// staging buffers).
void apex_delta_undo(uint8_t* buf, uint64_t rows, uint64_t row_bytes) {
    if (!buf) return;
    for (uint64_t r = 1; r < rows; ++r)
        xor_row(buf + r * row_bytes, buf + r * row_bytes,
                buf + (r - 1) * row_bytes, row_bytes);
}

// int8 affine quantization of a float32 param delta (the parameter-
// plane codec, comm/param_codec.py "delta-q8"): q = clip(rint((x-lo)/
// scale) - 127, -128, 127). Bit-parity with the numpy fallback is a
// wire contract — both sides of a delta chain must reconstruct the
// SAME float32 base or the drift outlives the quantization bound — so
// every operation stays strict single-precision in the same order as
// the numpy expression, and rounding is nearbyintf under the default
// round-to-nearest-even mode (== np.rint).
void apex_q8_encode(int8_t* dst, const float* src, uint64_t n,
                    float lo, float scale) {
    if (!dst || !src || scale == 0.0f) return;
    for (uint64_t i = 0; i < n; ++i) {
        float r = nearbyintf((src[i] - lo) / scale) - 127.0f;
        if (r < -128.0f) r = -128.0f;
        if (r > 127.0f) r = 127.0f;
        dst[i] = (int8_t)r;
    }
}

// Dequantize-and-accumulate: base[i] += (q[i] + 127) * scale + lo —
// the decode side of apex_q8_encode AND the encoder's own chain
// advance (the encoder reconstructs exactly what decoders will hold,
// so quantization error never compounds across versions). Same strict
// f32 op order as the numpy fallback.
void apex_q8_dequant_add(float* base, const int8_t* q, uint64_t n,
                         float lo, float scale) {
    if (!base || !q) return;
    for (uint64_t i = 0; i < n; ++i) {
        float d = ((float)q[i] + 127.0f) * scale;
        base[i] += d + lo;
    }
}

}  // extern "C"
