// Fused Atari observation kernel: two-frame max + RGB->grayscale
// (BT.601) + bilinear resize (align_corners=false) + clip + uint8 cast,
// in one pass over the pixels. This is the actor-side CPU hot loop (one
// call per env step, SURVEY.md §3.2); the Python reference path in
// envs/atari.py (grayscale() + bilinear_resize()) materializes three
// intermediate float arrays per frame.
//
// Numerics mirror the numpy path bit-for-bit so the two are
// interchangeable mid-run: grayscale accumulates in double and rounds
// once to float (numpy: float64 expression then .astype(np.float32));
// resize weights/indices follow the same align_corners=false formulas
// in double with float weights; the interpolation itself is float
// arithmetic in the same operation order; the final cast truncates like
// numpy's .astype(np.uint8). envs/native.py compiles this with
// -ffp-contract=off — a fused multiply-add would round differently
// from numpy's discrete float ops.

#include <cstdint>
#include <vector>

namespace {

struct ResizeTables {
  std::vector<int64_t> y0, y1, x0, x1;
  std::vector<float> wy, wx;
};

// align_corners=false source coordinates, matching
// envs/atari.py bilinear_resize's cached tables.
void fill_axis(uint64_t in_n, uint64_t out_n, std::vector<int64_t>& i0,
               std::vector<int64_t>& i1, std::vector<float>& w) {
  i0.resize(out_n);
  i1.resize(out_n);
  w.resize(out_n);
  for (uint64_t i = 0; i < out_n; ++i) {
    double s = ((i + 0.5) * (double)in_n) / (double)out_n - 0.5;
    int64_t lo = (int64_t)s;
    if (s < 0) lo = (int64_t)s - 1;  // floor for negatives
    if (lo < 0) lo = 0;
    if (lo > (int64_t)in_n - 1) lo = (int64_t)in_n - 1;
    int64_t hi = lo + 1 < (int64_t)in_n ? lo + 1 : (int64_t)in_n - 1;
    double frac = s - (double)lo;
    if (frac < 0.0) frac = 0.0;
    if (frac > 1.0) frac = 1.0;
    i0[i] = lo;
    i1[i] = hi;
    w[i] = (float)frac;
  }
}

}  // namespace

namespace {

// Per-channel grayscale contributions as uint8-indexed tables: numpy's
// (0.299*r + 0.587*g) + 0.114*b in float64 becomes three exact-double
// lookups and two adds per pixel — same values, same addition order,
// ~3x the scalar multiply version's throughput.
struct GrayTables {
  double r[256], g[256], b[256];
  GrayTables() {
    for (int i = 0; i < 256; ++i) {
      r[i] = 0.299 * i;
      g[i] = 0.587 * i;
      b[i] = 0.114 * i;
    }
  }
};
const GrayTables kGray;

}  // namespace

extern "C" {

// f0, f1: uint8 [h, w, 3] RGB frames; f1 may be null (single frame, no
// max-pool). out: uint8 [oh, ow] grayscale observation.
void apex_preproc(const uint8_t* f0, const uint8_t* f1, uint64_t h,
                  uint64_t w, uint8_t* out, uint64_t oh, uint64_t ow) {
  thread_local std::vector<float> gray;
  gray.resize(h * w);
  const uint64_t n = h * w;
  if (f1) {
    for (uint64_t i = 0; i < n; ++i) {
      const uint8_t* a = f0 + 3 * i;
      const uint8_t* q = f1 + 3 * i;
      uint8_t r = a[0] > q[0] ? a[0] : q[0];
      uint8_t g = a[1] > q[1] ? a[1] : q[1];
      uint8_t b = a[2] > q[2] ? a[2] : q[2];
      gray[i] = (float)((kGray.r[r] + kGray.g[g]) + kGray.b[b]);
    }
  } else {
    for (uint64_t i = 0; i < n; ++i) {
      const uint8_t* a = f0 + 3 * i;
      gray[i] = (float)((kGray.r[a[0]] + kGray.g[a[1]]) + kGray.b[a[2]]);
    }
  }

  // tables are call-invariant per shape (the numpy path caches them in
  // _RESIZE_CACHE for the same reason); shapes are fixed per run, so a
  // one-entry thread_local cache eliminates the per-step rebuild
  thread_local ResizeTables t;
  thread_local uint64_t cached[4] = {0, 0, 0, 0};
  if (cached[0] != h || cached[1] != w || cached[2] != oh ||
      cached[3] != ow) {
    fill_axis(h, oh, t.y0, t.y1, t.wy);
    fill_axis(w, ow, t.x0, t.x1, t.wx);
    cached[0] = h;
    cached[1] = w;
    cached[2] = oh;
    cached[3] = ow;
  }

  for (uint64_t y = 0; y < oh; ++y) {
    const float* r0 = gray.data() + t.y0[y] * w;
    const float* r1 = gray.data() + t.y1[y] * w;
    const float wy = t.wy[y];
    uint8_t* row = out + y * ow;
    for (uint64_t x = 0; x < ow; ++x) {
      const float wx = t.wx[x];
      float top = r0[t.x0[x]] * (1.0f - wx) + r0[t.x1[x]] * wx;
      float bot = r1[t.x0[x]] * (1.0f - wx) + r1[t.x1[x]] * wx;
      float v = top * (1.0f - wy) + bot * wy;
      if (v < 0.0f) v = 0.0f;
      if (v > 255.0f) v = 255.0f;
      row[x] = (uint8_t)v;
    }
  }
}

}  // extern "C"
