"""Small shared helpers."""

from __future__ import annotations


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (n - 1).bit_length()
