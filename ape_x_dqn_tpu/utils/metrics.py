"""Metrics / logging.

First-class metrics (BASELINE.json `metric`): learner grad-steps/sec,
actor env-frames/sec, Atari-57 median human-normalized score. Plus episode
returns, loss, priority stats, replay occupancy (SURVEY.md §5).

Output: JSONL stream + in-memory latest snapshot. TensorBoard is optional
(gated — not baked into this image).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, IO

from ape_x_dqn_tpu.obs.health import make_lock


class Throughput:
    """Windowed throughput counter (events/sec over a sliding window)."""

    def __init__(self, window_s: float = 10.0):
        self._window = window_s
        self._events: deque[tuple[float, float]] = deque()  # guarded-by: _lock
        self._total = 0.0  # guarded-by: _lock
        self._lock = make_lock("metrics.throughput")

    def add(self, n: float = 1.0, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._events.append((now, n))
            self._total += n
            self._trim(now)

    def _trim(self, now: float) -> None:
        cutoff = now - self._window
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def rate(self, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._trim(now)
            if len(self._events) < 2:
                return 0.0
            span = max(now - self._events[0][0], 1e-3)
            return sum(n for _, n in self._events) / span

    @property
    def total(self) -> float:
        # locked: add() mutates _total from producer threads while
        # drivers read totals from the supervisory loop
        with self._lock:
            return self._total


class Metrics:
    """Thread-safe scalar metric sink: JSONL is canonical, TensorBoard
    event files optional (SURVEY.md §5 metrics: "CSV/JSONL +
    TensorBoard").

    tensorboard_dir gates on a writer import (torch's bundled
    SummaryWriter, present wherever torch is; tensorboardX as a
    fallback) — asking for event files without either installed is a
    loud error, not a silent no-op."""

    def __init__(self, log_path: str | None = None,
                 tensorboard_dir: str | None = None):
        self._latest: dict[str, Any] = {}  # guarded-by: _lock
        self._lock = make_lock("metrics.sink")
        self._fh: IO[str] | None = None
        if log_path:
            os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
            self._fh = open(log_path, "a", buffering=1)
        self._tb = None
        if tensorboard_dir:
            try:
                from torch.utils.tensorboard import SummaryWriter
            except ImportError:
                try:
                    from tensorboardX import SummaryWriter  # type: ignore
                except ImportError as e:
                    raise ImportError(
                        "tensorboard_dir needs an event-file writer: "
                        "install torch (torch.utils.tensorboard) or "
                        "tensorboardX, or drop the flag — JSONL logging "
                        "works without either") from e
            self._tb = SummaryWriter(tensorboard_dir)

    def log(self, step: int, **scalars: Any) -> None:
        rec = {"step": int(step), "time": time.time()}
        for k, v in scalars.items():
            if isinstance(v, bool):
                pass  # JSON booleans stay booleans (flags in headers)
            elif hasattr(v, "__float__"):
                v = float(v)
                # keep the JSONL strictly parseable even when training
                # diverges (NaN/Inf are not valid JSON)
                if v != v or v in (float("inf"), float("-inf")):
                    v = None
            rec[k] = v
        with self._lock:
            self._latest.update(rec)
            if self._fh is not None:
                self._fh.write(json.dumps(rec) + "\n")
            if self._tb is not None:
                for k, v in rec.items():
                    if k not in ("step", "time") and isinstance(
                            v, (int, float)):
                        self._tb.add_scalar(k, v, int(step))

    def latest(self) -> dict[str, Any]:
        with self._lock:
            return dict(self._latest)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            if self._tb is not None:
                self._tb.close()
                self._tb = None


def log_run_header(metrics: "Metrics", cfg: Any, step: int = 0) -> None:
    """First-record run description (SURVEY.md §5 metrics/logging).

    The sampling semantics and storage layout that produced a run's
    numbers must live IN the metrics stream, not only in the config
    dump: presets diverge on sample_chunk (pong/atari57 run the K-batch
    relaxation, r2d2 runs exact), and a JSONL read in isolation was
    silent about which semantics it recorded (round-4 verdict weak #6).
    Every driver calls this once before its first training record.
    """
    from ape_x_dqn_tpu import __version__

    metrics.log(
        step,
        run_name=cfg.name,
        version=__version__,
        sample_chunk=max(getattr(cfg.learner, "sample_chunk", 1) or 1, 1),
        # PR 1's double-buffered pipeline changes sampling semantics
        # (one-dispatch priority staleness) — a JSONL must say whether
        # its numbers were produced with the pipeline on
        sample_prefetch=bool(getattr(cfg.learner, "sample_prefetch",
                                     False)),
        replay_kind=cfg.replay.kind,
        replay_storage=cfg.replay.storage,
        replay_capacity=cfg.replay.capacity,
        batch_size=cfg.learner.batch_size,
        train_chunk=cfg.learner.train_chunk,
        dp=cfg.parallel.dp, tp=cfg.parallel.tp)


# Atari-57 human / random score table for the human-normalized-score (HNS)
# metric — the reference's north-star metric (BASELINE.json). Values from
# Wang et al. 2016 (Dueling) appendix, the standard source.
ATARI_HUMAN_RANDOM: dict[str, tuple[float, float]] = {
    # game: (random, human)
    "alien": (227.8, 7127.7), "amidar": (5.8, 1719.5),
    "assault": (222.4, 742.0), "asterix": (210.0, 8503.3),
    "asteroids": (719.1, 47388.7), "atlantis": (12850.0, 29028.1),
    "bank_heist": (14.2, 753.1), "battle_zone": (2360.0, 37187.5),
    "beam_rider": (363.9, 16926.5), "berzerk": (123.7, 2630.4),
    "bowling": (23.1, 160.7), "boxing": (0.1, 12.1),
    "breakout": (1.7, 30.5), "centipede": (2090.9, 12017.0),
    "chopper_command": (811.0, 7387.8), "crazy_climber": (10780.5, 35829.4),
    "defender": (2874.5, 18688.9), "demon_attack": (152.1, 1971.0),
    "double_dunk": (-18.6, -16.4), "enduro": (0.0, 860.5),
    "fishing_derby": (-91.7, -38.7), "freeway": (0.0, 29.6),
    "frostbite": (65.2, 4334.7), "gopher": (257.6, 2412.5),
    "gravitar": (173.0, 3351.4), "hero": (1027.0, 30826.4),
    "ice_hockey": (-11.2, 0.9), "jamesbond": (29.0, 302.8),
    "kangaroo": (52.0, 3035.0), "krull": (1598.0, 2665.5),
    "kung_fu_master": (258.5, 22736.3), "montezuma_revenge": (0.0, 4753.3),
    "ms_pacman": (307.3, 6951.6), "name_this_game": (2292.3, 8049.0),
    "phoenix": (761.4, 7242.6), "pitfall": (-229.4, 6463.7),
    "pong": (-20.7, 14.6), "private_eye": (24.9, 69571.3),
    "qbert": (163.9, 13455.0), "riverraid": (1338.5, 17118.0),
    "road_runner": (11.5, 7845.0), "robotank": (2.2, 11.9),
    "seaquest": (68.4, 42054.7), "skiing": (-17098.1, -4336.9),
    "solaris": (1236.3, 12326.7), "space_invaders": (148.0, 1668.7),
    "star_gunner": (664.0, 10250.0), "surround": (-10.0, 6.5),
    "tennis": (-23.8, -8.3), "time_pilot": (3568.0, 5229.2),
    "tutankham": (11.4, 167.6), "up_n_down": (533.4, 11693.2),
    "venture": (0.0, 1187.5), "video_pinball": (16256.9, 17667.9),
    "wizard_of_wor": (563.5, 4756.5), "yars_revenge": (3092.9, 54576.9),
    "zaxxon": (32.5, 9173.3),
}


def human_normalized_score(game: str, score: float) -> float:
    if game not in ATARI_HUMAN_RANDOM:
        import difflib
        close = difflib.get_close_matches(game, ATARI_HUMAN_RANDOM,
                                          n=3, cutoff=0.4)
        hint = (f"; closest valid keys: {close}" if close
                else "; valid keys are snake_case ALE game names "
                     "(e.g. 'space_invaders')")
        raise ValueError(
            f"unknown Atari game {game!r} for the human-normalized "
            f"score table{hint}")
    rand, human = ATARI_HUMAN_RANDOM[game]
    return (score - rand) / (human - rand)


def median_hns(scores: dict[str, float]) -> float:
    """Median human-normalized score over a suite of games."""
    import statistics
    vals = [human_normalized_score(g, s) for g, s in scores.items()]
    return statistics.median(vals) if vals else 0.0
