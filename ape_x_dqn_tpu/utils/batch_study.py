"""Batch-size / MFU scaling study on the real chip (round-5 verdict
item 3): grad-steps/s, samples/s, and MFU at batch 512/1024/2048 with
constant replay capacity, interleaved A-B-C-C-B-A order so machine
drift cancels (the same discipline as PERF.md's K-batch A/B).

Usage:
    python -m ape_x_dqn_tpu.utils.batch_study [--capacity 1048576]

Prints one JSON line per measurement plus a summary table to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# bench.py lives at the repo root, not inside the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))


def measure_one(batch_size: int, capacity: int, steps: int,
                dispatches: int, sample_chunk: int,
                peak_tflops: float) -> dict:
    import jax

    from bench import (bench_learner, build_learner, prefill,
                       train_step_flops_analytic)

    net, learner, state, spec = build_learner(
        capacity, batch_size, "frame_ring", sample_chunk)
    state, _ = prefill(learner, state, spec, 1 << 15, "frame_ring",
                       repeats=1)
    rates, state = bench_learner(learner, state, steps, dispatches,
                                 repeats=3)
    del state, learner, net
    jax.clear_caches()
    gsps = float(np.median(rates))
    flops = train_step_flops_analytic(batch_size)
    return {
        "batch_size": batch_size,
        "grad_steps_per_s": round(gsps, 1),
        "spread": [round(float(np.min(rates)), 1),
                   round(float(np.max(rates)), 1)],
        "samples_per_s": round(gsps * batch_size),
        "achieved_tflops": round(gsps * flops / 1e12, 2),
        "mfu": round(gsps * flops / 1e12 / peak_tflops, 4),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--capacity", type=int, default=1 << 20)
    p.add_argument("--batches", default="512,1024,2048")
    p.add_argument("--steps-per-dispatch", type=int, default=50)
    p.add_argument("--dispatches", type=int, default=8)
    p.add_argument("--sample-chunk", type=int, default=4)
    p.add_argument("--peak-tflops", type=float, default=197.0,
                   help="chip peak bf16 TFLOP/s (bench.py's default)")
    args = p.parse_args()

    batches = [int(b) for b in args.batches.split(",")]
    order = batches + batches[::-1]  # A-B-C-C-B-A
    runs: dict[int, list[dict]] = {b: [] for b in batches}
    for i, b in enumerate(order):
        t0 = time.monotonic()
        r = measure_one(b, args.capacity, args.steps_per_dispatch,
                        args.dispatches, args.sample_chunk,
                        args.peak_tflops)
        r["order_pos"] = i
        runs[b].append(r)
        print(json.dumps(r), flush=True)
        print(f"[{i + 1}/{len(order)}] batch {b}: "
              f"{r['grad_steps_per_s']} steps/s, mfu {r['mfu']:.1%} "
              f"({time.monotonic() - t0:.0f}s)", file=sys.stderr,
              flush=True)
    print("batch  steps/s(two runs)  samples/s  MFU", file=sys.stderr)
    for b in batches:
        two = runs[b]
        print(f"{b:5}  {[r['grad_steps_per_s'] for r in two]}  "
              f"{[r['samples_per_s'] for r in two]}  "
              f"{[r['mfu'] for r in two]}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
