"""Checkpoint / resume (SURVEY.md §5).

Saves params, target params, optimizer state, learner step, and actor
epsilon-schedule state via Orbax; replay contents are optionally included
(large — off by default). Resume must reproduce metric continuity, which
``tests/test_checkpoint.py`` asserts.
"""

from __future__ import annotations

import os
from typing import Any

import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()

    def restore(self, step: int | None = None, template: Any = None) -> Any:
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if template is not None:
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(template))
        return self._mngr.restore(step)

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
