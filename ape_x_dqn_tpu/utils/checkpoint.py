"""Checkpoint / resume (SURVEY.md §5).

Orbax-backed manager. The driver (runtime/driver.py) saves params, target
params, optimizer state, RNG, and the grad-step counter on its
``checkpoint_every`` cadence plus once at shutdown, and restores the
latest checkpoint at construction; replay contents are not saved (large,
and Ape-X regenerates them — actors refill the buffer on resume).
``tests/test_checkpoint.py`` asserts the round-trip is bitwise and that a
resumed run continues the grad-step counter.

FORMAT BREAK (round 5): replay-bearing checkpoints
(``RunConfig.checkpoint_replay=True``) written before the byte-row
storage layout (replay/packing.py — frames [S*F, pad128(H*W)] instead
of [S*F, H, W] planes, packed pixel obs rows in flat storage) do not
restore into the new layout: the Orbax template mirrors the CURRENT
storage shapes and the restore fails with a structure mismatch at
startup. Param-only checkpoints (the default) are unaffected. Restart
replay-bearing runs fresh, or restore on the old code and re-save
params-only.
"""

from __future__ import annotations

import os
from typing import Any

import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()

    def restore(self, step: int | None = None, template: Any = None) -> Any:
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if template is not None:
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(template))
        return self._mngr.restore(step)

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def item_keys(self, step: int | None = None) -> set[str] | None:
        """Top-level keys of a saved checkpoint's pytree, or None when
        unknowable. Lets a restore build its template from what was
        actually SAVED — e.g. toggling RunConfig.checkpoint_replay
        between runs must not brick resume with an Orbax structure
        mismatch (the flag governs saves; restores follow the file)."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        # in-memory metadata works once THIS manager has saved; a fresh
        # manager over an existing directory cannot infer the handler
        # (item_metadata returns tree=None), so fall back to orbax's
        # on-disk _METADATA, whose tree_metadata entries carry each
        # leaf's key path
        try:
            meta = self._mngr.item_metadata(step)
            tree = getattr(meta, "tree", meta)
            if tree is not None:
                return set(tree.keys())
        except Exception:
            pass
        import json
        path = os.path.join(self._dir, str(step), "default", "_METADATA")
        try:
            with open(path) as fh:
                tm = json.load(fh)["tree_metadata"]
            return {e["key_metadata"][0]["key"] for e in tm.values()}
        except Exception:  # layout varies across orbax versions
            return None

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
