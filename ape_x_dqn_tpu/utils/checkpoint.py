"""Checkpoint / resume (SURVEY.md §5).

Orbax-backed manager. The driver (runtime/driver.py) saves params, target
params, optimizer state, RNG, and the grad-step counter on its
``checkpoint_every`` cadence plus once at shutdown, and restores the
latest checkpoint at construction; replay contents are not saved (large,
and Ape-X regenerates them — actors refill the buffer on resume).
``tests/test_checkpoint.py`` asserts the round-trip is bitwise and that a
resumed run continues the grad-step counter.

STORAGE LAYOUT VERSIONING (round 5 FORMAT BREAK, now machine-checked):
replay-bearing checkpoints (``RunConfig.checkpoint_replay=True``)
written before the byte-row storage layout (replay/packing.py — frames
[S*F, pad128(H*W)] instead of [S*F, H, W] planes, packed pixel obs rows
in flat storage) do not restore into the new layout. Every dict payload
saved here is therefore stamped with ``STORAGE_LAYOUT_VERSION``; a
restore that hits a version mismatch — or the Orbax structure mismatch
an unstamped pre-versioning checkpoint produces — fails with a
RuntimeError carrying the documented recovery guidance instead of a raw
Orbax traceback: restart the run fresh, or restore on the old code and
re-save a params-only checkpoint. Param-only checkpoints (the default)
are unaffected by layout breaks either way.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

# Bump on any break in the on-disk layout of checkpointed device state
# (storage byte-rows, ReplayState fields, ...). v2 = the round-5
# byte-row packing layout.
STORAGE_LAYOUT_VERSION = 2
_LAYOUT_KEY = "storage_layout_version"

_LAYOUT_GUIDANCE = (
    "this checkpoint was written under an incompatible storage layout "
    "(see utils/checkpoint.py STORAGE LAYOUT VERSIONING). Either restart "
    "the run fresh, or restore the checkpoint on the code version that "
    "wrote it and re-save params-only (checkpoint_replay=False)")


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        if isinstance(state, dict) and _LAYOUT_KEY not in state:
            # stamp rides inside the payload so it survives any orbax
            # version / directory relocation the metadata might not
            state = {**state,
                     _LAYOUT_KEY: np.asarray(STORAGE_LAYOUT_VERSION,
                                             np.int32)}
        # orbax's StandardSave accepts 0-d ndarrays but rejects bare
        # numpy scalars (np.generic) such as an np.int32 step counter;
        # promote them so callers don't have to care
        state = jax.tree.map(
            lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
            state)
        # orbax asserts (finalize_thread is None) if a save starts while
        # the previous async save is still finalizing; drain it first.
        # wait_until_finished only clears the handle when called from
        # the thread that issued the previous save — the driver saves
        # from both its train loop and its shutdown path, so a finished
        # thread's handle can linger and still trip the assert; clear it.
        self._mngr.wait_until_finished()
        lock = getattr(self._mngr, "_finalize_thread_lock", None)
        if lock is not None:
            with lock:
                ft = getattr(self._mngr, "_finalize_thread", None)
                if ft is not None and not ft.is_alive():
                    self._mngr._finalize_thread = None
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()

    def restore(self, step: int | None = None, template: Any = None) -> Any:
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if isinstance(template, dict) and _LAYOUT_KEY not in template:
            saved = self._raw_item_keys(step)
            if saved is not None and _LAYOUT_KEY in saved:
                # match the stamped payload; checked + stripped below so
                # callers (driver template building) never see the key
                template = {**template,
                            _LAYOUT_KEY: np.asarray(0, np.int32)}
        try:
            if template is not None:
                out = self._mngr.restore(
                    step, args=ocp.args.StandardRestore(template))
            else:
                # a fresh manager has no handler registered for the
                # saved item; an argless StandardRestore restores from
                # the checkpoint's own metadata
                out = self._mngr.restore(
                    step, args=ocp.args.StandardRestore())
        except (ValueError, KeyError, TypeError) as e:
            # the raw Orbax structure-mismatch traceback names neither
            # the cause nor the way out; translate it
            raise RuntimeError(
                f"checkpoint restore failed at step {step} with a "
                f"structure mismatch ({e!s:.300}) — most likely "
                + _LAYOUT_GUIDANCE) from e
        if isinstance(out, dict) and _LAYOUT_KEY in out:
            ver = int(np.asarray(out.pop(_LAYOUT_KEY)))
            if ver != STORAGE_LAYOUT_VERSION:
                raise RuntimeError(
                    f"checkpoint storage layout v{ver} does not match "
                    f"this code's v{STORAGE_LAYOUT_VERSION} — "
                    + _LAYOUT_GUIDANCE)
        return out

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def item_keys(self, step: int | None = None) -> set[str] | None:
        """Top-level keys of a saved checkpoint's pytree (version stamp
        excluded), or None when unknowable. Lets a restore build its
        template from what was actually SAVED — e.g. toggling
        RunConfig.checkpoint_replay between runs must not brick resume
        with an Orbax structure mismatch (the flag governs saves;
        restores follow the file)."""
        keys = self._raw_item_keys(step)
        if keys is not None:
            keys.discard(_LAYOUT_KEY)
        return keys

    def _raw_item_keys(self, step: int | None = None) -> set[str] | None:
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        # in-memory metadata works once THIS manager has saved; a fresh
        # manager over an existing directory cannot infer the handler
        # (item_metadata returns tree=None), so fall back to orbax's
        # on-disk _METADATA, whose tree_metadata entries carry each
        # leaf's key path
        try:
            meta = self._mngr.item_metadata(step)
            tree = getattr(meta, "tree", meta)
            if tree is not None:
                return set(tree.keys())
        except Exception:
            pass
        import json
        path = os.path.join(self._dir, str(step), "default", "_METADATA")
        try:
            with open(path) as fh:
                tm = json.load(fh)["tree_metadata"]
            return {e["key_metadata"][0]["key"] for e in tm.values()}
        except Exception:  # layout varies across orbax versions
            return None

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
