"""Checkpoint / resume (SURVEY.md §5).

Orbax-backed manager. The driver (runtime/driver.py) saves params, target
params, optimizer state, RNG, and the grad-step counter on its
``checkpoint_every`` cadence plus once at shutdown, and restores the
latest checkpoint at construction; replay contents are not saved (large,
and Ape-X regenerates them — actors refill the buffer on resume).
``tests/test_checkpoint.py`` asserts the round-trip is bitwise and that a
resumed run continues the grad-step counter.
"""

from __future__ import annotations

import os
from typing import Any

import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()

    def restore(self, step: int | None = None, template: Any = None) -> Any:
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if template is not None:
            return self._mngr.restore(
                step, args=ocp.args.StandardRestore(template))
        return self._mngr.restore(step)

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
