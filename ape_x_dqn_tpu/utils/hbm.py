"""Per-preset HBM budgeting with an early, loud fits-check.

Round-4 verdict missing #3: the shipping pong preset's frame ring did
not fit the 16GB bench chip, and nothing in the config system said so —
the bench silently measured at 1/4 capacity. This module makes the
budget explicit: `replay_budget` prices a RunConfig's replay storage the
way the device will actually hold it (byte-row packed pixel leaves, see
replay/packing.py), `run_budget` adds the model/optimizer state, and
`check_hbm_fits` raises before any device allocation happens if the
preset cannot fit its chip.

Measured anchors for the transient allowance (v5e, 15.75GB usable,
round 5): the pong preset's compiled graphs at full 2^20 capacity show
add temp = 0 bytes (in-place DUS ring write) and train_many temp =
0.16GB at batch 512 x sample_chunk 4 — the budget reserves
`TRANSIENT_HEADROOM` for temps + XLA reserved + inference/publish
buffers, which the measured graphs sit well inside.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from types import SimpleNamespace
from typing import Any

import numpy as np

from ape_x_dqn_tpu.replay.frame_ring import frame_ring_mode
from ape_x_dqn_tpu.replay.packing import packable, pad128
from ape_x_dqn_tpu.replay.sequence import sequence_frame_mode
from ape_x_dqn_tpu.utils.misc import next_pow2


def _leaf_stored_bytes(shape: tuple[int, ...], dtype) -> int:
    """Bytes one stored leaf actually occupies: pad128 byte rows when
    the leaf is packed (the SAME packing.packable predicate the replay
    storage uses — the budget must not drift from the layout), raw
    bytes otherwise."""
    n = math.prod(shape) * np.dtype(dtype).itemsize
    if packable(SimpleNamespace(shape=shape, dtype=dtype)):
        return pad128(n)
    return n

# bytes reserved for: XLA reserved segment (~258MB measured), train/add
# HLO temps (<=0.2GB measured at batch 512), host-staged ingest blocks,
# published param copies, and the inference server's buckets.
TRANSIENT_HEADROOM = 1 << 31  # 2.0 GB


@dataclass(frozen=True)
class HbmBudget:
    """All sizes in bytes, PER DEVICE (dp-sharded replay counts one
    shard; replicated model state counts fully)."""
    replay_storage: int
    replay_tree: int
    model_state: int
    headroom: int
    capacity: int          # effective per-device item capacity (pow2)
    detail: dict

    @property
    def total(self) -> int:
        return (self.replay_storage + self.replay_tree
                + self.model_state + self.headroom)

    def table(self) -> str:
        gib = 1024 ** 3
        rows = [("replay storage", self.replay_storage),
                ("sum-tree", self.replay_tree),
                ("model+opt state", self.model_state),
                ("transient headroom", self.headroom),
                ("TOTAL per device", self.total)]
        body = "\n".join(f"  {k:<20} {v / gib:8.2f} GiB" for k, v in rows)
        extra = ", ".join(f"{k}={v}" for k, v in self.detail.items())
        return f"{body}\n  ({extra})"


def _frame_ring_bytes(capacity: int, seg_transitions: int, n_step: int,
                      obs_shape: tuple[int, ...]) -> tuple[int, dict]:
    h, w, stack = obs_shape
    f = seg_transitions + n_step + stack - 1
    s = capacity // seg_transitions
    frames = s * f * pad128(h * w)
    fields = capacity * 4 * 4  # action/reward/discount/next_off, 4B each
    return frames + fields, {"layout": "frame_ring", "frame_rows": s * f,
                             "frame_row_bytes": pad128(h * w)}


def _flat_bytes(capacity: int, obs_shape: tuple[int, ...],
                obs_dtype) -> tuple[int, dict]:
    obs = _leaf_stored_bytes(obs_shape, obs_dtype)
    per_item = 2 * obs + 3 * 4  # obs + next_obs + action/reward/discount
    return capacity * per_item, {"layout": "flat", "item_bytes": per_item}


def _sequence_bytes(capacity: int, seq_len: int, obs_shape: tuple[int, ...],
                    obs_dtype, lstm_size: int,
                    frame_mode: bool) -> tuple[int, dict]:
    if frame_mode:
        h, w, stack = obs_shape
        obs = _leaf_stored_bytes((seq_len + stack - 1, h, w), obs_dtype)
    else:
        obs = _leaf_stored_bytes((seq_len, *obs_shape), obs_dtype)
    per_item = obs + seq_len * 4 * 4 + 2 * lstm_size * 4
    return capacity * per_item, {"layout": "sequence",
                                 "seq_item_bytes": per_item,
                                 "frame_mode": frame_mode}


def replay_budget(cfg: Any, obs_shape: tuple[int, ...],
                  obs_dtype=np.uint8) -> tuple[int, int, int, dict]:
    """-> (storage_bytes, tree_bytes, per_device_capacity, detail) for
    cfg (a RunConfig), per device after dp sharding, capacity rounded to
    the pow2 the drivers actually allocate."""
    r = cfg.replay
    dp = max(getattr(cfg.parallel, "dp", 1), 1)
    cap = next_pow2(max(r.capacity // dp, 2)) if dp > 1 \
        else next_pow2(r.capacity)
    if r.kind == "sequence":
        storage, detail = _sequence_bytes(
            cap, r.seq_length, obs_shape, obs_dtype,
            lstm_size=getattr(cfg.network, "lstm_size", 512),
            # the SHARED predicate (replay/sequence.py) — pricing must
            # follow the layout runtime/family.py actually selects
            frame_mode=sequence_frame_mode(r.storage, obs_shape))
    elif frame_ring_mode(r.storage, obs_shape):
        storage, detail = _frame_ring_bytes(
            cap, r.seg_transitions, cfg.learner.n_step, obs_shape)
    else:
        storage, detail = _flat_bytes(cap, obs_shape, obs_dtype)
    tree = 2 * cap * 4 if r.kind != "uniform" else 4
    detail["dp"] = dp
    return storage, tree, cap, detail


def model_state_bytes(param_count: int, adam: bool = True) -> int:
    """params + target copy (+2 adam moments), all f32."""
    per = 4 * (2 + (2 if adam else 0))
    return param_count * per


def run_budget(cfg: Any, obs_shape: tuple[int, ...], obs_dtype=np.uint8,
               param_count: int = 5_000_000) -> HbmBudget:
    """Budget a RunConfig per device. `param_count` defaults to a
    generous flagship-CNN-class estimate when the caller has not built
    the network yet (Nature-CNN ~1.7M, LSTM-Q ~6.5M params)."""
    storage, tree, cap, detail = replay_budget(cfg, obs_shape, obs_dtype)
    return HbmBudget(replay_storage=storage, replay_tree=tree,
                     model_state=model_state_bytes(param_count),
                     headroom=TRANSIENT_HEADROOM, capacity=cap,
                     detail=detail)


# usable-HBM fallbacks by device_kind substring, for backends whose
# memory_stats() returns None (this rig's tunneled v5e does). Values are
# XLA's usable figure, not the marketing number — the v5e OOM message
# reads "15.75G hbm" on a "16GB" chip.
KNOWN_HBM_BYTES = (
    ("v5 lite", int(15.75 * 1024 ** 3)),
    ("v5e", int(15.75 * 1024 ** 3)),
    ("v5p", 95 * 1024 ** 3),
    ("v6", int(31.25 * 1024 ** 3)),
    ("v4", int(31.75 * 1024 ** 3)),
)


def device_hbm_bytes(device=None) -> int | None:
    """HBM limit of `device` (default: first addressable): the
    backend's memory_stats when exposed, else a device_kind table
    lookup (KNOWN_HBM_BYTES), else None (CPU test meshes)."""
    import jax
    if device is None:
        devices = jax.local_devices()
        if not devices:
            return None
        device = devices[0]
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 - backend-dependent API
        stats = None
    if stats:
        limit = stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit")
        if limit:
            return limit
    if getattr(device, "platform", "") != "tpu":
        return None  # CPU/virtual meshes have no HBM budget to enforce
    kind = getattr(device, "device_kind", "").lower()
    for sub, limit in KNOWN_HBM_BYTES:
        if sub in kind:
            return limit
    return None


def check_hbm_fits(cfg: Any, obs_shape: tuple[int, ...], obs_dtype=np.uint8,
                   param_count: int = 5_000_000, device=None,
                   hbm_bytes: int | None = None) -> HbmBudget:
    """Raise ValueError (loudly, with the budget table and the fix)
    when the config's per-device footprint exceeds the device's HBM.
    Returns the budget either way on success; silently returns when the
    backend has no queryable memory limit (CPU meshes — the virtual
    dryrun is a compile check, not a memory model).
    """
    budget = run_budget(cfg, obs_shape, obs_dtype, param_count)
    limit = hbm_bytes if hbm_bytes is not None else device_hbm_bytes(device)
    if limit is None:
        # an UNKNOWN TPU (no memory_stats, no KNOWN_HBM_BYTES entry)
        # must not silently skip enforcement — that is the round-4
        # silent-OOM failure mode this module exists to prevent. CPU
        # test meshes stay silent (no HBM budget to enforce).
        import jax
        devs = jax.local_devices()
        if devs and getattr(devs[0], "platform", "") == "tpu":
            import sys
            print(
                f"[hbm] WARNING: device kind "
                f"{getattr(devs[0], 'device_kind', '?')!r} exposes no "
                f"memory_stats and is not in KNOWN_HBM_BYTES — the HBM "
                f"fits-check is UNENFORCED; per-device budget is "
                f"{budget.total / 1024**3:.2f} GiB:\n{budget.table()}",
                file=sys.stderr, flush=True)
        return budget
    if budget.total > limit:
        gib = 1024 ** 3
        raise ValueError(
            f"config {getattr(cfg, 'name', '?')!r} needs "
            f"{budget.total / gib:.2f} GiB per device but the device has "
            f"{limit / gib:.2f} GiB HBM.\n{budget.table()}\n"
            f"Fix: lower replay.capacity (per-device items: "
            f"{budget.capacity}), raise parallel.dp to shard the replay "
            f"wider, or switch replay.storage='frame_ring' for pixel "
            f"configs.")
    return budget


def compiled_memory_summary(compiled: Any) -> dict[str, int] | None:
    """XLA memory_analysis() of a compiled jit as a plain int dict —
    the MEASURED per-graph numbers the static budget above is
    calibrated against (module docstring "measured anchors"). The obs
    layer logs these per warmed jit (Obs.log_compiled) so every run's
    JSONL records what its graphs actually reserve. None when the
    backend exposes no analysis (some CPU builds)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for field_name, key in (
            ("argument_size_in_bytes", "arg_bytes"),
            ("output_size_in_bytes", "out_bytes"),
            ("temp_size_in_bytes", "temp_bytes"),
            ("alias_size_in_bytes", "alias_bytes"),
            ("generated_code_size_in_bytes", "code_bytes")):
        v = getattr(ma, field_name, None)
        if v is not None:
            out[key] = int(v)
    return out or None
