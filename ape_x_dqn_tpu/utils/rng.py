"""Deterministic RNG threading.

Every component (actor i, learner, replay, eval) derives its keys from the
run seed by folding in a stable component tag, so runs are reproducible
regardless of process/thread scheduling (SURVEY.md §4 determinism tests).
"""

from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp


def component_key(seed: int, component: str, index: int = 0) -> jax.Array:
    """Stable per-component PRNG key: fold a string tag + index into seed."""
    tag = zlib.crc32(component.encode()) & 0x7FFFFFFF
    key = jax.random.key(seed)
    key = jax.random.fold_in(key, tag)
    return jax.random.fold_in(key, index)


def split_key(key: jax.Array, n: int = 2):
    return jax.random.split(key, n)


class RngStream:
    """Host-side stateful stream of keys (for actor loops, not for jit)."""

    def __init__(self, seed: int, component: str, index: int = 0):
        self._key = component_key(seed, component, index)
        self._count = 0

    def next(self) -> jax.Array:
        self._count += 1
        return jax.random.fold_in(self._key, self._count)

    def next_uint32(self) -> int:
        """A host-side uint32 draw (for numpy envs / python-side decisions)."""
        k = self.next()
        return int(jax.random.bits(k, shape=(), dtype=jnp.uint32))
