from ape_x_dqn_tpu.utils.rng import RngStream, split_key
from ape_x_dqn_tpu.utils.metrics import Metrics, Throughput
from ape_x_dqn_tpu.utils.misc import next_pow2
