"""Shared lazy build-and-load for the native C++ components.

The runtime's native pieces (cpp/framing.cpp wire codec, cpp/preproc.cpp
observation kernel) compile with g++ on first use and cache the .so next
to the source; without a toolchain the callers fall back to numpy/zlib
paths that are wire/bit compatible. This module owns the
concurrency-sensitive scaffolding once — per-pid temp + atomic rename
(concurrent first use across processes must not cache a corrupt .so),
temp cleanup on failed/timed-out compiles, mtime staleness, one-shot
caching — so the per-component bindings don't each re-implement it.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import threading

_lock = threading.Lock()
_cache: dict[str, ctypes.CDLL | None] = {}


def build_and_load(src: str, so: str,
                   flags: tuple[str, ...] = ()) -> ctypes.CDLL | None:
    """Compile src -> so with g++ (if missing/stale) and dlopen it.

    Returns None when no compiler is available or the build fails —
    callers fall back to their pure-Python implementations. The result
    (including None) is cached per so-path for the process lifetime.
    """
    with _lock:
        if so in _cache:
            return _cache[so]
        lib = None
        tmp = f"{so}.{os.getpid()}"
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                subprocess.run(
                    ["g++", "-O3", *flags, "-shared", "-fPIC",
                     src, "-o", tmp],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.SubprocessError):
            lib = None
        finally:
            try:
                os.unlink(tmp)  # leftover from a failed/killed compile
            except OSError:
                pass
        _cache[so] = lib
        return lib


def machine_tag() -> str:
    """Stable per-CPU-model tag for arch-specific builds.

    -march=native binaries cached on a shared filesystem (NFS home,
    cluster checkout) would SIGILL on hosts with a different ISA —
    CDLL succeeds, so no graceful fallback fires. Embedding this tag
    in the .so name gives identical CPUs a shared cache and everything
    else its own build.
    """
    try:
        with open("/proc/cpuinfo") as fh:
            # x86 keys plus their ARM equivalents; frequency lines vary
            # run to run and must stay out of the hash
            lines = {ln for ln in fh
                     if ln.startswith(("model name", "flags", "Features",
                                       "CPU implementer", "CPU part"))}
        if not lines:
            raise OSError("no ISA-identifying cpuinfo lines")
        return hashlib.md5("".join(sorted(lines)).encode()).hexdigest()[:8]
    except OSError:
        return platform.machine() or "generic"
