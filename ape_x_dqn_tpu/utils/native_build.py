"""Shared lazy build-and-load for the native C++ components.

The runtime's native pieces (cpp/framing.cpp wire codec, cpp/preproc.cpp
observation kernel) compile with g++ on first use and cache the .so next
to the source; without a toolchain the callers fall back to numpy/zlib
paths that are wire/bit compatible. This module owns the
concurrency-sensitive scaffolding once — per-pid temp + atomic rename
(concurrent first use across processes must not cache a corrupt .so),
temp cleanup on failed/timed-out compiles, mtime staleness, one-shot
caching — so the per-component bindings don't each re-implement it.

Every build runs with -Wall -Wextra -Werror: the native modules are
small enough that zero-warning is cheap to hold, and a warning in a
memcpy/pointer-arithmetic data plane is usually a bug report.

APEX_NATIVE_SANITIZE=1 additionally compiles with
-fsanitize=address,undefined for local debugging runs. Sanitized
builds land in a separate `<name>.san.so` artifact so they can never
poison the normal build cache. Loading one into a non-ASan python
needs the process launched with the runtime preloaded (or the ASan
link-order check relaxed), e.g.:

    LD_PRELOAD=$(gcc -print-file-name=libasan.so) \
        APEX_NATIVE_SANITIZE=1 python ...
    # or: ASAN_OPTIONS=verify_asan_link_order=0 APEX_NATIVE_SANITIZE=1 ...

When neither is set the sanitized .so is still BUILT (so the compile
gate runs) but not loaded — the callers fall back to the pure-Python
paths with a one-line stderr warning instead of ASan aborting the
process at dlopen.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import threading

_lock = threading.Lock()
_cache: dict[str, ctypes.CDLL | None] = {}  # guarded-by: _lock

# the data plane must stay warning-clean; -Werror keeps it honest
WARNING_FLAGS = ("-Wall", "-Wextra", "-Werror")
SANITIZE_FLAGS = ("-fsanitize=address,undefined", "-fno-omit-frame-pointer")


def _sanitize() -> bool:
    return os.environ.get("APEX_NATIVE_SANITIZE", "") not in ("", "0")


def build_and_load(src: str, so: str,
                   flags: tuple[str, ...] = ()) -> ctypes.CDLL | None:
    """Compile src -> so with g++ (if missing/stale) and dlopen it.

    Returns None when no compiler is available or the build fails —
    callers fall back to their pure-Python implementations. The result
    (including None) is cached per so-path for the process lifetime.
    """
    extra: tuple[str, ...] = WARNING_FLAGS
    load_ok = True
    if _sanitize():
        # distinct artifact name: a sanitized .so must never be picked
        # up by a later non-sanitized run's mtime check (or vice versa)
        root, ext = os.path.splitext(so)
        so = f"{root}.san{ext}"
        extra = extra + SANITIZE_FLAGS
        # dlopen'ing an ASan .so into a python that wasn't started with
        # the runtime preloaded (or the link-order check relaxed) makes
        # the ASan init ABORT the whole process — and it snapshots the
        # environment before python code runs, so this cannot be fixed
        # from here. Build the artifact (so -Werror + sanitizer compile
        # checks still gate), but only load it when the process was
        # launched prepared; otherwise warn once and fall back.
        load_ok = (
            "asan" in os.environ.get("LD_PRELOAD", "")
            or "verify_asan_link_order=0" in os.environ.get(
                "ASAN_OPTIONS", ""))
        if not load_ok:
            import sys
            print(
                "[native-build] APEX_NATIVE_SANITIZE=1 but the ASan "
                "runtime is not loadable in this process; built "
                f"{os.path.basename(so)} but using the Python "
                "fallback. Relaunch with LD_PRELOAD=$(gcc "
                "-print-file-name=libasan.so) or "
                "ASAN_OPTIONS=verify_asan_link_order=0.",
                file=sys.stderr)
    with _lock:
        if so in _cache:
            return _cache[so]
        lib = None
        tmp = f"{so}.{os.getpid()}"
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                subprocess.run(
                    ["g++", "-O3", *extra, *flags, "-shared", "-fPIC",
                     src, "-o", tmp],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so) if load_ok else None
        except (OSError, subprocess.SubprocessError):
            lib = None
        finally:
            try:
                os.unlink(tmp)  # leftover from a failed/killed compile
            except OSError:
                pass
        _cache[so] = lib
        return lib


def machine_tag() -> str:
    """Stable per-CPU-model tag for arch-specific builds.

    -march=native binaries cached on a shared filesystem (NFS home,
    cluster checkout) would SIGILL on hosts with a different ISA —
    CDLL succeeds, so no graceful fallback fires. Embedding this tag
    in the .so name gives identical CPUs a shared cache and everything
    else its own build.
    """
    try:
        with open("/proc/cpuinfo") as fh:
            # x86 keys plus their ARM equivalents; frequency lines vary
            # run to run and must stay out of the hash
            lines = {ln for ln in fh
                     if ln.startswith(("model name", "flags", "Features",
                                       "CPU implementer", "CPU part"))}
        if not lines:
            raise OSError("no ISA-identifying cpuinfo lines")
        return hashlib.md5("".join(sorted(lines)).encode()).hexdigest()[:8]
    except OSError:
        return platform.machine() or "generic"
