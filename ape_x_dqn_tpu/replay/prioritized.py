"""Device-resident prioritized replay.

Storage (an arbitrary transition pytree of per-slot arrays) and the
sum-tree both live in HBM; add / sample / priority-update are pure
functions designed to be fused into the learner's single jit (SURVEY.md
§7 step 5, §2.3 item 5). The learner is the single owner of the buffer
state, which removes the sample-vs-update race of host-side designs by
construction (SURVEY.md §5 "race detection").

Conventions (Schaul et al. 2016; Horgan et al. 2018):
- stored priority = (|td| + eps)^alpha  (alpha applied at write time)
- IS weight w_i = (N * P(i))^-beta, normalized by max over the batch
- new transitions arrive WITH priorities (actors compute initial
  priorities actor-side — SURVEY.md §2.2 "Actor runtime")
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from ape_x_dqn_tpu.ops import sum_tree
from ape_x_dqn_tpu.replay.packing import (PixelPacker, dus_rows,
                                          dus_rows_per_shard, make_packer,
                                          ring_write_size,
                                          ring_write_start)


class ReplayState(NamedTuple):
    storage: Any          # pytree of [capacity, ...] arrays
    tree: jax.Array       # (2*capacity,) sum-tree of p^alpha
    pos: jax.Array        # int32 next write cursor
    size: jax.Array       # int32 filled slots


def ring_cursor(pos, size, block: int, capacity: int, nl: int,
                size_scale: int = 1):
    """Skip-to-head cursor math shared by every ring layout and both
    the single-chip (nl=0) and lockstep-dist (nl=1, [dp]-vector
    cursors) forms: -> (start, new_pos, new_size). `size_scale`
    converts cursor units to size units (the frame ring's cursor
    counts segments while its size counts transitions)."""
    pos0 = pos if nl == 0 else pos[0]
    size0 = size if nl == 0 else size[0]
    start = ring_write_start(pos0, block, capacity)
    pos1 = (start + block) % capacity
    size1 = ring_write_size(size0, start * size_scale,
                            block * size_scale, capacity * size_scale)
    return start, pos1, size1


def ring_finish(tree, idx, pri, pos1, size1, lead: tuple[int, ...]):
    """Tree write-back + cursor broadcast shared by every ring layout:
    single-chip (lead=()) updates the one tree; the lockstep-dist form
    (idx [b], same every shard) vmaps the small per-shard trees (the
    storage itself was already written with one multi-axis DUS) and
    broadcasts the common cursor to [dp] vectors; the DIRECTED dist
    form (idx [dp, b], each shard's evict_plan picked its own region)
    vmaps tree AND indices and passes the per-shard [dp] cursors
    through. -> (tree, pos, size)."""
    if not lead:
        return sum_tree.update(tree, idx, pri), pos1, size1
    if idx.ndim == 1:
        tree = jax.vmap(sum_tree.update,
                        in_axes=(0, None, 0))(tree, idx, pri)
        return (tree, jnp.full(lead, pos1, jnp.int32),
                jnp.full(lead, size1, jnp.int32))
    tree = jax.vmap(sum_tree.update, in_axes=(0, 0, 0))(tree, idx, pri)
    return tree, pos1.astype(jnp.int32), size1.astype(jnp.int32)


class PrioritizedReplay:
    """Static config + pure state-transition functions.

    Pixel leaves are stored as exactly-tiled byte rows and ring writes
    are in-place dynamic_update_slice blocks with skip-to-head wrap —
    see replay/packing.py for the measured HBM rationale (a scatter or
    a tile-padded layout each cost a full-buffer copy per add/sample on
    TPU).
    """

    def __init__(self, capacity: int, alpha: float = 0.6, beta: float = 0.4,
                 eps: float = 1e-6, item_spec: Any = None):
        assert capacity > 0 and (capacity & (capacity - 1)) == 0, \
            "capacity must be a power of two"
        self.capacity = capacity
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._packer: PixelPacker | None = None
        self._storage_spec: Any = None
        # packer construction is DETERMINISTIC: given a spec here, the
        # codec exists from construction — encode/decode behavior no
        # longer depends on whether init() happened to run first (the
        # hidden side effect a replay shared across restore paths could
        # otherwise observe mid-flight)
        if item_spec is not None:
            self._build_packer(item_spec)

    def _build_packer(self, item_spec: Any) -> None:
        self._packer, self._storage_spec = make_packer(item_spec)

    # -- state construction ------------------------------------------------

    def init(self, item_spec: Any = None) -> ReplayState:
        """item_spec: pytree of ShapeDtypeStruct (or arrays) for ONE
        item. Optional when the constructor already received it; calling
        with neither raises instead of silently building packer-less
        storage."""
        if item_spec is not None:
            self._build_packer(item_spec)
        if self._storage_spec is None:
            raise ValueError(
                "PrioritizedReplay has no item spec — pass item_spec to "
                "the constructor or to init()")
        storage = jax.tree.map(
            lambda s: jnp.zeros((self.capacity, *s.shape), s.dtype),
            self._storage_spec)
        return ReplayState(
            storage=storage, tree=sum_tree.init(self.capacity),
            pos=jnp.int32(0), size=jnp.int32(0))

    # -- transitions (all pure, jit-friendly) ------------------------------

    def _write_block(self, state: ReplayState, items: Any,
                     td_abs: jax.Array, lead: tuple[int, ...],
                     start: jax.Array | None = None) -> ReplayState:
        """Shared body of `add` (lead=()) and `add_lockstep`
        (lead=(dp,)): one in-place dynamic_update_slice block per leaf
        with skip-to-head wrap; only the small per-shard sum-trees go
        through vmap on the lockstep path."""
        nl = len(lead)
        b = td_abs.shape[nl]
        per_shard = False
        if start is None:
            start, pos1, size1 = ring_cursor(state.pos, state.size, b,
                                             self.capacity, nl)
        else:
            # directed write (add_at / add_at_lockstep): overwrite the
            # caller-chosen region; the cursor resumes after it so
            # subsequent FIFO adds don't immediately clobber what was
            # just written. Dist form: start is a [dp] vector (each
            # shard's evict_plan picked its own region) and the cursor
            # math is elementwise over shards.
            per_shard = nl > 0
            pos1 = (start + b) % self.capacity
            size1 = ring_write_size(state.size, start, b, self.capacity)
        if per_shard:
            idx = start[:, None] + jnp.arange(b, dtype=jnp.int32)[None]
        else:
            idx = start + jnp.arange(b, dtype=jnp.int32)  # every shard
        if self._packer is not None:
            items = self._packer.encode(items)
        if per_shard:
            storage = jax.tree.map(
                lambda buf, x: dus_rows_per_shard(buf, x, start),
                state.storage, items)
        else:
            storage = jax.tree.map(
                lambda buf, x: dus_rows(buf, x, start, lead=nl),
                state.storage, items)
        pri = (td_abs + self.eps) ** self.alpha
        tree, pos, size = ring_finish(state.tree, idx, pri, pos1, size1,
                                      lead)
        return ReplayState(storage=storage, tree=tree, pos=pos, size=size)

    def add(self, state: ReplayState, items: Any,
            td_abs: jax.Array) -> ReplayState:
        """Append a batch of items with initial |TD| priorities.

        items: pytree of [B, ...] arrays; td_abs: [B] f32. Overwrites
        FIFO when full; a block that would cross the ring boundary is
        written at slot 0 instead (skip-to-head — identical to modular
        semantics whenever the block size divides the capacity, which
        every fixed-block ingest staging guarantees).
        """
        return self._write_block(state, items, td_abs, lead=())

    def add_lockstep(self, state: ReplayState, items: Any,
                     td_abs: jax.Array) -> ReplayState:
        """`add` for [dp, ...]-stacked shard states whose cursors
        advance in LOCKSTEP — the dist ingest contract (every add ships
        equal-size [dp, B] blocks, so all shard cursors are equal by
        induction from init).

        Why not jax.vmap(add): vmap's batching rule rewrites
        dynamic_update_slice into lax.scatter, and a scatter into a
        donated buffer materializes a full-buffer HLO temp copy
        (measured 19.1GB on a 9.47GB ring — replay/packing.py). The
        lockstep form writes all shards with ONE multi-axis DUS at
        (0, start, 0...) covering the full dp extent, which stays in
        place (verified: temp=0 at the atari57 per-shard scale).
        """
        return self._write_block(state, items, td_abs,
                                 lead=(td_abs.shape[0],))

    # -- tiered cold store hooks (replay/cold_store.py; single-chip) -------
    #
    # Three pure functions the driver composes into its eviction cycle
    # when ReplayConfig.cold_tier_capacity > 0: pick the ring's
    # lowest-priority-mass contiguous region (evict_plan), read it out
    # in STAGING layout (read_region, fetched to host and handed to
    # ColdStore.put), then overwrite exactly that region with the fresh
    # staged block (add_at). With the tier off none of these run and
    # `add` keeps its blind skip-to-head FIFO — bitwise-identical
    # default path, pinned by tests/test_cold_store.py.

    def evict_plan(self, state: ReplayState, block: int) -> jax.Array:
        """Start slot of the minimum-priority-mass contiguous
        `block`-slot window (windowed leaf-mass sum via cumsum; the
        argmin range [0, capacity-block] never wraps, so the start is
        always a legal dynamic-slice origin)."""
        leaves = state.tree[self.capacity:]
        c = jnp.concatenate([jnp.zeros(1, leaves.dtype),
                             jnp.cumsum(leaves)])
        return jnp.argmin(c[block:] - c[:-block]).astype(jnp.int32)

    def read_region(self, state: ReplayState, start: jax.Array,
                    block: int) -> tuple[Any, jax.Array]:
        """-> (items [block, ...] in staging layout, stored leaf
        priorities [block]) for the region about to be overwritten."""
        items = jax.tree.map(
            lambda buf: jax.lax.dynamic_slice_in_dim(buf, start, block),
            state.storage)
        if self._packer is not None:
            items = self._packer.decode(items)
        pri = jax.lax.dynamic_slice_in_dim(
            state.tree, self.capacity + start, block)
        return items, pri

    def add_at(self, state: ReplayState, items: Any, td_abs: jax.Array,
               start: jax.Array) -> ReplayState:
        """Directed `add`: overwrite the `B` slots at `start` (an
        evict_plan result) instead of the FIFO cursor position."""
        return self._write_block(state, items, td_abs, lead=(),
                                 start=start)

    def add_at_lockstep(self, state: ReplayState, items: Any,
                        td_abs: jax.Array,
                        start: jax.Array) -> ReplayState:
        """Directed `add_lockstep`: shard d of the [dp, ...]-stacked
        state gets items[d] at start[d] (each shard's own evict_plan
        result — the dp form of the cold tier's eviction swap). Writes
        are dp unrolled single-shard DUS calls (dus_rows_per_shard);
        shard cursors DIVERGE here, which is safe because the eviction
        swap only runs once the ring is full — every subsequent ship
        routes back through evict_plan/add_at, so the lockstep FIFO
        cursor is never consulted again (pinned by
        tests/test_ingest.py's dp=2 cold closure test)."""
        return self._write_block(state, items, td_abs,
                                 lead=(td_abs.shape[0],), start=start)

    def sample_items(self, state: ReplayState, rng: jax.Array, batch: int
                     ) -> tuple[Any, jax.Array, jax.Array]:
        """-> (item batch pytree, leaf indices [B], probs [B]) without IS
        weights — the dist learner computes those globally across shards
        (parallel/dist_learner.py), and FrameRingReplay shares the
        calling convention."""
        idx, probs = sum_tree.sample(state.tree, rng, batch,
                                     size=state.size)
        items = jax.tree.map(lambda buf: buf[idx], state.storage)
        if self._packer is not None:
            items = self._packer.decode(items)
        return items, idx, probs

    def sample(self, state: ReplayState, rng: jax.Array, batch: int
               ) -> tuple[Any, jax.Array, jax.Array]:
        """-> (item batch pytree, leaf indices [B], IS weights [B]).

        valid_mask zeroes the weight of storage layouts' dead slots
        BEFORE max-normalization (a ~zero-probability dead draw would
        otherwise become the max and crush every live weight); for flat
        storage it is all-ones and folds away."""
        items, idx, probs = self.sample_items(state, rng, batch)
        n = jnp.maximum(state.size.astype(jnp.float32), 1.0)
        w = (n * jnp.maximum(probs, 1e-12)) ** (-self.beta)
        w = w * self.valid_mask(state, idx)
        w = w / jnp.maximum(w.max(), 1e-12)
        return items, idx, w

    def update_priorities(self, state: ReplayState, idx: jax.Array,
                          td_abs: jax.Array) -> ReplayState:
        pri = (td_abs + self.eps) ** self.alpha
        return state._replace(tree=sum_tree.update(state.tree, idx, pri))

    def valid_mask(self, state: ReplayState, idx: jax.Array) -> jax.Array:
        """[B] f32: 1 where idx is trainable. Flat storage has no dead
        slots; the frame-ring layout overrides this (pad slots)."""
        return jnp.ones(idx.shape, jnp.float32)

    # -- learning-health accessors (obs/learning.py; pure, jit-safe) -------

    # static capability flag: UniformReplayDevice sets False so the
    # learner's diag tap specializes away the priority statistics
    has_priorities = True

    def leaf_priorities(self, state: ReplayState,
                        idx: jax.Array) -> jax.Array:
        """Stored p^alpha at the given leaf indices (any idx shape)."""
        return state.tree[self.capacity + idx]

    def cursor_transitions(self, state: ReplayState) -> jax.Array:
        """Write cursor in TRANSITION (= leaf-index) units, so ring
        distance to a sampled leaf is its age in transitions. The
        frame-ring layout overrides (its cursor counts segments)."""
        return state.pos

    # -- split entry points (double-buffered learner pipeline) -------------

    def sample_state(self, state: ReplayState, rng: jax.Array, batch: int
                     ) -> tuple[Any, jax.Array, jax.Array]:
        """SAMPLE half of the split learner cycle — `sample` under its
        pipeline-contract name. Reads only storage, tree, and size
        (never the write cursor `pos`), so a prefetched draw commutes
        with a concurrent `update_state` write-back: the draw simply
        sees the pre-write-back priorities, the one-dispatch staleness
        the double-buffered train_many accepts by design. Subclasses
        override sample/sample_items, not this delegator, so every
        storage layout inherits the contract."""
        return self.sample(state, rng, batch)

    def update_state(self, state: ReplayState, idx: jax.Array,
                     td_abs: jax.Array) -> ReplayState:
        """UPDATE half of the split learner cycle — `update_priorities`
        under its pipeline-contract name. Writes ONLY the sum-tree
        (storage/pos/size pass through untouched), which is what makes
        it safe to reorder against a prefetched sample_state draw."""
        return self.update_priorities(state, idx, td_abs)

    # -- convenience jitted endpoints (standalone use / replay server) -----

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add_jit(self, state, items, td_abs):
        return self.add(state, items, td_abs)

    @partial(jax.jit, static_argnums=(0, 3))
    def sample_jit(self, state, rng, batch):
        return self.sample(state, rng, batch)

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def update_priorities_jit(self, state, idx, td_abs):
        return self.update_priorities(state, idx, td_abs)


class UniformReplayDevice:
    """Uniform ring buffer with the same pure-functional API (config 1).

    Sampling is uniform over filled slots; IS weights are all ones.
    """

    def __init__(self, capacity: int, item_spec: Any = None):
        assert capacity > 0 and (capacity & (capacity - 1)) == 0
        self.capacity = capacity
        self._packer: PixelPacker | None = None
        self._storage_spec: Any = None
        if item_spec is not None:  # deterministic, like PrioritizedReplay
            self._build_packer(item_spec)

    def _build_packer(self, item_spec: Any) -> None:
        self._packer, self._storage_spec = make_packer(item_spec)

    def init(self, item_spec: Any = None) -> ReplayState:
        if item_spec is not None:
            self._build_packer(item_spec)
        if self._storage_spec is None:
            raise ValueError(
                "UniformReplayDevice has no item spec — pass item_spec "
                "to the constructor or to init()")
        storage = jax.tree.map(
            lambda s: jnp.zeros((self.capacity, *s.shape), s.dtype),
            self._storage_spec)
        return ReplayState(storage=storage,
                           tree=jnp.zeros(1, jnp.float32),  # unused
                           pos=jnp.int32(0), size=jnp.int32(0))

    def add(self, state: ReplayState, items: Any,
            td_abs: jax.Array | None = None) -> ReplayState:
        b = jax.tree.leaves(items)[0].shape[0]
        start = ring_write_start(state.pos, b, self.capacity)
        if self._packer is not None:
            items = self._packer.encode(items)
        storage = jax.tree.map(
            lambda buf, x: dus_rows(buf, x, start), state.storage, items)
        return ReplayState(
            storage=storage, tree=state.tree,
            pos=(start + b) % self.capacity,
            size=ring_write_size(state.size, start, b, self.capacity))

    def sample(self, state: ReplayState, rng: jax.Array, batch: int):
        idx = jax.random.randint(rng, (batch,), 0,
                                 jnp.maximum(state.size, 1))
        items = jax.tree.map(lambda buf: buf[idx], state.storage)
        if self._packer is not None:
            items = self._packer.decode(items)
        return items, idx, jnp.ones(batch, jnp.float32)

    def update_priorities(self, state: ReplayState, idx, td_abs):
        return state

    # learning-health accessors: no tree, so priority statistics are
    # statically skipped by the learner's diag tap
    has_priorities = False

    def leaf_priorities(self, state: ReplayState, idx):
        return jnp.zeros(idx.shape, jnp.float32)

    def cursor_transitions(self, state: ReplayState):
        return state.pos

    # split entry points (see PrioritizedReplay): sampling is uniform
    # and updates are no-ops, so the commuting contract holds trivially
    def sample_state(self, state: ReplayState, rng: jax.Array, batch: int):
        return self.sample(state, rng, batch)

    def update_state(self, state: ReplayState, idx, td_abs):
        return self.update_priorities(state, idx, td_abs)
