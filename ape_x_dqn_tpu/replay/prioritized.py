"""Device-resident prioritized replay.

Storage (an arbitrary transition pytree of per-slot arrays) and the
sum-tree both live in HBM; add / sample / priority-update are pure
functions designed to be fused into the learner's single jit (SURVEY.md
§7 step 5, §2.3 item 5). The learner is the single owner of the buffer
state, which removes the sample-vs-update race of host-side designs by
construction (SURVEY.md §5 "race detection").

Conventions (Schaul et al. 2016; Horgan et al. 2018):
- stored priority = (|td| + eps)^alpha  (alpha applied at write time)
- IS weight w_i = (N * P(i))^-beta, normalized by max over the batch
- new transitions arrive WITH priorities (actors compute initial
  priorities actor-side — SURVEY.md §2.2 "Actor runtime")
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from ape_x_dqn_tpu.ops import sum_tree


class ReplayState(NamedTuple):
    storage: Any          # pytree of [capacity, ...] arrays
    tree: jax.Array       # (2*capacity,) sum-tree of p^alpha
    pos: jax.Array        # int32 next write cursor
    size: jax.Array       # int32 filled slots


class PrioritizedReplay:
    """Static config + pure state-transition functions."""

    def __init__(self, capacity: int, alpha: float = 0.6, beta: float = 0.4,
                 eps: float = 1e-6):
        assert capacity > 0 and (capacity & (capacity - 1)) == 0, \
            "capacity must be a power of two"
        self.capacity = capacity
        self.alpha = alpha
        self.beta = beta
        self.eps = eps

    # -- state construction ------------------------------------------------

    def init(self, item_spec: Any) -> ReplayState:
        """item_spec: pytree of ShapeDtypeStruct (or arrays) for ONE item."""
        storage = jax.tree.map(
            lambda s: jnp.zeros((self.capacity, *s.shape), s.dtype),
            item_spec)
        return ReplayState(
            storage=storage, tree=sum_tree.init(self.capacity),
            pos=jnp.int32(0), size=jnp.int32(0))

    # -- transitions (all pure, jit-friendly) ------------------------------

    def add(self, state: ReplayState, items: Any,
            td_abs: jax.Array) -> ReplayState:
        """Append a batch of items with initial |TD| priorities.

        items: pytree of [B, ...] arrays; td_abs: [B] f32.
        Overwrites FIFO when full (ring semantics via modular cursor).
        """
        b = td_abs.shape[0]
        idx = (state.pos + jnp.arange(b, dtype=jnp.int32)) % self.capacity
        storage = jax.tree.map(
            lambda buf, x: buf.at[idx].set(x.astype(buf.dtype)),
            state.storage, items)
        pri = (td_abs + self.eps) ** self.alpha
        tree = sum_tree.update(state.tree, idx, pri)
        return ReplayState(
            storage=storage, tree=tree,
            pos=(state.pos + b) % self.capacity,
            size=jnp.minimum(state.size + b, self.capacity))

    def sample_items(self, state: ReplayState, rng: jax.Array, batch: int
                     ) -> tuple[Any, jax.Array, jax.Array]:
        """-> (item batch pytree, leaf indices [B], probs [B]) without IS
        weights — the dist learner computes those globally across shards
        (parallel/dist_learner.py), and FrameRingReplay shares the
        calling convention."""
        idx, probs = sum_tree.sample(state.tree, rng, batch,
                                     size=state.size)
        items = jax.tree.map(lambda buf: buf[idx], state.storage)
        return items, idx, probs

    def sample(self, state: ReplayState, rng: jax.Array, batch: int
               ) -> tuple[Any, jax.Array, jax.Array]:
        """-> (item batch pytree, leaf indices [B], IS weights [B]).

        valid_mask zeroes the weight of storage layouts' dead slots
        BEFORE max-normalization (a ~zero-probability dead draw would
        otherwise become the max and crush every live weight); for flat
        storage it is all-ones and folds away."""
        items, idx, probs = self.sample_items(state, rng, batch)
        n = jnp.maximum(state.size.astype(jnp.float32), 1.0)
        w = (n * jnp.maximum(probs, 1e-12)) ** (-self.beta)
        w = w * self.valid_mask(state, idx)
        w = w / jnp.maximum(w.max(), 1e-12)
        return items, idx, w

    def update_priorities(self, state: ReplayState, idx: jax.Array,
                          td_abs: jax.Array) -> ReplayState:
        pri = (td_abs + self.eps) ** self.alpha
        return state._replace(tree=sum_tree.update(state.tree, idx, pri))

    def valid_mask(self, state: ReplayState, idx: jax.Array) -> jax.Array:
        """[B] f32: 1 where idx is trainable. Flat storage has no dead
        slots; the frame-ring layout overrides this (pad slots)."""
        return jnp.ones(idx.shape, jnp.float32)

    # -- convenience jitted endpoints (standalone use / replay server) -----

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add_jit(self, state, items, td_abs):
        return self.add(state, items, td_abs)

    @partial(jax.jit, static_argnums=(0, 3))
    def sample_jit(self, state, rng, batch):
        return self.sample(state, rng, batch)

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def update_priorities_jit(self, state, idx, td_abs):
        return self.update_priorities(state, idx, td_abs)


class UniformReplayDevice:
    """Uniform ring buffer with the same pure-functional API (config 1).

    Sampling is uniform over filled slots; IS weights are all ones.
    """

    def __init__(self, capacity: int):
        assert capacity > 0 and (capacity & (capacity - 1)) == 0
        self.capacity = capacity

    def init(self, item_spec: Any) -> ReplayState:
        storage = jax.tree.map(
            lambda s: jnp.zeros((self.capacity, *s.shape), s.dtype),
            item_spec)
        return ReplayState(storage=storage,
                           tree=jnp.zeros(1, jnp.float32),  # unused
                           pos=jnp.int32(0), size=jnp.int32(0))

    def add(self, state: ReplayState, items: Any,
            td_abs: jax.Array | None = None) -> ReplayState:
        b = jax.tree.leaves(items)[0].shape[0]
        idx = (state.pos + jnp.arange(b, dtype=jnp.int32)) % self.capacity
        storage = jax.tree.map(
            lambda buf, x: buf.at[idx].set(x.astype(buf.dtype)),
            state.storage, items)
        return ReplayState(
            storage=storage, tree=state.tree,
            pos=(state.pos + b) % self.capacity,
            size=jnp.minimum(state.size + b, self.capacity))

    def sample(self, state: ReplayState, rng: jax.Array, batch: int):
        idx = jax.random.randint(rng, (batch,), 0,
                                 jnp.maximum(state.size, 1))
        items = jax.tree.map(lambda buf: buf[idx], state.storage)
        return items, idx, jnp.ones(batch, jnp.float32)

    def update_priorities(self, state: ReplayState, idx, td_abs):
        return state
