"""Host-RAM cold tier behind the device replay ring (PR 11, ROADMAP 3).

The device-resident ring (flat or frame-ring) is the HOT set — its
capacity is a chip-memory constant (flagship 2^20 transitions ~ 20 GB
HBM). This module turns retention into a provisioning knob: when the
ring is full, the driver evicts the ring's lowest-priority-mass region
(replay .evict_plan/.read_region) to a ColdStore segment compressed
with the delta+deflate wire codec (replay/packing.py cold_pack, riding
the C++ kernels in cpp/framing.cpp via comm/native.py — or their
bit-identical numpy fallback), and an idle-time refill path recalls the
highest-mass cold segments back through the double-buffered
IngestStager so recalled data rides the exact same one-copy
staging->add path as fresh actor data.

Priority-mass bookkeeping: each segment carries the sum and max of the
sum-tree mass its transitions held at eviction (p = (|td|+eps)^alpha,
exactly the leaf values). Admission and displacement order by mass_sum
(what sampling probability the segment would contribute back);
recall pops the highest mass first. When the store is full, a new
segment displaces the lowest-mass stored segments only if it carries
more mass than they do — otherwise it is dropped at the door. The
driver pins the resulting closure: evicted == cold_stored +
cold_dropped (displacements are a separate counter; a displaced
segment was stored first, so the closure stays exact).

Pure host-side code: numpy + zlib, no jax. Thread ownership: the
driver's ingest thread is the only caller (evict on ship, recall on
idle tick), so there is no locking here.
"""

from __future__ import annotations

import bisect
import logging
from typing import Any

import numpy as np

from ape_x_dqn_tpu.replay.packing import cold_pack, cold_plan, cold_unpack

log = logging.getLogger(__name__)


def codec_status() -> tuple[bool, str]:
    """-> (available, detail). The cold tier needs the delta+deflate
    codec from comm/native.py; `available` is False only when that
    module genuinely fails to import (broken install), because a
    stale/missing libapex_framing.so degrades to a bit-identical numpy
    fallback — detail says which path is live ("native" /
    "numpy-fallback") so ColdStore can log the one-liner."""
    try:
        from ape_x_dqn_tpu.comm import native
    except Exception as e:  # pragma: no cover - broken install only
        return False, f"{type(e).__name__}: {e}"
    return True, ("native" if native.have_delta_native()
                  else "numpy-fallback")


class ColdSegment:
    """One compressed eviction region (host bytes + priority summary)."""

    __slots__ = ("payload", "units", "live", "raw_bytes",
                 "mass_sum", "mass_max", "seq")

    def __init__(self, payload: bytes, units: int, live: int,
                 raw_bytes: int, mass_sum: float, mass_max: float,
                 seq: int):
        self.payload = payload
        self.units = units          # staging units (segments / transitions)
        self.live = live            # live transitions (pri > 0)
        self.raw_bytes = raw_bytes  # uncompressed leaf bytes
        self.mass_sum = mass_sum    # sum-tree mass at eviction
        self.mass_max = mass_max
        self.seq = seq              # admission order (stable tiebreak)


class ColdStore:
    """Fixed-capacity host-RAM store of compressed eviction segments,
    ordered by priority mass.

    capacity_transitions bounds LIVE transitions held (dead frame-ring
    pad slots ride along in the payload but don't count — they carry
    zero mass and zero sampling probability). unit_items converts
    staging units to transitions for the ring-multiple stats
    (seg_transitions in frame mode, 1 flat).
    """

    def __init__(self, item_spec: Any, capacity_transitions: int,
                 unit_items: int = 1, ptail: tuple = (),
                 compress_level: int = 1, spill: Any = None):
        ok, detail = codec_status()
        if not ok:  # configs.py validation normally rejects this earlier
            raise RuntimeError(f"cold tier codec unavailable: {detail}")
        if detail != "native":
            log.warning(
                "cold tier: libapex_framing.so missing or stale — using "
                "the bit-identical numpy delta codec (slower, same bytes)")
        self.capacity = int(capacity_transitions)
        self.unit_items = int(unit_items)
        self.level = int(compress_level)
        # optional disk rung (replay/disk_store.py): door losers —
        # displaced victims and live door-dropped candidates — are
        # offered there instead of vanishing. offer() never blocks.
        self.spill = spill
        self._plan = cold_plan(item_spec, ptail)
        # ascending (mass_sum, seq): [0] is the next displacement
        # victim, [-1] the next recall
        self._segs: list[ColdSegment] = []
        self._keys: list[tuple[float, int]] = []
        self._seq = 0
        self.transitions = 0        # live transitions stored
        self.bytes_compressed = 0
        self.bytes_raw = 0
        # door counters (driver closure: evicted == stored + dropped)
        self.stored = 0
        self.dropped = 0
        self.displaced = 0
        self.recalled = 0
        self.spilled = 0            # door losers offered to the disk rung

    # -- admission ---------------------------------------------------------

    def put(self, items: dict, priorities: np.ndarray, live: int) -> str:
        """Admit one eviction region -> "stored" | "dropped".

        items: {key: [n, *unit_shape]} host arrays in STAGING layout;
        priorities: the evicted sum-tree leaf values (shape [n, B] in
        frame mode, [n] flat) — stored in the payload so a recall can
        restage at eviction-time mass. live: count of pri > 0 slots.
        """
        n = int(priorities.shape[0])
        pri = np.asarray(priorities, np.float32)
        mass_sum = float(pri.sum())
        mass_max = float(pri.max()) if pri.size else 0.0
        if live <= 0 or mass_sum <= 0.0:
            self.dropped += 1           # all-dead region: nothing to keep
            return "dropped"
        # door policy before paying for compression: displace only
        # strictly lighter segments, never heavier ones
        freed = 0
        victims = 0
        while (self.transitions + live - freed > self.capacity
               and victims < len(self._segs)
               and self._keys[victims][0] < mass_sum):
            freed += self._segs[victims].live
            victims += 1
        if self.transitions + live - freed > self.capacity:
            self.dropped += 1
            if self.spill is not None:
                # the candidate lost the RAM door but still carries
                # live mass: pack it and offer it to the disk rung
                # (non-blocking; a full queue loses it exactly as the
                # drop would have)
                payload, raw = cold_pack(dict(items, priorities=pri),
                                         self._plan, self.level)
                if self.spill.offer(ColdSegment(
                        payload, n, int(live), raw, mass_sum, mass_max,
                        self._seq)):
                    self.spilled += 1
                self._seq += 1
            return "dropped"
        for seg in self._segs[:victims]:
            self.transitions -= seg.live
            self.bytes_compressed -= len(seg.payload)
            self.bytes_raw -= seg.raw_bytes
            if self.spill is not None and self.spill.offer(seg):
                self.spilled += 1
        del self._segs[:victims], self._keys[:victims]
        self.displaced += victims

        payload, raw = cold_pack(dict(items, priorities=pri),
                                 self._plan, self.level)
        seg = ColdSegment(payload, n, int(live), raw, mass_sum, mass_max,
                          self._seq)
        self._seq += 1
        key = (seg.mass_sum, seg.seq)
        at = bisect.bisect(self._keys, key)
        self._segs.insert(at, seg)
        self._keys.insert(at, key)
        self.transitions += seg.live
        self.bytes_compressed += len(payload)
        self.bytes_raw += raw
        self.stored += 1
        return "stored"

    def put_segment(self, seg: ColdSegment) -> str:
        """Admit an already-packed segment (a disk promotion) through
        the same door -> "stored" | "dropped". Displaced victims spill
        back to disk, but a door-dropped CANDIDATE is intentionally
        lost rather than re-spilled: re-offering a segment the door
        just rejected would ping-pong it between the rungs forever
        (the promote() floor makes this path rare — it only fires when
        the floor rose mid-tick). Does NOT touch the eviction-door
        stored/dropped counters: the driver's closure evicted ==
        cold_stored + cold_dropped is denominated in ring evictions,
        and promotions are not evictions."""
        if seg.live <= 0 or seg.mass_sum <= 0.0:
            return "dropped"
        freed = 0
        victims = 0
        while (self.transitions + seg.live - freed > self.capacity
               and victims < len(self._segs)
               and self._keys[victims][0] < seg.mass_sum):
            freed += self._segs[victims].live
            victims += 1
        if self.transitions + seg.live - freed > self.capacity:
            return "dropped"
        for victim in self._segs[:victims]:
            self.transitions -= victim.live
            self.bytes_compressed -= len(victim.payload)
            self.bytes_raw -= victim.raw_bytes
            if self.spill is not None and self.spill.offer(victim):
                self.spilled += 1
        del self._segs[:victims], self._keys[:victims]
        self.displaced += victims
        seg.seq = self._seq         # re-key in RAM admission order
        self._seq += 1
        key = (seg.mass_sum, seg.seq)
        at = bisect.bisect(self._keys, key)
        self._segs.insert(at, seg)
        self._keys.insert(at, key)
        self.transitions += seg.live
        self.bytes_compressed += len(seg.payload)
        self.bytes_raw += seg.raw_bytes
        return "stored"

    def displacement_floor(self) -> float:
        """Minimum mass_sum a candidate needs to clear the door right
        now: the lightest stored segment's mass when the store is full,
        else 0.0 (free space admits anything live). The disk rung's
        promote() uses this to skip segments — and whole files — that
        would bounce."""
        if not self._segs or self.transitions < self.capacity:
            return 0.0
        return self._keys[0][0]

    # -- recall ------------------------------------------------------------

    def recall(self, k: int = 1) -> list[dict]:
        """Pop the k highest-mass segments, decompressed back to
        STAGING layout ({item keys: [n, ...]} + "priorities" holding
        the eviction-time sum-tree leaf values). Bitwise equal to what
        was evicted (tests/test_cold_store.py)."""
        out = []
        for _ in range(min(int(k), len(self._segs))):
            seg = self._segs.pop()
            self._keys.pop()
            self.transitions -= seg.live
            self.bytes_compressed -= len(seg.payload)
            self.bytes_raw -= seg.raw_bytes
            self.recalled += 1
            out.append(cold_unpack(seg.payload, self._plan, seg.units))
        return out

    # -- stats -------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._segs)

    def compression_ratio(self) -> float:
        """raw/compressed bytes over the resident set, floored at 1.0:
        the per-leaf never-inflate guard (packing.cold_pack) bounds any
        overshoot to the constant 9-byte/leaf framing, so the floor is
        the honest healthy-range bound the obs row warns below."""
        if self.bytes_compressed <= 0:
            return 1.0
        return max(1.0, self.bytes_raw / self.bytes_compressed)
