"""Tile-exact pixel packing + in-place ring writes for HBM replay.

Why this module exists (round-5 HBM story, PERF.md "HBM budget"):

TPU HBM arrays are stored in (8, 128)-tiled layouts — for uint8 the
effective tile is (32, 128) over the two minor dimensions. A pixel
buffer shaped [capacity, 84, 84] therefore pads 84 -> (88, 128) and
occupies **1.6x** its logical bytes. Worse, XLA then assigns the
*parameter* a compact (unpadded) layout to save that memory and inserts
a full-buffer relayout copy inside every program that gathers from or
scatters into it: measured on the v5e chip, the pong preset's 9.47GB
frame ring compiled to a 15.12GB HLO temp copy inside `add` (25.1GB
total — OOM on a 15.75GB chip).

Two design rules eliminate both costs:

1. **Pack pixel leaves into exactly-tiled byte rows.** Store
   [capacity, pad128(prod(frame_dims))] uint8 — the minor dim a
   multiple of 128 and the major dim a multiple of 32 makes the padded
   tiled layout bit-identical to the compact layout, so no relayout
   copy can exist anywhere, and the storage overhead is the row
   padding alone (<=1.6%, e.g. 7056 -> 7168 bytes for an 84x84 frame).
   Unpacking after a sample's row gather touches only the sampled
   batch (MBs, not GBs).

2. **Ring writes are `dynamic_update_slice`, never scatter.** A
   scatter into a large donated buffer still materializes a full copy
   (measured: 19.1GB for the 9.47GB 2-D ring); a dynamic_update_slice
   on a donated argument aliases in place (measured: temp=0). Since a
   replay add always writes a contiguous index block, the only case
   DUS cannot express is a block wrapping the ring boundary — handled
   by SKIP-TO-HEAD semantics: a block that would wrap is written at
   slot 0 instead, leaving the few tail slots holding their previous
   (still-consistent) items. When the block size divides the capacity
   the wrap case never occurs and semantics are bit-identical to the
   modular ring — which covers the frame-ring/segment ingest paths
   (fixed-size segment blocks, capacity a multiple of the segment
   size) but NOT every flat-transition path: the default
   ActorConfig.ingest_batch=50 does not divide a power-of-two
   capacity, and a shutdown flush ships whatever partial block
   remains. For such non-dividing block sizes, every skip restarts
   the cursor at slot 0 and up to block-1 tail slots are permanently
   RETIRED: ring_write_size never counts them as filled, the sum-tree
   never carries priority there, and sampling never returns them
   (regression-tested in tests/test_packing.py with ingest_batch=50)
   — a <= block/capacity capacity loss, not a correctness hazard.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# Minor-dim tile width (lanes) shared by every TPU dtype; uint8 arrays
# additionally want the second-minor dim a multiple of 32 (8 sublanes x
# 4-byte packing) for the padded layout to equal the compact one.
LANE = 128
U8_SUBLANE = 32


def pad128(n: int) -> int:
    """Round up to the 128-byte lane tile."""
    return -(-int(n) // LANE) * LANE


def frame_mode(storage: str, obs_shape: tuple[int, ...]) -> bool:
    """THE single-frame-storage predicate — shared (aliased) by
    replay/frame_ring.frame_ring_mode (flat-DQN segment layout) and
    replay/sequence.sequence_frame_mode (R2D2 sequence layout), and
    through them by runtime/family.py (layout selection) and
    utils/hbm.py (budget pricing), so the selection and the pricing can
    never drift: frame mode applies to [H, W, stack] pixel observations
    under frame_ring storage, any dtype (the byte-row packing inside
    the replay additionally engages only for uint8, but the item SHAPE
    is the same either way; the frame-ring layout's uint8 requirement
    is enforced with a ValueError at FrameRingReplay construction).

    Defined here rather than in either layout module because packing
    is the one module both already import — the two predicates used to
    be byte-identical copies, each claiming to be "THE predicate", and
    could drift exactly the way the claim promised they couldn't."""
    return storage == "frame_ring" and len(obs_shape) == 3


def ring_write_start(pos: jax.Array, block: int, capacity: int) -> jax.Array:
    """Start slot for an in-place contiguous ring write (skip-to-head).

    pos is the ring cursor; a `block`-slot write that would cross the
    ring boundary restarts at 0 (see module docstring). Returns the
    int32 start slot; the caller advances the cursor to
    (start + block) % capacity.

    Correct for ANY block size, including the non-dividing remainder a
    single-chip shutdown flush ships: tail slots a skip leaves behind
    keep their previous (still index-consistent) items, and callers
    must grow `size` as min(max(size, start + block), capacity) —
    NOT size + block — so never-written tail slots are never counted
    as filled (ring_write_size below).
    """
    assert block <= capacity, (block, capacity)
    return jnp.where(pos + block <= capacity, pos, 0).astype(jnp.int32)


def ring_write_size(size: jax.Array, start: jax.Array, block: int,
                    capacity: int) -> jax.Array:
    """Filled-slot count after a skip-to-head ring write. Pre-fill the
    ring fills [0, size) contiguously, so the new high-water mark is
    max(size, start + block); a skip that restarts at 0 therefore does
    NOT count the unwritten tail as filled (a plain size+block would —
    and uniform sampling would then draw all-zero slots)."""
    return jnp.minimum(jnp.maximum(size, start + block), capacity)


def dus_rows(buf: jax.Array, block: jax.Array, start: jax.Array,
             lead: int = 0) -> jax.Array:
    """dynamic_update_slice of a block at row `start` on axis `lead` —
    the in-place ring write (donated callers alias; scatter would
    copy). Axes before `lead` are written at origin over their full
    extent: the dist learners' lockstep form updates every [dp] shard
    of a [dp, capacity, ...] buffer in the same DUS (lead=1), which is
    what keeps the mesh add in place — a jax.vmap over the shard axis
    would rebatch the DUS into a full-copy scatter."""
    idx = ((jnp.int32(0),) * lead + (start,)
           + (jnp.int32(0),) * (buf.ndim - lead - 1))
    return jax.lax.dynamic_update_slice(buf, block.astype(buf.dtype), idx)


def dus_rows_per_shard(buf: jax.Array, block: jax.Array,
                       starts: jax.Array) -> jax.Array:
    """Per-shard directed ring write: shard d of a [dp, capacity, ...]
    buffer gets block[d] at row starts[d] — the dist form of the cold
    tier's add_at, where each shard's evict_plan picked its OWN region.

    dp single-shard multi-axis DUS calls, unrolled (dp is static).
    Chained DUS into a donated buffer alias in place; the obvious
    jax.vmap over the shard axis would rebatch the DUS into a
    lax.scatter and materialize a full-buffer copy (see dus_rows)."""
    dp = block.shape[0]
    out = buf
    for d in range(dp):
        idx = ((jnp.int32(d), starts[d])
               + (jnp.int32(0),) * (buf.ndim - 2))
        out = jax.lax.dynamic_update_slice(
            out, block[d:d + 1].astype(buf.dtype), idx)
    return out


def packable(spec) -> bool:
    """Pack uint8 pixel leaves big enough that tile padding matters.

    Small leaves (scalars, action vectors) stay in their natural layout
    — their padding is bytes, and reshaping them would cost more in
    decode than it saves.
    """
    return (np.dtype(spec.dtype) == np.uint8 and len(spec.shape) >= 2
            and math.prod(spec.shape) >= 4096)


class PixelPacker:
    """Per-leaf codec: pixel frames <-> exactly-tiled byte rows.

    Built from an item spec (pytree of ShapeDtypeStruct for ONE item).
    `storage_spec` rewrites packable leaves to [pad128(nbytes)] uint8
    rows; `encode` flattens+pads an incoming [b, ...] item block to row
    form inside the add jit; `decode` restores a sampled [b, rows]
    gather to the original frame shape (touches only the batch).
    """

    def __init__(self, item_spec: Any):
        leaves, treedef = jax.tree.flatten(item_spec)
        self._treedef = treedef
        self._plan = []  # per leaf: None | (orig_shape, nbytes, row)
        for leaf in leaves:
            if packable(leaf):
                nbytes = math.prod(leaf.shape)
                self._plan.append((tuple(leaf.shape), nbytes,
                                   pad128(nbytes)))
            else:
                self._plan.append(None)

    @property
    def packs_anything(self) -> bool:
        return any(p is not None for p in self._plan)

    def storage_spec(self, item_spec: Any) -> Any:
        leaves = jax.tree.leaves(item_spec)
        out = []
        for leaf, plan in zip(leaves, self._plan):
            if plan is None:
                out.append(leaf)
            else:
                _, _, row = plan
                out.append(jax.ShapeDtypeStruct((row,), jnp.uint8))
        return jax.tree.unflatten(self._treedef, out)

    def encode(self, items: Any) -> Any:
        """[*lead, *orig] leaves -> [*lead, row] byte rows (zero pad).
        Any number of leading batch axes ([b] single-chip, [dp, b] on
        the mesh) — the item dims are always the trailing ones."""
        leaves = jax.tree.leaves(items)
        out = []
        for leaf, plan in zip(leaves, self._plan):
            if plan is None:
                out.append(leaf)
            else:
                shape, nbytes, row = plan
                lead = leaf.shape[:leaf.ndim - len(shape)]
                flat = leaf.reshape(*lead, nbytes)
                if row != nbytes:
                    pad = [(0, 0)] * len(lead) + [(0, row - nbytes)]
                    flat = jnp.pad(flat, pad)
                out.append(flat)
        return jax.tree.unflatten(self._treedef, out)

    def decode(self, items: Any) -> Any:
        """Sampled [*lead, row] byte rows -> [*lead, *orig] frames."""
        leaves = jax.tree.leaves(items)
        out = []
        for leaf, plan in zip(leaves, self._plan):
            if plan is None:
                out.append(leaf)
            else:
                shape, nbytes, row = plan
                lead = leaf.shape[:-1]
                out.append(leaf[..., :nbytes].reshape(*lead, *shape))
        return jax.tree.unflatten(self._treedef, out)


# -- cold-segment serialization (replay/cold_store.py) ----------------------
#
# A cold segment is one eviction region — `n` staging units of the item
# spec plus their stored priorities — flattened to ONE host byte string:
# per leaf, delta-XOR (uint8 pixel leaves, reusing the wire codec's
# kernels in comm/native.py) + zlib deflate, framed with pack_records.
# A 1-byte mode prefix per leaf records what was applied, with a
# per-leaf never-inflate guard: if deflate would grow a leaf, its raw
# bytes are stored instead (mode 0), so a segment's payload can exceed
# its raw bytes only by the constant framing overhead (9 bytes/leaf).
# Round trips are bitwise exact in every mode (XOR and deflate both
# are; tests/test_cold_store.py pins it on both storage layouts).

_COLD_RAW = 0        # leaf bytes verbatim
_COLD_DEFLATE = 1    # zlib only
_COLD_DELTA = 2      # XOR-delta rows, then zlib


def cold_plan(item_spec: Any, ptail: tuple = ()) -> list[tuple]:
    """Per-leaf serialization plan for one staging unit: [(key, shape,
    dtype, delta_rows)]. delta_rows is the per-unit leading axis the
    XOR-delta transform rows over (frames of a segment / image rows of
    a stacked obs) or 0 for non-delta leaves. "priorities" (trailing
    shape `ptail`, f32) is appended — it rides every cold segment so a
    recall can restage with its eviction-time priority mass."""
    plan = []
    entries = [(k, tuple(s.shape), np.dtype(s.dtype))
               for k, s in item_spec.items()]
    entries.append(("priorities", tuple(ptail), np.dtype(np.float32)))
    for key, shape, dtype in entries:
        unit_bytes = math.prod(shape) * dtype.itemsize if shape \
            else dtype.itemsize
        delta_rows = (int(shape[0])
                      if (dtype == np.uint8 and len(shape) >= 2
                          and unit_bytes >= 4096) else 0)
        plan.append((key, shape, dtype, delta_rows))
    return plan


def cold_pack(items: dict, plan: list[tuple],
              level: int = 1) -> tuple[bytes, int]:
    """Serialize {key: [n, *shape] host arrays} -> (payload, raw_bytes)
    following `plan`. Pure host work (numpy + zlib + the comm/native.py
    delta kernels or their bit-identical numpy fallback)."""
    import zlib

    from ape_x_dqn_tpu.comm.native import delta_encode, pack_records

    chunks = []
    raw_total = 0
    for key, shape, dtype, delta_rows in plan:
        a = np.ascontiguousarray(np.asarray(items[key], dtype=dtype))
        raw_total += a.nbytes
        if delta_rows:
            n = a.shape[0]
            body = zlib.compress(
                delta_encode(a.reshape(n * delta_rows, -1)), level)
            mode = _COLD_DELTA
        else:
            body = zlib.compress(a.tobytes(), level)
            mode = _COLD_DEFLATE
        if len(body) >= a.nbytes:  # never-inflate guard (per leaf)
            body, mode = a.tobytes(), _COLD_RAW
        chunks.append(bytes([mode]) + body)
    return pack_records(chunks), raw_total


def cold_unpack(payload: bytes, plan: list[tuple], n: int) -> dict:
    """Inverse of cold_pack: payload -> {key: [n, *shape] arrays},
    bitwise equal to what went in. Returned arrays may be read-only
    views over decompressed bytes (the restage path only reads)."""
    import zlib

    from ape_x_dqn_tpu.comm.native import (delta_undo_inplace,
                                           unpack_records)

    recs = unpack_records(payload, max_records=len(plan) + 1)
    if len(recs) != len(plan):
        raise ValueError(
            f"cold segment holds {len(recs)} leaves, plan expects "
            f"{len(plan)} — segment written under a different item spec")
    out = {}
    for (key, shape, dtype, delta_rows), rec in zip(plan, recs):
        mode, body = rec[0], rec[1:]
        if mode == _COLD_RAW:
            raw: Any = body
        elif mode == _COLD_DEFLATE:
            raw = zlib.decompress(body)
        elif mode == _COLD_DELTA:
            rows = np.frombuffer(zlib.decompress(body), np.uint8) \
                .reshape(n * delta_rows, -1).copy()
            delta_undo_inplace(rows)
            raw = rows
        else:
            raise ValueError(f"unknown cold leaf mode {mode}")
        buf = raw.tobytes() if isinstance(raw, np.ndarray) else raw
        out[key] = np.frombuffer(buf, dtype=dtype).reshape((n, *shape))
    return out


def make_packer(item_spec: Any) -> tuple[PixelPacker | None, Any]:
    """-> (packer or None, storage spec): the one place the packing
    decision is made, shared by every replay class so storage layout
    and the HBM budget (utils/hbm.py) cannot drift."""
    spec = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), item_spec)
    packer = PixelPacker(spec)
    if packer.packs_anything:
        return packer, packer.storage_spec(spec)
    return None, spec
