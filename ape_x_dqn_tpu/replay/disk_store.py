"""Disk-spill rung under the host-RAM cold store (PR 16, ROADMAP 3).

The ColdStore (replay/cold_store.py) turns replay retention into a
host-RAM knob; this module turns it into a DISK-provisioning knob.
When the RAM store's admission door would drop a candidate segment or
displace a stored one, the loser spills here instead of vanishing:
ColdStore hands the already-compressed segment to `DiskStore.offer`,
which enqueues it on a bounded queue serviced by a single writeback
thread. The ingest thread NEVER blocks on disk — a full queue counts
(`queue_full`) and drops, it does not wait; zero ship-path blocking is
structural, not tuned.

On-disk format: append-only segment files `segments-<id:08d>.cold`,
each a concatenation of records

    [52-byte header][cold_pack payload]

with header `<4sIIddQQII` = magic b"APXD", units u32, live u32,
mass_sum f64, mass_max f64, seq u64, raw_bytes u64, payload_len u32,
crc32(payload) u32. Files roll at `file_bytes`. The index is in RAM
only: an ascending bisect list of (mass_sum, seq) -> (file_id, offset,
length, crc) mirroring ColdStore's key discipline, plus a per-file
summary (live bytes, dead bytes, max live mass) that drives both
compaction and the mass_max readback skip.

Readback (`promote`): pops the HEAVIEST index entries whose mass beats
the RAM store's current displacement floor, groups reads by file, and
skips whole files whose recorded max live mass is at or below the
floor — the consumer of ColdSegment.mass_max that PR 11 recorded but
never used. Payloads are CRC-checked on read; a mismatch is counted
and skipped, never returned.

Compaction: when a sealed file's dead fraction exceeds
`compact_frac`, the writeback thread rewrites its live records into
the active file (updating the index under the lock) and unlinks it.

Crash safety: the index is rebuilt at open by scanning record headers
sequentially. A torn tail (short header, short payload, bad magic) is
TRUNCATED at the last whole record — an fsync-less append can only
tear at the end. A CRC mismatch with intact framing (bit rot) is
skipped with an attributed error and the scan continues past it.
Appends after recovery always start a FRESH file so a truncated tail
is never extended through a stale buffered handle.

Threading: `offer` is called from the ingest thread (via ColdStore's
spill hook); `promote`/`stats`/`displacement_floor` from the ingest
thread's idle tick; the writeback/compaction work runs on the daemon
thread. `_lock` guards the index + per-file summaries + counters;
file appends happen outside the lock (single writer thread), index
mutations inside it.
"""

from __future__ import annotations

import bisect
import logging
import os
import queue
import struct
import threading
import time
import zlib

from ape_x_dqn_tpu.replay.cold_store import ColdSegment

log = logging.getLogger(__name__)

_MAGIC = b"APXD"
# magic, units, live, mass_sum, mass_max, seq, raw_bytes, payload_len, crc
_HEADER = struct.Struct("<4sIIddQQII")
HEADER_BYTES = _HEADER.size  # 52


class _FileInfo:
    """Per-segment-file summary driving compaction + readback skip."""

    __slots__ = ("live_bytes", "dead_bytes", "records", "mass_max")

    def __init__(self) -> None:
        self.live_bytes = 0   # bytes of records still in the index
        self.dead_bytes = 0   # bytes of promoted/displaced/rotten records
        self.records = 0      # live record count
        self.mass_max = 0.0   # max mass_sum over LIVE records (monotone
        #                       upper bound: not lowered on removal, so
        #                       the readback skip is conservative-safe)


class _IndexEntry:
    __slots__ = ("mass_sum", "seq", "file_id", "offset", "length",
                 "units", "live", "raw_bytes", "mass_max", "crc")

    def __init__(self, mass_sum: float, seq: int, file_id: int,
                 offset: int, length: int, units: int, live: int,
                 raw_bytes: int, mass_max: float, crc: int):
        self.mass_sum = mass_sum
        self.seq = seq              # disk-local admission order (tiebreak)
        self.file_id = file_id
        self.offset = offset        # payload offset (past the header)
        self.length = length        # payload length
        self.units = units
        self.live = live
        self.raw_bytes = raw_bytes
        self.mass_max = mass_max
        self.crc = crc

    def key(self) -> tuple[float, int]:
        return (self.mass_sum, self.seq)

    def record_bytes(self) -> int:
        return HEADER_BYTES + self.length


class DiskStore:
    """Append-only segment-file spill store, mass-ordered like ColdStore.

    capacity_transitions bounds live transitions on disk; the disk door
    mirrors the RAM door (displace strictly lighter, else drop), so the
    heaviest retained transitions across RAM+disk survive end-to-end.
    """

    def __init__(self, directory: str, capacity_transitions: int,
                 queue_depth: int = 16,
                 file_bytes: int = 64 * 1024 * 1024,
                 compact_frac: float = 0.5):
        self.dir = str(directory)
        self.capacity = int(capacity_transitions)
        self.file_bytes = int(file_bytes)
        self.compact_frac = float(compact_frac)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        # ascending (mass_sum, seq), mirroring ColdStore._keys
        self._entries: list[_IndexEntry] = []
        self._keys: list[tuple[float, int]] = []
        self._files: dict[int, _FileInfo] = {}
        self._seq = 0               # next disk-local record seq
        self._next_file_id = 0
        self._active_id = -1
        self._active_fh = None
        self._active_size = 0
        # counters (mutated under _lock once the thread runs; stats()
        # snapshots them)
        self.transitions = 0
        self.bytes_stored = 0       # live header+payload bytes indexed
        self.spilled = 0            # segments accepted off the queue
        self.promoted = 0           # segments handed back via promote()
        self.dropped = 0            # disk-door drops (lighter than floor)
        self.queue_full = 0         # offer() rejections — never waited on
        self.io_errors = 0          # IO OSErrors (segment lost/file kept)
        self.corrupt_segments = 0   # CRC/framing rejects (recovery + read)
        self.compactions = 0
        self._recover()
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, queue_depth))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._writeback_loop, name="cold-disk-writeback",
            daemon=True)
        self._thread.start()

    # -- ingest-thread API -------------------------------------------------

    def offer(self, seg: ColdSegment) -> bool:
        """Enqueue a segment for async writeback. NEVER blocks: a full
        queue counts queue_full and returns False (the segment is lost,
        exactly as it would have been without a disk tier)."""
        try:
            self._queue.put_nowait(seg)
            return True
        except queue.Full:
            with self._lock:
                self.queue_full += 1
            return False

    def promote(self, k: int, floor: float = 0.0) -> list[ColdSegment]:
        """Pop up to k of the heaviest disk segments with mass_sum >
        floor (the RAM store's displacement floor — promoting anything
        lighter would bounce off the RAM door and ping-pong).

        Readback is FILE-granular (the batched mass-ordered path):
        files are visited by descending per-file mass bound, and a file
        whose recorded max segment mass is at or below the floor is
        skipped without touching its entries or the disk — the
        ColdSegment.mass_max consumer the PR-11 field existed for. The
        bound is monotone (not lowered on removal), so a visit that
        finds nothing above the floor tightens it to the true max and
        the next tick skips the file outright. Within a file the
        heaviest segments pop first; CRC mismatches are counted,
        attributed, and skipped."""
        picked: list[_IndexEntry] = []
        with self._lock:
            order = sorted(self._files.items(),
                           key=lambda kv: -kv[1].mass_max)
            for file_id, fi in order:
                if len(picked) >= int(k):
                    break
                if fi.records <= 0 or fi.mass_max <= floor:
                    continue        # file-granular skip: bound says no
                                    # live segment can clear the door
                mine = [e for e in self._entries
                        if e.file_id == file_id and e.mass_sum > floor]
                if not mine:
                    # stale bound (heaviest record already left):
                    # tighten to the true max so the skip fires next
                    fi.mass_max = max(
                        (e.mass_sum for e in self._entries
                         if e.file_id == file_id), default=0.0)
                    continue
                mine.sort(key=lambda e: (-e.mass_sum, -e.seq))
                take = mine[:int(k) - len(picked)]
                gone = {id(e) for e in take}
                self._entries = [e for e in self._entries
                                 if id(e) not in gone]
                self._keys = [e.key() for e in self._entries]
                for e in take:
                    self._remove_accounting(e)
                picked.extend(take)
        out: list[ColdSegment] = []
        by_file: dict[int, list[_IndexEntry]] = {}
        for e in picked:
            by_file.setdefault(e.file_id, []).append(e)
        for file_id, entries in by_file.items():
            entries.sort(key=lambda e: e.offset)
            path = self._path(file_id)
            try:
                with open(path, "rb") as fh:
                    for e in entries:
                        fh.seek(e.offset)
                        payload = fh.read(e.length)
                        if (len(payload) != e.length
                                or zlib.crc32(payload) != e.crc):
                            with self._lock:
                                self.corrupt_segments += 1
                            log.error(
                                "cold disk: CRC/length mismatch reading "
                                "seq=%d from %s offset=%d — segment "
                                "dropped", e.seq, path, e.offset)
                            continue
                        out.append(ColdSegment(
                            payload, e.units, e.live, e.raw_bytes,
                            e.mass_sum, e.mass_max, e.seq))
            except OSError as err:
                with self._lock:
                    self.io_errors += 1
                log.error("cold disk: read failed on %s: %s — %d "
                          "segments dropped", path, err, len(entries))
        # reads batch in file/offset order for IO locality; the caller
        # contract is still heaviest-first (mirror of ColdStore.recall)
        out.sort(key=lambda s: (-s.mass_sum, -s.seq))
        with self._lock:
            self.promoted += len(out)
        return out

    def displacement_floor(self) -> float:
        """Lightest indexed mass when at capacity, else 0.0 (mirror of
        ColdStore's door: below this, a spill would be dropped)."""
        with self._lock:
            if self.transitions < self.capacity or not self._entries:
                return 0.0
            return self._entries[0].mass_sum

    def stats(self) -> dict:
        with self._lock:
            return {
                "segments": len(self._entries),
                "transitions": self.transitions,
                "bytes": self.bytes_stored,
                "files": len(self._files),
                "spilled": self.spilled,
                "promoted": self.promoted,
                "dropped": self.dropped,
                "queue_full": self.queue_full,
                "io_errors": self.io_errors,
                "corrupt_segments": self.corrupt_segments,
                "compactions": self.compactions,
            }

    def drain(self, timeout: float = 10.0) -> None:
        """Block until queued segments are durably indexed
        (tests/shutdown only — never called from the ship path)."""
        deadline = time.monotonic() + timeout
        done = threading.Event()
        try:
            self._queue.put(done, timeout=timeout)
        except queue.Full as err:
            raise TimeoutError(
                "disk writeback queue did not accept the drain "
                "handshake") from err
        if not done.wait(max(0.0, deadline - time.monotonic())):
            raise TimeoutError("disk writeback thread did not drain")

    def close(self) -> None:
        self._stop.set()
        try:
            self._queue.put_nowait(None)   # wake the thread promptly
        except queue.Full:
            pass                           # 0.1s get-timeout wakes it
        self._thread.join(timeout=10.0)
        if self._active_fh is not None:
            try:
                self._active_fh.close()
            except OSError:  # apexlint: lossy(handle close at shutdown)
                pass
            self._active_fh = None

    # -- writeback thread --------------------------------------------------

    def _writeback_loop(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                continue                    # close() wake-up token
            if isinstance(item, threading.Event):
                item.set()                  # drain() handshake: every
                continue                    # earlier segment is indexed
            self._write_one(item)
            self._maybe_compact()

    def _write_one(self, seg: ColdSegment) -> None:
        # disk door (mirrors ColdStore.put): displace strictly lighter
        # indexed segments, else drop the candidate
        with self._lock:
            freed = 0
            victims = 0
            while (self.transitions + seg.live - freed > self.capacity
                   and victims < len(self._entries)
                   and self._keys[victims][0] < seg.mass_sum):
                freed += self._entries[victims].live
                victims += 1
            if self.transitions + seg.live - freed > self.capacity:
                self.dropped += 1
                return
            for e in self._entries[:victims]:
                self._remove_accounting(e)
            del self._entries[:victims], self._keys[:victims]
            disk_seq = self._seq
            self._seq += 1
        try:
            file_id, offset = self._append_record(seg, disk_seq)
        except OSError as err:
            with self._lock:
                self.io_errors += 1
            log.error("cold disk: writeback append failed (%s) — "
                      "segment seq=%d lost", err, disk_seq)
            return
        with self._lock:
            self._insert(_IndexEntry(
                seg.mass_sum, disk_seq, file_id, offset,
                len(seg.payload), seg.units, seg.live, seg.raw_bytes,
                seg.mass_max, zlib.crc32(seg.payload)))
            self.spilled += 1

    def _append_record(self, seg: ColdSegment,
                       disk_seq: int) -> tuple[int, int]:
        """Append one record to the active file -> (file_id, payload
        offset). Writeback thread only; raises OSError to the caller."""
        if (self._active_fh is None
                or self._active_size >= self.file_bytes):
            self._roll_file()
        header = _HEADER.pack(
            _MAGIC, seg.units, seg.live, seg.mass_sum, seg.mass_max,
            disk_seq, seg.raw_bytes, len(seg.payload),
            zlib.crc32(seg.payload))
        offset = self._active_size + HEADER_BYTES
        self._active_fh.write(header)
        self._active_fh.write(seg.payload)
        self._active_fh.flush()
        self._active_size += HEADER_BYTES + len(seg.payload)
        return self._active_id, offset

    def _roll_file(self) -> None:
        if self._active_fh is not None:
            self._active_fh.close()
            self._active_fh = None
        new_id = self._next_file_id
        self._next_file_id += 1
        self._active_fh = open(self._path(new_id), "wb")
        self._active_id = new_id
        self._active_size = 0
        with self._lock:
            self._files.setdefault(new_id, _FileInfo())

    # -- compaction --------------------------------------------------------

    def _maybe_compact(self) -> None:
        with self._lock:
            target = None
            for file_id, fi in self._files.items():
                if file_id == self._active_id:
                    continue
                total = fi.live_bytes + fi.dead_bytes
                if total > 0 and fi.dead_bytes / total > self.compact_frac:
                    target = file_id
                    break
                if total <= 0 and fi.records == 0:
                    target = file_id     # empty sealed file: just unlink
                    break
            if target is None:
                return
            moved = [e for e in self._entries if e.file_id == target]
        path = self._path(target)
        rewritten: list[tuple[_IndexEntry, bytes]] = []
        if moved:
            try:
                with open(path, "rb") as fh:
                    for e in sorted(moved, key=lambda e: e.offset):
                        fh.seek(e.offset)
                        payload = fh.read(e.length)
                        if (len(payload) != e.length
                                or zlib.crc32(payload) != e.crc):
                            with self._lock:
                                self.corrupt_segments += 1
                            log.error(
                                "cold disk: CRC mismatch compacting "
                                "seq=%d from %s — record dropped",
                                e.seq, path)
                            continue
                        rewritten.append((e, payload))
            except OSError as err:
                with self._lock:
                    self.io_errors += 1
                log.error("cold disk: compaction read failed on %s: %s "
                          "— file kept", path, err)
                return
        for e, payload in rewritten:
            seg = ColdSegment(payload, e.units, e.live, e.raw_bytes,
                              e.mass_sum, e.mass_max, e.seq)
            try:
                file_id, offset = self._append_record(seg, e.seq)
            except OSError as err:
                with self._lock:
                    self.io_errors += 1
                log.error("cold disk: compaction append failed (%s) — "
                          "aborting compaction of %s", err, path)
                return
            with self._lock:
                if not any(x is e for x in self._entries):
                    continue    # promoted mid-compaction: stale copy
                old_fi = self._files.get(e.file_id)
                if old_fi is not None:
                    old_fi.live_bytes -= e.record_bytes()
                    old_fi.dead_bytes += e.record_bytes()
                    old_fi.records -= 1
                e.file_id = file_id
                e.offset = offset
                fi = self._files[file_id]
                fi.live_bytes += e.record_bytes()
                fi.records += 1
                fi.mass_max = max(fi.mass_max, e.mass_sum)
        with self._lock:
            if any(e.file_id == target for e in self._entries):
                return              # some records still live there
            self._files.pop(target, None)
            self.compactions += 1
        try:
            os.unlink(path)
        except OSError as err:
            with self._lock:
                self.io_errors += 1
            log.error("cold disk: unlink failed on %s: %s", path, err)

    # -- recovery (runs before the writeback thread starts) ----------------

    def _recover(self) -> None:
        """Rebuild the index by scanning segment headers. Torn tails
        (short/garbled framing at EOF) are truncated; CRC mismatches
        with intact framing are skipped with an attributed error."""
        try:
            names = sorted(os.listdir(self.dir))
        except OSError as err:
            raise RuntimeError(
                f"cold disk: cannot list {self.dir}: {err}") from err
        for name in names:
            if not (name.startswith("segments-")
                    and name.endswith(".cold")):
                continue
            try:
                file_id = int(name[len("segments-"):-len(".cold")])
            except ValueError:
                log.error("cold disk: ignoring unparseable segment "
                          "file name %s", name)
                continue
            self._next_file_id = max(self._next_file_id, file_id + 1)
            self._scan_file(file_id)
        # appends resume in a FRESH file (forced roll on first write):
        # never extend a just-truncated tail through a new handle
        self._active_fh = None
        self._active_size = self.file_bytes

    def _scan_file(self, file_id: int) -> None:
        path = self._path(file_id)
        fi = _FileInfo()
        offset = 0
        valid_end = 0
        try:
            with open(path, "rb") as fh:
                size = os.fstat(fh.fileno()).st_size
                while offset + HEADER_BYTES <= size:
                    fh.seek(offset)
                    raw = fh.read(HEADER_BYTES)
                    if len(raw) < HEADER_BYTES:
                        break               # torn tail
                    (magic, units, live, mass_sum, mass_max, seq,
                     raw_bytes, plen, crc) = _HEADER.unpack(raw)
                    if magic != _MAGIC:
                        log.error(
                            "cold disk: bad magic at %s offset %d — "
                            "truncating torn tail", path, offset)
                        break
                    if offset + HEADER_BYTES + plen > size:
                        log.error(
                            "cold disk: short payload at %s offset %d "
                            "(%d bytes past EOF) — truncating torn "
                            "tail", path, offset,
                            offset + HEADER_BYTES + plen - size)
                        break               # torn tail
                    payload = fh.read(plen)
                    next_off = offset + HEADER_BYTES + plen
                    if zlib.crc32(payload) != crc:
                        # intact framing, rotten payload: skip the
                        # record, keep scanning, and count the bytes as
                        # dead weight so compaction reclaims them
                        self.corrupt_segments += 1
                        fi.dead_bytes += HEADER_BYTES + plen
                        log.error(
                            "cold disk: CRC mismatch at %s offset %d "
                            "(seq=%d) — record skipped", path, offset,
                            seq)
                        offset = next_off
                        valid_end = next_off
                        continue
                    self._insert_into(fi, _IndexEntry(
                        mass_sum, seq, file_id, offset + HEADER_BYTES,
                        plen, units, live, raw_bytes, mass_max, crc))
                    self._seq = max(self._seq, seq + 1)
                    offset = next_off
                    valid_end = next_off
            if valid_end < size:
                with open(path, "r+b") as fh:
                    fh.truncate(valid_end)
                log.warning("cold disk: truncated %s from %d to %d "
                            "bytes (torn tail)", path, size, valid_end)
        except OSError as err:
            self.io_errors += 1
            log.error("cold disk: recovery scan failed on %s: %s — "
                      "file ignored", path, err)
            return
        self._files[file_id] = fi

    # -- index helpers (caller holds _lock, or runs pre-thread) ------------

    def _insert(self, entry: _IndexEntry) -> None:
        fi = self._files.setdefault(entry.file_id, _FileInfo())
        self._insert_into(fi, entry)

    def _insert_into(self, fi: _FileInfo, entry: _IndexEntry) -> None:
        key = entry.key()
        at = bisect.bisect(self._keys, key)
        self._entries.insert(at, entry)
        self._keys.insert(at, key)
        self.transitions += entry.live
        self.bytes_stored += entry.record_bytes()
        fi.live_bytes += entry.record_bytes()
        fi.records += 1
        fi.mass_max = max(fi.mass_max, entry.mass_sum)

    def _remove_accounting(self, entry: _IndexEntry) -> None:
        self.transitions -= entry.live
        self.bytes_stored -= entry.record_bytes()
        fi = self._files.get(entry.file_id)
        if fi is not None:
            fi.live_bytes -= entry.record_bytes()
            fi.dead_bytes += entry.record_bytes()
            fi.records -= 1

    def _path(self, file_id: int) -> str:
        return os.path.join(self.dir, f"segments-{file_id:08d}.cold")
