"""R2D2 sequence replay: host-side sequence assembly + device storage.

Actors assemble fixed-length overlapping sequences with the recurrent
state stored from *before* the first step (SURVEY.md §2.2 "Sequence
replay", §3.4); the sequences are then items in the generic
device-resident PrioritizedReplay, so sampling/priority updates run
inside the learner jit exactly like flat transitions.

Defaults follow Kapturowski et al. 2019: length 80, overlap 40
(adjacent sequences share half their steps), burn-in 40 handled by the
loss, priority = eta*max|td| + (1-eta)*mean|td|.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ape_x_dqn_tpu.replay.packing import frame_mode


# THE predicate for single-frame sequence storage — an alias of the
# ONE shared implementation in replay/packing.py (frame_ring_mode in
# replay/frame_ring.py is the same object), so layout selection
# (runtime/family.py) and budget pricing (utils/hbm.py) cannot drift
# from each other or from the flat-DQN segment layout.
sequence_frame_mode = frame_mode


def sequence_item_spec(obs_shape: tuple[int, ...], obs_dtype,
                       seq_len: int, lstm_size: int,
                       frame_mode: bool = False) -> dict:
    """ShapeDtypeStruct-style pytree describing ONE stored sequence.

    frame_mode (pixel obs only): store single frames
    [seq_len + stack - 1, H, W] instead of per-step stacks
    [seq_len, H, W, stack] — consecutive steps share all but one frame,
    so stacked storage is ~stack x redundant (~4x at Atari shapes; the
    attested 100k-sequence capacity only fits in HBM without it).
    Stacks are rebuilt by `batch_to_sequence_batch` with `stack` cheap
    slices inside the learner jit.
    """
    import jax
    f32 = np.float32
    if frame_mode:
        h, w, stack = obs_shape
        obs_sds = jax.ShapeDtypeStruct((seq_len + stack - 1, h, w),
                                       obs_dtype)
        obs_key = "seq_frames"
    else:
        obs_sds = jax.ShapeDtypeStruct((seq_len, *obs_shape), obs_dtype)
        obs_key = "obs"
    return {
        obs_key: obs_sds,
        "actions": jax.ShapeDtypeStruct((seq_len,), np.int32),
        "rewards": jax.ShapeDtypeStruct((seq_len,), f32),
        "terminals": jax.ShapeDtypeStruct((seq_len,), f32),
        "mask": jax.ShapeDtypeStruct((seq_len,), f32),
        "init_c": jax.ShapeDtypeStruct((lstm_size,), f32),
        "init_h": jax.ShapeDtypeStruct((lstm_size,), f32),
    }


class SequenceBuilder:
    """Per-env accumulator emitting overlapping fixed-length sequences.

    Actors attach a per-step |TD| estimate (1-step, from the Q-values they
    already hold for action selection), and every emitted item carries an
    initial sequence priority under the extra key ``"priority"`` — the
    same eta-mix of max/mean |TD| the learner writes back (SURVEY.md §2.2
    "Actor runtime": initial priorities computed actor-side). Callers
    strip the key before device storage via `split_priorities`.
    """

    def __init__(self, seq_len: int = 80, overlap: int = 40,
                 lstm_size: int = 512, priority_eta: float = 0.9,
                 frame_mode: bool = False):
        """frame_mode: emit single frames ("seq_frames") instead of
        per-step stacks — valid for [H, W, stack] pixel obs whose
        channels slide one frame per step (the Atari wrapper's
        invariant; holds within an episode, and sequences never span
        episodes)."""
        assert 0 <= overlap < seq_len
        self.seq_len = seq_len
        self.overlap = overlap
        self.lstm_size = lstm_size
        self.priority_eta = priority_eta
        self.frame_mode = frame_mode
        self._steps: list[dict] = []  # each: obs/action/reward/terminal/pre_c/pre_h
        self._retained = 0  # leading steps already covered by a prior emit

    def append(self, obs, action, reward, terminal: bool,
               pre_state: tuple[np.ndarray, np.ndarray],
               td: float = 0.0,
               episode_end: bool | None = None) -> list[dict]:
        """Add one step; pre_state is the (c, h) fed to the net AT this step.

        `terminal` marks a bootstrapping-relevant episode end (stored in
        the terminals array); `episode_end` (default: terminal) flushes
        the sequence — a time-limit truncation ends the sequence without
        marking a terminal, since the recurrent state resets but the
        bootstrap must survive. Returns 0+ completed sequence items
        (dicts matching sequence_item_spec plus "priority").
        """
        if episode_end is None:
            episode_end = terminal
        c, h = pre_state
        self._steps.append(dict(
            obs=np.asarray(obs), action=int(action), reward=float(reward),
            terminal=bool(terminal), td=abs(float(td)),
            pre_c=np.asarray(c, np.float32).reshape(-1),
            pre_h=np.asarray(h, np.float32).reshape(-1)))
        out = []
        if len(self._steps) == self.seq_len:
            out.append(self._emit(self._steps))
            # retain the trailing overlap as the head of the next sequence
            self._steps = self._steps[self.seq_len - self.overlap:] \
                if self.overlap else []
            self._retained = len(self._steps)
        if episode_end:
            # flush the padded partial tail, but only if it contains steps
            # not already covered by the previous emit's overlap
            if len(self._steps) > self._retained:
                out.append(self._emit(self._steps))
            self._steps = []
            self._retained = 0
        return out

    def reset(self) -> None:
        self._steps = []
        self._retained = 0

    def flush(self) -> list[dict]:
        """Emit the padded partial tail (actor shutdown), if it holds any
        step not already covered by the previous emit's overlap."""
        out = []
        if len(self._steps) > self._retained:
            out.append(self._emit(self._steps))
        self._steps = []
        self._retained = 0
        return out

    def _emit(self, steps: list[dict]) -> dict:
        n = len(steps)
        assert n > 0
        length = self.seq_len
        first = steps[0]
        actions = np.zeros(length, np.int32)
        rewards = np.zeros(length, np.float32)
        terminals = np.zeros(length, np.float32)
        mask = np.zeros(length, np.float32)
        tds = np.zeros(n, np.float32)
        for i, s in enumerate(steps):
            actions[i] = s["action"]
            rewards[i] = s["reward"]
            terminals[i] = float(s["terminal"])
            mask[i] = 1.0
            tds[i] = s["td"]
        eta = self.priority_eta
        priority = eta * float(tds.max()) + (1 - eta) * float(tds.mean())
        item = {
            "actions": actions, "rewards": rewards,
            "terminals": terminals, "mask": mask,
            "init_c": first["pre_c"], "init_h": first["pre_h"],
            "priority": priority,
        }
        if self.frame_mode:
            # single frames: [0:stack] = the first step's channels, then
            # one new frame (newest channel) per step; obs stack at step
            # i is frames[i:i+stack] by the sliding invariant. Pad the
            # unmasked tail by repeating the last frame.
            h, w, stack = first["obs"].shape
            frames = np.zeros((length + stack - 1, h, w),
                              first["obs"].dtype)
            for c in range(stack):
                frames[c] = first["obs"][..., c]
            for i, s in enumerate(steps[1:], start=1):
                frames[stack - 1 + i] = s["obs"][..., -1]
            frames[stack - 1 + n:] = frames[stack - 2 + n]
            item["seq_frames"] = frames
        else:
            obs = np.zeros((length, *first["obs"].shape),
                           first["obs"].dtype)
            for i, s in enumerate(steps):
                obs[i] = s["obs"]
            item["obs"] = obs
        return item


def split_priorities(items: list[dict]) -> tuple[list[dict], np.ndarray]:
    """Strip the builder's "priority" key -> (storage items, priorities)."""
    pris = np.asarray([it.get("priority", 0.0) for it in items], np.float32)
    return [{k: v for k, v in it.items() if k != "priority"}
            for it in items], pris


def stack_items(items: list[dict]) -> dict:
    """Stack a list of sequence items into a batch pytree of [B, ...].

    Skips the builder's scalar "priority" side-channel key, which is not
    part of the stored item spec.
    """
    return {k: np.stack([it[k] for it in items])
            for k in items[0] if k != "priority"}


def batch_to_sequence_batch(items: Any):
    """Device item batch (dict of [B, L, ...]) -> losses.SequenceBatch.

    Frame-mode items carry "seq_frames" [B, L+stack-1, H, W]; the
    per-step [B, L, H, W, stack] obs rebuild is `stack` slices stacked
    on the channel axis — contiguous reads, no gather, fused into the
    learner jit."""
    import jax.numpy as jnp

    from ape_x_dqn_tpu.ops.losses import SequenceBatch
    if "seq_frames" in items:
        f = items["seq_frames"]
        length = items["actions"].shape[-1]
        stack = f.shape[1] - length + 1
        obs = jnp.stack([f[:, c:c + length] for c in range(stack)],
                        axis=-1)
    else:
        obs = items["obs"]
    return SequenceBatch(
        obs=obs, actions=items["actions"],
        rewards=items["rewards"], terminals=items["terminals"],
        mask=items["mask"],
        init_state=(items["init_c"], items["init_h"]))
