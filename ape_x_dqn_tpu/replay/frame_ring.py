"""Frame-ring prioritized replay: single frames in HBM, stacks on demand.

The flat transition layout (replay/prioritized.py) stores each n-step
transition as two full frame stacks (obs + next_obs = 2*stack frames,
~56KB at 84x84x4 uint8) — 8x redundant, since consecutive transitions
share all but one frame. That redundancy caps capacity (the attested
~2M-transition flagship replay would need ~59GB) and multiplies ingest
bytes across the wire, host->device DMA, and HBM writes (SURVEY.md §7
hard part 2 "ingest bandwidth"; §2.2 "Prioritized replay" capacity ~2M).

TPU-native fix: store each frame ONCE and reconstruct stacks with a
device-side gather at sample time (frames are uint8 in HBM; the gather
rides HBM bandwidth inside the learner jit, costing nothing extra — the
flat layout reads the same bytes, it just also *stores* them 8x).

Layout. Everything is built from fixed-size SEGMENTS so every shape is
static under jit:

- An actor cuts each episode's transition stream into segments of
  exactly B transitions (`seg_transitions`), padding the episode tail
  with dead slots (priority 0, next_off 0).
- Per episode it keeps a frame log P where P[0:stack] are the reset
  observation's channels and each env step appends one new frame; the
  step-t observation stack is then always the contiguous slice
  P[t:t+stack] (this also captures episodic-life pseudo-resets exactly,
  because the wrapper's stack carries over and so do the seeded
  channels). A transition starting at step t with span m (env steps
  between obs and bootstrap obs, ops/nstep.py) has
      obs      = P[t     : t+stack]
      next_obs = P[t+m   : t+m+stack],  1 <= m <= n_step.
- A segment covering start steps [t0, t0+B) therefore needs only the
  frames P[t0 : t0+F], F = B + n_step + stack - 1 — about (B+6)/(8B)
  of the flat layout's bytes (~6-7x less for B=16..64).

Device state reuses ReplayState: storage holds a frames ring
[S*F, pad128(H*W)] uint8 byte rows (S = capacity/B segments) plus
per-transition fields [capacity] (action/reward/discount/next_off);
`pos` counts SEGMENTS; the sum-tree indexes transitions. Segment k owns
transition slots [k*B, (k+1)*B) and frame rows [k*F, (k+1)*F): eviction
overwrites a whole segment at a time, so transition<->frame aliasing is
impossible by construction.

Frames are BYTE ROWS, not [H, W] planes, and adds are contiguous
dynamic_update_slice blocks with skip-to-head wrap — the two rules that
keep the ring resident in HBM at its logical size with zero-copy
add/sample graphs (see replay/packing.py for the measured OOM story a
plane layout + scatter produce at flagship capacity).

Dead padding slots carry tree priority 0 and are never sampled (the
descent clamp in ops/sum_tree.py keeps float rounding off them); their
share of capacity is <= B/(2*avg_episode_len), typically <1%. IS-weight
N counts all filled slots including dead ones — a <=1% overestimate of
N, well inside PER's tolerance (the beta anneal it feeds is itself a
heuristic).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.ops import sum_tree
from ape_x_dqn_tpu.replay.packing import (dus_rows, dus_rows_per_shard,
                                          frame_mode, pad128,
                                          ring_write_size)
from ape_x_dqn_tpu.replay.prioritized import (PrioritizedReplay,
                                              ReplayState, ring_cursor,
                                              ring_finish)


# THE predicate for frame-segment storage in the flat-DQN family — an
# alias of the ONE shared implementation in replay/packing.py
# (sequence_frame_mode in replay/sequence.py is the same object), so
# layout selection (runtime/family.py) and budget pricing
# (utils/hbm.py) cannot drift from each other or from the sequence
# layout. The uint8 dtype requirement is enforced with a ValueError at
# FrameRingReplay construction.
frame_ring_mode = frame_mode


def frame_segment_spec(seg_transitions: int, n_step: int,
                       obs_shape: tuple[int, ...], obs_dtype) -> dict:
    """Item pytree spec for ONE shipped segment (leading axis added by
    the ingest staging, like every other item spec)."""
    h, w, stack = obs_shape
    f = seg_transitions + n_step + stack - 1
    return {
        "seg_frames": jax.ShapeDtypeStruct((f, h, w), obs_dtype),
        "action": jax.ShapeDtypeStruct((seg_transitions,), jnp.int32),
        "reward": jax.ShapeDtypeStruct((seg_transitions,), jnp.float32),
        "discount": jax.ShapeDtypeStruct((seg_transitions,), jnp.float32),
        "next_off": jax.ShapeDtypeStruct((seg_transitions,), jnp.int32),
    }


class FrameSegmentBuilder:
    """Actor-side segment assembly (host numpy; one per actor env).

    Call order per actor loop (runtime/actor.py):
      on_reset(obs)            after every env.reset()
      on_step(next_obs)        after every env.step()
      add(action, reward, discount, span, priority)
                               for each emitted n-step transition, in
                               start-step order (the actor's outbox order
                               already guarantees this)
      take_ready() -> [segment dicts] ready to ship
      flush()                  at shutdown: pad + emit the partial tail

    on_reset flushes the open partial segment first, so segments never
    span episodes and the frame-slice invariant above always holds.
    """

    def __init__(self, seg_transitions: int, n_step: int, stack: int):
        self.B = seg_transitions
        self.n = n_step
        self.stack = stack
        self.F = self.B + self.n + self.stack - 1
        self._frames: list[np.ndarray] = []  # P[base:], trimmed left
        self._base = 0          # P-index of self._frames[0]
        self._t = 0             # next transition start step (per episode)
        self._t0: int | None = None  # open segment's first start step
        self._fields: list[tuple] = []
        self._ready: list[dict] = []

    def on_reset(self, obs: np.ndarray) -> None:
        self._flush_partial()
        # seed from ALL channels: a full reset gives the wrapper's
        # zero-padded stack, an episodic-life pseudo-reset gives the
        # carried-over frames — both reconstruct exactly
        self._frames = [np.ascontiguousarray(obs[..., c])
                        for c in range(self.stack)]
        self._base = 0
        self._t = 0
        self._t0 = None

    def on_step(self, next_obs: np.ndarray) -> None:
        self._frames.append(np.ascontiguousarray(next_obs[..., -1]))

    def add(self, action, reward: float, discount: float, span: int,
            priority: float) -> None:
        assert 1 <= span <= self.n, span
        if self._t0 is None:
            self._t0 = self._t
            drop = self._t0 - self._base  # frames left of P[t0]: done with
            if drop:
                del self._frames[:drop]
                self._base = self._t0
        self._t += 1
        self._fields.append((action, float(reward), float(discount),
                             int(span), float(priority)))
        if len(self._fields) == self.B:
            self._emit()

    def _emit(self) -> None:
        s = self._t0 - self._base
        frames = self._frames[s:s + self.F]
        while len(frames) < self.F:      # episode ended early: repeat tail
            frames.append(frames[-1])
        pad = self.B - len(self._fields)
        # dead slots: priority 0 AND next_off 0 (the replay masks the
        # tree priority on next_off>0, so eps^alpha never leaks in)
        fields = self._fields + [(0, 0.0, 0.0, 0, 0.0)] * pad
        acts, rews, discs, offs, pris = zip(*fields)
        self._ready.append({
            "seg_frames": np.stack(frames)[None],
            "action": np.asarray(acts, np.int32).reshape(1, self.B),
            "reward": np.asarray(rews, np.float32).reshape(1, self.B),
            "discount": np.asarray(discs, np.float32).reshape(1, self.B),
            "next_off": np.asarray(offs, np.int32).reshape(1, self.B),
            "priorities": np.asarray(pris, np.float32).reshape(1, self.B),
        })
        self._t0 = None
        self._fields = []

    def _flush_partial(self) -> None:
        if self._fields:
            self._emit()

    def flush(self) -> list[dict]:
        self._flush_partial()
        return self.take_ready()

    def take_ready(self) -> list[dict]:
        out, self._ready = self._ready, []
        return out


class FrameRingReplay(PrioritizedReplay):
    """Device-side prioritized replay over segment storage.

    Subclasses PrioritizedReplay: `sample` (IS weights incl. the
    valid_mask dead-slot zeroing) is inherited, while storage
    construction, segment `add`, the stack-gathering `sample_items`,
    and the dead-slot-preserving `update_priorities` are overridden —
    so DQNLearner and DistDQNLearner use either layout unchanged. `add`
    consumes staged segments {field: [G, ...]} with priorities [G, B]
    instead of flat items.
    """

    def __init__(self, capacity: int, seg_transitions: int, n_step: int,
                 obs_shape: tuple[int, ...], obs_dtype=np.uint8,
                 alpha: float = 0.6, beta: float = 0.4, eps: float = 1e-6):
        super().__init__(capacity=capacity, alpha=alpha, beta=beta, eps=eps)
        # ValueError, not assert: user-config validation must survive
        # `python -O` (same rule as the multihost driver's kind check)
        if capacity % seg_transitions != 0:
            raise ValueError("segment size must divide capacity")
        if len(obs_shape) != 3:
            raise ValueError(
                f"frame-ring replay needs [H, W, stack] pixel obs, "
                f"got {obs_shape}")
        self.B = seg_transitions
        self.n = n_step
        self.h, self.w, self.stack = obs_shape
        self.F = self.B + self.n + self.stack - 1
        self.S = capacity // self.B          # segment slots
        self.frame_bytes = self.h * self.w
        self.frame_row = pad128(self.frame_bytes)
        self.obs_dtype = obs_dtype
        if np.dtype(obs_dtype) != np.uint8:
            raise ValueError(
                f"frame-ring byte-row storage requires uint8 frames "
                f"(got {np.dtype(obs_dtype)}); use replay.storage='flat' "
                f"for non-uint8 pixel observations")

    # -- state construction ------------------------------------------------

    def init(self, item_spec: Any = None) -> ReplayState:
        """item_spec is accepted for interface parity and ignored — the
        storage layout is fixed by the constructor arguments."""
        storage = {
            "frames": jnp.zeros((self.S * self.F, self.frame_row),
                                self.obs_dtype),
            "action": jnp.zeros((self.capacity,), jnp.int32),
            "reward": jnp.zeros((self.capacity,), jnp.float32),
            "discount": jnp.zeros((self.capacity,), jnp.float32),
            "next_off": jnp.zeros((self.capacity,), jnp.int32),
        }
        return ReplayState(storage=storage, tree=sum_tree.init(self.capacity),
                           pos=jnp.int32(0), size=jnp.int32(0))

    # -- transitions (pure, jit-friendly) ----------------------------------

    def _write_segments(self, state: ReplayState, items: Any,
                        td_abs: jax.Array, lead: tuple[int, ...],
                        seg0: jax.Array | None = None) -> ReplayState:
        """Shared body of `add` (lead=()) and `add_lockstep`
        (lead=(dp,)): ONE contiguous dynamic_update_slice block of
        G*F frame rows / G*B transition slots per leading shard axis
        (in place on the donated state; a vmapped DUS would rebatch to
        a full-copy scatter — replay/packing.py), with skip-to-head
        wrap at the segment cursor. A caller-supplied seg0 (add_at;
        [dp]-vector seg0 under add_at_lockstep) directs the write at
        that segment instead."""
        nl = len(lead)
        g = td_abs.shape[nl]
        per_shard = False
        if seg0 is None:
            # cursor counts SEGMENTS, size counts transitions (size_scale)
            seg0, pos1, size1 = ring_cursor(state.pos, state.size, g,
                                            self.S, nl, size_scale=self.B)
        else:
            # directed write (add_at / add_at_lockstep). Dist form:
            # seg0 is a [dp] vector (each shard's own evict_plan) and
            # the cursor math is elementwise over shards.
            per_shard = nl > 0
            pos1 = (seg0 + g) % self.S
            size1 = ring_write_size(state.size, seg0 * self.B,
                                    g * self.B, self.capacity)
        if per_shard:
            tidx = (seg0[:, None] * self.B
                    + jnp.arange(g * self.B, dtype=jnp.int32)[None])
        else:
            tidx = seg0 * self.B + jnp.arange(g * self.B, dtype=jnp.int32)
        rows = items["seg_frames"].astype(self.obs_dtype) \
            .reshape(*lead, g * self.F, self.frame_bytes)
        if self.frame_row != self.frame_bytes:
            rows = jnp.pad(rows, [(0, 0)] * (nl + 1)
                           + [(0, self.frame_row - self.frame_bytes)])
        storage = dict(state.storage)
        if per_shard:
            storage["frames"] = dus_rows_per_shard(
                state.storage["frames"], rows, seg0 * self.F)
            for k in ("action", "reward", "discount", "next_off"):
                storage[k] = dus_rows_per_shard(
                    state.storage[k],
                    items[k].reshape(*lead, g * self.B), seg0 * self.B)
        else:
            storage["frames"] = dus_rows(state.storage["frames"], rows,
                                         seg0 * self.F, lead=nl)
            for k in ("action", "reward", "discount", "next_off"):
                storage[k] = dus_rows(state.storage[k],
                                      items[k].reshape(*lead, g * self.B),
                                      seg0 * self.B, lead=nl)
        valid = items["next_off"].reshape(*lead, g * self.B) > 0
        pri = jnp.where(
            valid,
            (td_abs.reshape(*lead, g * self.B) + self.eps) ** self.alpha,
            0.0)
        tree, pos, size = ring_finish(state.tree, tidx, pri, pos1, size1,
                                      lead)
        return ReplayState(storage=storage, tree=tree, pos=pos, size=size)

    def add(self, state: ReplayState, items: Any,
            td_abs: jax.Array) -> ReplayState:
        """Write G whole segments at the segment cursor.

        items: {"seg_frames": [G, F, H, W], "action"/"reward"/"discount"/
        "next_off": [G, B]}; td_abs: [G, B] initial |TD| (0 on dead pads).
        In-place block write with skip-to-head wrap (_write_segments).
        """
        return self._write_segments(state, items, td_abs, lead=())

    def add_lockstep(self, state: ReplayState, items: Any,
                     td_abs: jax.Array) -> ReplayState:
        """Segment add for [dp, ...]-stacked lockstep shard states —
        see PrioritizedReplay.add_lockstep for the lockstep-cursor
        contract. items: {"seg_frames": [dp, G, F, H, W], fields:
        [dp, G, B]}; td_abs: [dp, G, B]."""
        return self._write_segments(state, items, td_abs,
                                    lead=(td_abs.shape[0],))

    # -- tiered cold store hooks (segment units; see PrioritizedReplay) ----

    def evict_plan(self, state: ReplayState, block: int) -> jax.Array:
        """Start SEGMENT of the minimum-priority-mass run of `block`
        contiguous segments (eviction granularity is whole segments —
        the transition<->frame aliasing invariant demands it)."""
        seg_mass = state.tree[self.capacity:].reshape(self.S, self.B) \
            .sum(axis=-1)
        c = jnp.concatenate([jnp.zeros(1, seg_mass.dtype),
                             jnp.cumsum(seg_mass)])
        return jnp.argmin(c[block:] - c[:-block]).astype(jnp.int32)

    def read_region(self, state: ReplayState, seg0: jax.Array,
                    block: int) -> tuple[Any, jax.Array]:
        """-> (staging-layout segments {"seg_frames": [g, F, H, W],
        fields [g, B]}, stored leaf priorities [g, B]) for the `block`
        segments at seg0 — the exact shape _write_segments consumes, so
        a cold round trip restages bit-identically."""
        g = block
        st = state.storage
        rows = jax.lax.dynamic_slice_in_dim(st["frames"], seg0 * self.F,
                                            g * self.F)
        items = {"seg_frames": rows[:, :self.frame_bytes].reshape(
            g, self.F, self.h, self.w)}
        for k in ("action", "reward", "discount", "next_off"):
            items[k] = jax.lax.dynamic_slice_in_dim(
                st[k], seg0 * self.B, g * self.B).reshape(g, self.B)
        pri = jax.lax.dynamic_slice_in_dim(
            state.tree, self.capacity + seg0 * self.B,
            g * self.B).reshape(g, self.B)
        return items, pri

    def add_at(self, state: ReplayState, items: Any, td_abs: jax.Array,
               seg0: jax.Array) -> ReplayState:
        """Directed segment add: overwrite the G segments at seg0 (an
        evict_plan result) instead of the FIFO segment cursor."""
        return self._write_segments(state, items, td_abs, lead=(),
                                    seg0=seg0)

    def add_at_lockstep(self, state: ReplayState, items: Any,
                        td_abs: jax.Array,
                        seg0: jax.Array) -> ReplayState:
        """Directed segment add for [dp, ...]-stacked shard states:
        shard d gets items[d] at segment seg0[d] (its own evict_plan
        result). Per-shard unrolled DUS writes (dus_rows_per_shard);
        shard cursors diverge, which is safe because the eviction swap
        only runs on a full ring — the lockstep FIFO cursor is never
        consulted again (see PrioritizedReplay.add_at_lockstep)."""
        return self._write_segments(state, items, td_abs,
                                    lead=(td_abs.shape[0],), seg0=seg0)

    def _gather(self, state: ReplayState, idx: jax.Array) -> dict:
        """Reconstruct flat transitions {obs, action, reward, next_obs,
        discount} for transition indices idx [Bt] — a row gather of
        stack frames per side, then a batch-local reshape to [H, W]
        planes (the ring itself is never relaid out)."""
        st = state.storage
        seg, j = idx // self.B, idx % self.B
        base = seg * self.F + j
        offs = jnp.arange(self.stack, dtype=jnp.int32)[None, :]

        def stack_at(rows_base):
            f = st["frames"][rows_base[:, None] + offs]  # [Bt,stack,row]
            f = f[..., :self.frame_bytes].reshape(
                -1, self.stack, self.h, self.w)
            return jnp.moveaxis(f, 1, -1)                # -> [Bt,H,W,st]

        return {
            "obs": stack_at(base),
            "action": st["action"][idx],
            "reward": st["reward"][idx],
            # dead slots: next_off 0 — never sampled
            "next_obs": stack_at(base + st["next_off"][idx]),
            "discount": st["discount"][idx],
        }

    def sample_items(self, state: ReplayState, rng: jax.Array, batch: int
                     ) -> tuple[Any, jax.Array, jax.Array]:
        """-> (flat transition batch, leaf indices [B], probs [B])."""
        idx, probs = sum_tree.sample(state.tree, rng, batch,
                                     size=state.size)
        return self._gather(state, idx), idx, probs

    # sample() is inherited: PrioritizedReplay.sample composes
    # sample_items (overridden above) with IS weights and the
    # valid_mask dead-slot zeroing (overridden below).

    def update_priorities(self, state: ReplayState, idx: jax.Array,
                          td_abs: jax.Array) -> ReplayState:
        pri = (td_abs + self.eps) ** self.alpha
        # a dead slot must stay dead: a clamp-landed draw would otherwise
        # write (garbage-TD)^alpha here and resurrect it into the
        # sampling distribution permanently
        pri = jnp.where(state.storage["next_off"][idx] > 0, pri, 0.0)
        return state._replace(tree=sum_tree.update(state.tree, idx, pri))

    def valid_mask(self, state: ReplayState, idx: jax.Array) -> jax.Array:
        """[B] f32: 1 on live transitions, 0 on dead pad slots."""
        return (state.storage["next_off"][idx] > 0).astype(jnp.float32)

    def cursor_transitions(self, state: ReplayState) -> jax.Array:
        """Write cursor in transition units (the frame ring's `pos`
        counts segments) — the learning-health age statistic's clock."""
        return state.pos * self.B

    def live_transitions(self, state: ReplayState) -> jax.Array:
        """Count of live (non-pad) transition slots, reducing only the
        trailing slot axis — so it works unchanged on a single-chip
        state (scalar out) and on the dp-sharded lockstep state
        ([dp] out), where it feeds the per-shard fill stats of the
        multichip lane (bench.py --multichip) and
        `_DistLearnerBase.shard_stats`."""
        return (state.storage["next_off"] > 0).sum(axis=-1)
