"""ape_x_dqn_tpu — a TPU-native distributed deep-RL framework.

Brand-new implementation of the Ape-X DQN capability family (reference:
Jia-Mo/Ape-X-DQN; see SURVEY.md — the reference mount was empty at survey
time, so parity is built against the driver-attested contract in
SURVEY.md §2 / BASELINE.json rather than file:line citations):

- parallel actors feeding a prioritized (sum-tree) replay buffer,
- a learner running n-step double-DQN with dueling Nature-CNN heads as a
  single jit-compiled XLA graph,
- the sum-tree living in HBM with device-side stratified sampling,
- R2D2-style recurrent sequence replay with stored LSTM state,
- Ape-X DPG continuous control,
- learner collectives and weight broadcast over ICI via jax.sharding,
- batched TPU inference serving for actors.

Layout:
    configs   — dataclass run configurations (the five reference configs)
    utils/    — rng threading, metrics, checkpointing
    envs/     — native environments + Atari preprocessing stack
    models/   — flax Q-networks and actor-critic modules
    ops/      — losses, device sum-tree primitives, n-step returns
    replay/   — uniform / prioritized / sequence replay buffers
    parallel/ — mesh, shardings, collectives, batched inference server
    comm/     — transport abstraction (loopback queues, sockets for DCN)
    obs/      — span tracing, metric registry, stall watchdog, reporting
    runtime/  — actor / learner / replay-server / driver orchestration
    cpp/      — native C++ host components (sum-tree, ingest ring buffer)
"""

# keep in sync with pyproject.toml [project].version — log_run_header
# stamps this into every run's JSONL
__version__ = "0.2.0"
