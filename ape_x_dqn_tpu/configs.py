"""Run configuration system.

The reference ships five named configurations (SURVEY.md §2.1, attested by
BASELINE.json `configs`). Each is a preset here; every field can be
overridden from the CLI (``runtime/train.py``) or programmatically via
``dataclasses.replace``.

Hyperparameter defaults follow the published papers the reference
implements (Horgan et al. 2018 Ape-X; Kapturowski et al. 2019 R2D2;
Schaul et al. 2016 PER) as recorded in BASELINE.md.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any


@dataclass(frozen=True)
class EnvConfig:
    id: str = "CartPole-v1"
    kind: str = "cartpole"  # cartpole | cartpole_po | atari | control | synthetic_atari
    # Atari preprocessing (SURVEY.md §2.2 "Env wrappers")
    frame_skip: int = 4
    frame_stack: int = 4
    resize: int = 84
    max_noop_start: int = 30
    episodic_life: bool = True
    clip_rewards: bool = True
    max_episode_frames: int = 108_000  # 30 min @ 60Hz, standard ALE cap
    # Force ALE's 18-action legal set instead of the per-game minimal
    # set. Auto-enabled for id="atari57" fleets (one shared Q-net across
    # games with heterogeneous minimal sets), and set by per-game eval
    # workers evaluating such a net so action indices stay aligned.
    full_action_set: bool = False


@dataclass(frozen=True)
class NetworkConfig:
    kind: str = "mlp"  # mlp | nature_cnn | lstm_q | dpg
    mlp_hidden: tuple[int, ...] = (256, 256)
    cnn_channels: tuple[int, ...] = (32, 64, 64)
    cnn_kernels: tuple[int, ...] = (8, 4, 3)
    cnn_strides: tuple[int, ...] = (4, 2, 1)
    torso_dense: int = 512
    dueling: bool = True
    lstm_size: int = 512
    # DPG (continuous control)
    dpg_hidden: tuple[int, ...] = (300, 200)
    # Compute dtype for the forward/backward pass (params stay f32).
    compute_dtype: str = "bfloat16"


@dataclass(frozen=True)
class ReplayConfig:
    kind: str = "prioritized"  # uniform | prioritized | sequence
    capacity: int = 2_000_000
    alpha: float = 0.6
    beta: float = 0.4
    eps: float = 1e-6  # priority floor
    # Storage layout for pixel configs: "flat" stores stacked obs pairs
    # per transition; "frame_ring" stores single frames once and rebuilds
    # stacks with a device gather at sample time — ~6-7x less HBM and
    # ingest bandwidth (replay/frame_ring.py; SURVEY.md §7 hard part 2)
    storage: str = "flat"  # flat | frame_ring
    seg_transitions: int = 16  # transitions per shipped frame segment
    # segments per ingest add dispatch: bigger blocks = fewer add
    # dispatches contending with train_many for the device queue and
    # host->device link (the round-3 live soak measured 70 -> 125
    # grad-steps/s going 4 -> 16 under concurrent ingest; PERF.md
    # "Live soak"). Latency cost: a block buffers
    # dp * segs_per_add * seg_transitions transitions host-side.
    segs_per_add: int = 16
    # R2D2 sequence replay (SURVEY.md §3.4)
    seq_length: int = 80
    seq_overlap: int = 40
    burn_in: int = 40
    # priority = eta*max|td| + (1-eta)*mean|td| over the sequence
    priority_eta: float = 0.9
    min_fill: int = 50_000  # transitions before learning starts
    # -- zero-copy pipelined ingest staging (runtime/ingest.py) --
    # Wire batches decode DIRECTLY into preallocated fixed-shape staging
    # blocks at a write cursor (one copy per wire byte, contiguous by
    # construction — PERF.md round 5: contiguity is ~80 vs ~3,000
    # items/s of device_put), double-buffered so block N+1 decodes while
    # block N's async device_put is in flight.
    # ingest_coalesce: staged blocks fused into ONE donated add_many
    # dispatch — _state_lock is taken once per group instead of once per
    # block, so ingest adds stop interleaving with train_many. Latency
    # cost: a group buffers coalesce * block units host-side before
    # shipping (idle drains flush partial groups block-by-block).
    ingest_coalesce: int = 4
    # host staging buffers to rotate through (>= 2 for double buffering)
    stage_buffers: int = 2
    # compat escape hatch: False restores the list-append +
    # concatenate-per-flush legacy staging path in runtime/driver.py
    ingest_zero_copy: bool = True
    # -- tiered cold store (replay/cold_store.py), default OFF ----------
    # cold_tier_capacity > 0 enables the host-RAM cold tier behind the
    # device ring: when the ring is full, each ingest block overwrites
    # the ring's LOWEST-priority-mass region (instead of blind FIFO) and
    # the displaced region is delta+deflate-compressed into fixed-size
    # host segments carrying per-segment priority summaries. Capacity is
    # in TRANSITIONS; sizing rule of thumb: the cold tier holds ~10x
    # less bytes/transition than the ring (PERF.md "Tiered replay"), so
    # 8-64x the ring capacity costs host RAM comparable to the ring's
    # HBM. 0 keeps the default single-tier path bitwise untouched.
    cold_tier_capacity: int = 0
    # compressed cold segments decompressed + restaged (through the
    # SAME IngestStager -> add_many path as fresh actor data) per idle
    # refill tick, highest priority mass first; 0 disables recall while
    # still capturing evictions
    cold_tier_refill: int = 1
    # zlib level for cold segments (1 = speed, the wire codec's choice)
    cold_tier_compress_level: int = 1
    # -- disk-spill rung below the cold store (replay/disk_store.py),
    # default OFF ------------------------------------------------------
    # cold_tier_disk_capacity > 0 (transitions; requires the RAM tier)
    # adds an append-only segment-file rung under the cold store: RAM
    # door losers (displaced victims + live door-dropped candidates)
    # spill to disk via an async writeback thread instead of vanishing,
    # and the idle refill tick promotes the heaviest disk segments back
    # through the RAM door. Retention becomes a disk-provisioning knob
    # (10^8+ transitions at the cold tier's ~10x compression). 0 keeps
    # the RAM-only tier bitwise untouched.
    cold_tier_disk_capacity: int = 0
    # segment-file directory; REQUIRED non-empty when the disk rung is
    # on (an existing directory is recovered: index rebuilt from record
    # headers, torn tails truncated)
    cold_tier_disk_dir: str = ""
    # bounded writeback queue depth (segments). The ship path NEVER
    # waits on disk: a full queue counts cold_disk_queue_full and drops.
    cold_tier_disk_queue: int = 16
    # roll segment files at this size; compaction granularity
    cold_tier_disk_file_bytes: int = 64 * 1024 * 1024
    # compact a sealed file when its dead-byte fraction exceeds this
    cold_tier_disk_compact_frac: float = 0.5
    # disk segments promoted back toward the RAM store per idle refill
    # tick (after RAM recalls); 0 disables promotion while still
    # capturing spills
    cold_tier_disk_promote: int = 1

    def __post_init__(self) -> None:
        if self.cold_tier_capacity < 0:
            raise ValueError(
                f"replay.cold_tier_capacity must be >= 0 "
                f"(got {self.cold_tier_capacity}); 0 disables the tier")
        if self.cold_tier_capacity > 0:
            # guided error at CONFIG time, not mid-eviction: the cold
            # tier cannot run without the delta+deflate building blocks
            # (a stale/missing native .so is fine — comm/native.py
            # degrades to the bit-identical numpy fallback, and
            # ColdStore logs a one-liner saying so)
            from ape_x_dqn_tpu.replay.cold_store import codec_status
            ok, detail = codec_status()
            if not ok:
                raise ValueError(
                    f"replay.cold_tier_capacity={self.cold_tier_capacity} "
                    f"needs the delta+deflate codec, which failed to "
                    f"import: {detail}. Fix the install (ape_x_dqn_tpu."
                    f"comm.native must be importable — no compiler or "
                    f".so is required, the numpy fallback is "
                    f"bit-identical) or set replay.cold_tier_capacity=0 "
                    f"to run single-tier.")
        if self.cold_tier_disk_capacity < 0:
            raise ValueError(
                f"replay.cold_tier_disk_capacity must be >= 0 (got "
                f"{self.cold_tier_disk_capacity}); 0 disables the disk "
                f"rung")
        if self.cold_tier_disk_capacity > 0:
            if self.cold_tier_capacity <= 0:
                raise ValueError(
                    "replay.cold_tier_disk_capacity > 0 requires the "
                    "RAM cold tier (replay.cold_tier_capacity > 0): "
                    "the disk rung only sees segments through the RAM "
                    "store's admission door")
            if not self.cold_tier_disk_dir:
                raise ValueError(
                    "replay.cold_tier_disk_capacity > 0 requires "
                    "replay.cold_tier_disk_dir (the segment-file "
                    "directory; created if missing, recovered if it "
                    "holds prior segment files)")
            if self.cold_tier_disk_queue < 1:
                raise ValueError(
                    f"replay.cold_tier_disk_queue must be >= 1 (got "
                    f"{self.cold_tier_disk_queue}): the writeback "
                    f"queue needs at least one slot")
            if self.cold_tier_disk_file_bytes < 1024:
                raise ValueError(
                    f"replay.cold_tier_disk_file_bytes must be >= 1024 "
                    f"(got {self.cold_tier_disk_file_bytes}); one file "
                    f"must hold at least one record")
            if not (0.0 < self.cold_tier_disk_compact_frac <= 1.0):
                raise ValueError(
                    f"replay.cold_tier_disk_compact_frac must be in "
                    f"(0, 1] (got {self.cold_tier_disk_compact_frac})")
            if self.cold_tier_disk_promote < 0:
                raise ValueError(
                    f"replay.cold_tier_disk_promote must be >= 0 (got "
                    f"{self.cold_tier_disk_promote})")


@dataclass(frozen=True)
class LearnerConfig:
    batch_size: int = 512
    lr: float = 2.5e-4 / 4
    adam_eps: float = 1.5e-7
    gamma: float = 0.99
    n_step: int = 3
    target_sync_every: int = 2500
    max_grad_norm: float = 40.0
    huber_delta: float = 1.0
    double_dqn: bool = True
    value_rescale: bool = False  # R2D2 h(x) transform
    publish_every: int = 50  # learner→actor weight publish cadence (steps)
    # grad-steps fused into one train_many dispatch in the driver hot loop
    # (lax.scan on device; no host round-trips between steps)
    train_chunk: int = 8
    # K-batch sampling relaxation (SURVEY.md §3.3's sample<-update race,
    # resolved by relaxation): sample K*B items in ONE stratified tree
    # descent, run K grad-steps over the K chunks, write priorities back
    # ONCE. Within-chunk priority staleness (chunk j+1's sample does not
    # see chunk j's TD updates) matches the reference's async
    # replay-server semantics, where the host sampler always lags the
    # learner by an update round-trip. 1 = exact per-step semantics.
    # A/B'd on the real chip: PERF.md "K-batch sampling".
    sample_chunk: int = 1
    # Double-buffered replay sampling (PERF.md "Ideas not yet taken",
    # now "Prefetch A/B"): pipeline the learner cycle one dispatch deep
    # so the NEXT macro-step's tree descent + frame gather overlaps the
    # CURRENT macro-step's K grad-steps. The prefetched sample is drawn
    # against priorities that predate the in-flight write-back — a
    # one-dispatch staleness identical in kind to sample_chunk's
    # within-chunk staleness and to the reference's async host-side
    # replay server (its sampler always lags the learner by an update
    # round-trip). Default off until an on-chip A/B clears the ±3-5%
    # noise band (bench.py --prefetch-ab records both orders).
    sample_prefetch: bool = False
    # Pacing: cap grad-steps at this multiple of ingested transitions
    # (None = free-run, the Ape-X default where the learner trains as
    # fast as the device allows). Bounds replay overfit when actors are
    # slow relative to the learner, and on shared-core test hosts stops
    # the learner starving actor inference.
    steps_per_frame_cap: float | None = None
    # DPG
    critic_lr: float = 1e-3
    policy_lr: float = 1e-4
    tau: float = 0.005  # soft target update for DPG


@dataclass(frozen=True)
class ActorConfig:
    num_actors: int = 8
    # Envs per actor thread: >1 switches the dqn/dpg families to the
    # vectorized actor (runtime/vector_actor.py) — one thread steps K
    # envs and makes ONE batched inference query per vector step, so
    # RPC round-trips amortize K ways and the server sees batch-K work
    # (SURVEY.md §2.4 "inference batching parallelism", §7 hard part 3).
    # The eps schedule spans num_actors * envs_per_actor global slots.
    envs_per_actor: int = 1
    # eps_i = base_eps ** (1 + alpha * i / (N-1))  (Horgan et al. 2018)
    base_eps: float = 0.4
    eps_alpha: float = 7.0
    ingest_batch: int = 50  # transitions buffered before shipping
    param_pull_every: int = 400  # env steps between parameter pulls
    # Elastic recovery (SURVEY.md §5): a crashed actor is rebuilt (fresh
    # env + n-step state) and resumes its remaining frame budget, up to
    # this many times per actor slot; Ape-X tolerates actor loss, so a
    # restart costs only the crashed actor's in-flight transitions
    max_restarts: int = 2
    # Fleet supervisor (runtime/driver._supervise_tick): when obs
    # heartbeats flag a LOCAL actor thread as stalled past the
    # watchdog timeout, restart its slot in place (fresh env + actor,
    # remaining frame budget) instead of raising StallError for the
    # whole run. Each slot gets supervisor_max_restarts supervised
    # restarts; past the budget the slot is QUARANTINED — heartbeat
    # cleared, actor_quarantines counter + attributed JSONL event —
    # and the run continues degraded, never a crash loop. Stalls of
    # the learner/ingest/inference-server still raise (a driver
    # cannot restart its own learner), and remote-peer stalls are
    # counted + quarantined, not fatal (the peer's own host owns its
    # recovery).
    supervise: bool = True
    supervisor_max_restarts: int = 3
    # multihost: how long an actor-less listening learner waits for its
    # first remote actor-host connection before it may report idle
    # (raise for cluster queues / slow container pulls; too low and a
    # learner-only fleet self-terminates with 0 grad steps)
    remote_boot_grace_s: float = 300.0
    # continuous-control exploration noise stddev (DPG)
    noise_sigma: float = 0.2


@dataclass(frozen=True)
class InferenceConfig:
    max_batch: int = 64
    deadline_ms: float = 2.0  # dynamic batching deadline
    # shard query batches over the learner's (dp, tp) mesh (replicated
    # params, leading axis split) when running distributed; forwards/s
    # then scales with chip count
    shard_over_mesh: bool = True


@dataclass(frozen=True)
class ServingConfig:
    """Multi-tenant serving tier (parallel/inference_server.py,
    MultiPolicyInferenceServer). Off by default: drivers then build the
    single-tenant BatchedInferenceServer exactly as before. On, every
    policy registers into one continuous-batching tier — per-policy
    epoch-versioned params, priority-class admission, load-shedding,
    and per-tenant serve/<tenant>/ SLO gauges."""

    # route inference through the multi-tenant tier (drivers register
    # their policy under env.id; actor hosts tag wire hellos with it)
    multi_tenant: bool = False
    # admission classes; class 0 is the top class and is never shed
    priority_classes: int = 3
    # class that ordinary actor traffic rides in (eval workers and
    # other latency-sensitive callers should use a lower number)
    default_class: int = 1
    # pending-item depth where the admission controller starts
    # shedding lower classes and engages transport backpressure
    # (hysteresis: releases at half this depth)
    queue_slo_items: int = 256
    # per-request admission-queue deadline; an expired request raises
    # ServeDeadlineExceeded naming its policy_id. 0 disables.
    request_deadline_ms: float = 0.0
    # per-tenant serve/<tenant>/ gauge publish cadence
    stats_every_s: float = 1.0
    # coalesce same-family tenants into one stacked/gather-indexed
    # forward (off: mixed batches still work, one dispatch per batch
    # is only guaranteed per single-tenant batch)
    coalesce: bool = True
    # propagate the admission controller's backpressure signal onto
    # the experience transport (SocketTransport.set_backpressure)
    backpressure: bool = True

    def __post_init__(self) -> None:
        if self.priority_classes < 1:
            raise ValueError(
                f"serving.priority_classes must be >= 1 "
                f"(got {self.priority_classes})")
        if not 0 <= self.default_class < self.priority_classes:
            raise ValueError(
                f"serving.default_class must be in "
                f"[0, {self.priority_classes}) "
                f"(got {self.default_class})")
        if self.queue_slo_items < 1:
            raise ValueError(
                f"serving.queue_slo_items must be >= 1 "
                f"(got {self.queue_slo_items})")


@dataclass(frozen=True)
class ParallelConfig:
    dp: int = 1  # data-parallel (ICI) learner shards
    tp: int = 1  # tensor-parallel shards for dense layers


@dataclass(frozen=True)
class CommConfig:
    """Cross-host experience/param transport (comm/socket_transport).

    wire_codec: per-leaf experience compression on the ingest wire —
    "delta-deflate" (default) ships uint8 frame leaves as XOR-delta vs
    the previous row + zlib deflate, bit-packs bools, deflates small
    ints, leaves floats raw; "raw" is the escape hatch (and what either
    peer silently degrades to when the other side predates the codec —
    negotiation happens per connection, see MSG_HELLO in
    comm/socket_transport.py). The ingest wire is the #1 measured live
    bottleneck (PERF.md round-4: 10.5 MB/s, ~9.7KB/transition), so the
    default is on."""

    wire_codec: str = "delta-deflate"
    # Param-plane codec (comm/param_codec.py): "delta-q8" (default)
    # ships params as per-leaf int8-quantized deltas vs the version the
    # peer last received, with per-leaf and whole-payload never-inflate
    # guards and automatic full resync on missed versions / epoch
    # bumps; "raw" is the escape hatch keeping the TCP param path
    # bitwise identical to the pre-codec build. Negotiated per channel
    # (hello offer for pushes, a request field for pulls), so either
    # peer predating the codec degrades silently to raw. Only the
    # actor-side policy copy rides this — optimizer state never crosses
    # this wire (PARITY.md pins the quantized-policy tolerance).
    param_codec: str = "delta-q8"
    # How many encoded delta segments the learner keeps for catch-up:
    # a peer further behind than this many publishes gets a full resync
    # instead of a delta chain.
    param_delta_window: int = 8
    # Supervised reconnect (SocketTransport): capped jittered
    # exponential backoff between reconnect attempts after the
    # experience connection fails. The cap MUST stay below the
    # server's idle_grace_s (5.0) — a backing-off fleet retries inside
    # every quiesce grace window, so a learner blip never reads as
    # "all producers gone" (see SocketIngestServer.quiesced).
    reconnect_base_s: float = 0.05
    reconnect_cap_s: float = 2.0
    # Offer the server-initiated param publication capability in the
    # hello (MSG_PARAMS_PUSH): params arrive at publish boundaries
    # instead of on the poll cadence. Off by default — the poll path
    # is the universally-interoperable one; against a pre-push learner
    # the offer is silently ignored either way.
    params_push: bool = False
    # Same-host shared-memory transport (comm/shm_transport.py):
    # experience packs straight into a per-connection shm ring
    # (MSG_SHM_DOORBELL names slots on the existing TCP socket) and
    # params read from one seqlock area, engaging only when the hello's
    # boot-id + namespace probe proves same-host. Off by default: the
    # TCP paths are bitwise unchanged when disabled, and every shm
    # failure mode (old peer, cross-host, full ring, torn read)
    # degrades to them anyway. shm=True on BOTH learner (grant) and
    # actor host (offer) sides engages it.
    shm: bool = False
    # per-connection experience ring geometry: slot count and bytes
    # per slot (a batch outsizing a slot falls back to TCP, counted in
    # shm_fallbacks). The learner side caps what an actor may request.
    shm_slots: int = 8
    shm_slot_bytes: int = 1 << 22
    # seqlock param area capacity (learner side, one area shared by
    # every granted client); an oversize pickled param blob publishes
    # a marker instead and readers fall back to the TCP param path
    shm_param_bytes: int = 1 << 26


@dataclass(frozen=True)
class ObsConfig:
    """Observability layer (ape_x_dqn_tpu/obs): span tracing, metric
    registry, heartbeat stall watchdog. Disabled by default — the
    runtime then routes every obs call through the no-op NullObs, so
    the hot loops carry ~zero instrumentation overhead (the learner
    jits are never touched either way)."""

    enabled: bool = False
    # Chrome/Perfetto trace_event JSON output path ("" = no trace file;
    # spans still aggregate into the JSONL stage-time breakdown).
    # Load in chrome://tracing or https://ui.perfetto.dev.
    trace_path: str = ""
    # bounded span buffer: beyond this, events still count toward the
    # stage aggregates but drop from the trace file (memory cap)
    trace_max_events: int = 200_000
    # publish cadence for the registry -> JSONL snapshot (grad-steps);
    # drivers also publish once at shutdown
    publish_every_steps: int = 500
    # heartbeat watchdog: a component (actor-i / ingest / learner /
    # inference-server) silent this long makes the driver raise an
    # attributed StallError instead of hanging. Must exceed the longest
    # legitimate gap (a cold inference-server bucket compile can hold
    # actors for 10-40s on TPU; the 60s query timeout bounds it).
    # 0 disables the watchdog.
    heartbeat_timeout_s: float = 120.0
    # opt-in jax.profiler window (XLA-level twin of the span trace):
    # trace this many grad-steps into jax_profile_dir starting at the
    # first training dispatch ("" = off)
    jax_profile_dir: str = ""
    jax_profile_steps: int = 24
    # log each warmed jit's XLA memory_analysis() into the JSONL
    # (hbm/<jit>/<field> keys — the measured anchors utils/hbm.py's
    # static budget calibrates against)
    hbm_dump: bool = True
    # fleet telemetry cadence (obs/fleet.py): remote actor hosts ship
    # a MSG_TELEMETRY snapshot frame this often; the learner-side
    # aggregator merges them into the run JSONL under peer/<id>/ keys
    # and re-beats remote heartbeats into the stall watchdog. 0
    # disables the emitter thread (frames also require both wire ends
    # to negotiate the capability — an old peer degrades to none).
    telemetry_every_s: float = 2.0
    # -- continuous perf plane (obs/profiling.py, ISSUE 8) --------------
    # live roofline gauges (per-stage mfu / hbm_bw_frac / device_ms):
    # default ON with obs — they reuse the block_until_ready sync
    # points the span tracer already pays for, so they add no new
    # device synchronization and touch no jit
    profile_gauges: bool = True
    # EXTRA sampling windows on paths that are otherwise async (the
    # zero-copy ingest ship): default OFF — enabling inserts a
    # block_until_ready every profile_window_every-th ship, trading a
    # sliver of pipeline overlap for honest ingest device time
    profile_windows: bool = False
    profile_window_every: int = 16
    # jit-compile interceptor (jit_compiles / jit_compile_ms counters
    # + the cumulative compile_cache_entries gauge that monitors the
    # XLA accumulation regime run_chunked.sh works around)
    compile_telemetry: bool = True
    # EWMA perf-regression engine: a rate window below perf_frac of
    # its rolling baseline logs an attributed PerfDegradation event
    # (warn-only — never raises, unlike the stall watchdog)
    perf_regression: bool = True
    perf_frac: float = 0.5
    perf_ewma_alpha: float = 0.1
    perf_min_samples: int = 8
    perf_cooldown_s: float = 30.0
    # -- learning-health plane (obs/learning.py, ISSUE 10) --------------
    # warn-only anomaly engine over the in-graph learner diagnostics
    # (loss spikes vs an EWMA baseline + absolute rules for Q blowup,
    # ESS collapse, dead gradients, priority collapse — thresholds in
    # obs/learning.py, mirrored by obs/report.py healthy ranges). The
    # learn_* gauges themselves ride the learner's metrics pytree and
    # are published whenever obs is enabled; this knob only gates the
    # event engine.
    learn_health: bool = True
    learn_spike_mult: float = 10.0
    learn_ewma_alpha: float = 0.2
    learn_min_samples: int = 8
    learn_cooldown_s: float = 30.0
    # MFU / bandwidth-fraction denominators; 0 = auto from
    # jax.devices()[0].device_kind (obs/profiling.device_peaks)
    device_peak_flops: float = 0.0
    device_peak_bytes_per_s: float = 0.0
    # -- forensics plane (obs/blackbox.py, ISSUE 17) --------------------
    # per-process flight recorder: fixed-size ring of attributed
    # events, dumped to blackbox-<peer>.json on crash / StallError /
    # SIGUSR2 / supervisor request. blackbox_dir="" puts dumps next to
    # the run JSONL (cwd when metrics are in-memory).
    blackbox: bool = True
    blackbox_dir: str = ""
    blackbox_capacity: int = 512
    blackbox_log_lines: int = 64


@dataclass(frozen=True)
class RemediationConfig:
    """Fleet remediation plane (runtime/remediation.py): the policy
    engine that closes the monitor->actuator loop inside the driver's
    supervisor tick. Off by default — the engine is then never
    constructed and the supervisor path is bitwise the pre-remediation
    one. "observe" dry-runs every rule (attributed JSONL `remediation`
    events with outcome=observed, counters, gauges) without ever
    calling an actuator; "enforce" acts."""

    mode: str = "off"  # off | observe | enforce
    # consecutive supervisor ticks a gauge rule (queue-SLO breach,
    # ingest-drop pressure) must agree before its actuator moves, and
    # again before it moves back — a sensor flapping breach/clear every
    # tick never accumulates a streak, so actuators cannot oscillate
    hysteresis_ticks: int = 3
    # event rules (peer perf degradation, tenant learning degradation)
    # fire after this many attributed events on one target inside the
    # sliding window — one noisy sample is not a policy decision
    event_threshold: int = 2
    event_window_s: float = 120.0
    # per-(target, action) cooldown: the same remedy is not re-applied
    # to the same target faster than this
    cooldown_s: float = 60.0
    # global token-bucket budget for NON-safety actions (backpressure,
    # autoscale, priority re-temper) in actions/minute; safety actions
    # (restart of a wedged local slot, quarantine of a stalled peer)
    # bypass the bucket — suppressing them would leave a stale
    # heartbeat for the watchdog to escalate into a run-fatal
    # StallError, strictly worse than acting
    budget_per_min: float = 6.0
    # quiet period after which engaged remedies are unwound: a boosted
    # tenant priority reverts to serving.default_class, a paused actor
    # slot resumes, a client-side backpressure flag with a dead
    # controller is released
    release_after_s: float = 300.0
    # autoscale floor: the ingest-pressure rule never pauses the fleet
    # below this many running local actor slots
    min_actors: int = 1

    def __post_init__(self) -> None:
        if self.mode not in ("off", "observe", "enforce"):
            raise ValueError(
                f"remediation.mode must be off | observe | enforce "
                f"(got {self.mode!r})")
        if self.hysteresis_ticks < 1:
            raise ValueError(
                f"remediation.hysteresis_ticks must be >= 1 "
                f"(got {self.hysteresis_ticks})")
        if self.event_threshold < 1:
            raise ValueError(
                f"remediation.event_threshold must be >= 1 "
                f"(got {self.event_threshold})")
        if self.budget_per_min <= 0:
            raise ValueError(
                f"remediation.budget_per_min must be > 0 "
                f"(got {self.budget_per_min})")
        if self.min_actors < 0:
            raise ValueError(
                f"remediation.min_actors must be >= 0 "
                f"(got {self.min_actors})")


@dataclass(frozen=True)
class RunConfig:
    name: str = "cartpole_smoke"
    seed: int = 0
    total_env_frames: int = 200_000
    env: EnvConfig = field(default_factory=EnvConfig)
    network: NetworkConfig = field(default_factory=NetworkConfig)
    replay: ReplayConfig = field(default_factory=ReplayConfig)
    learner: LearnerConfig = field(default_factory=LearnerConfig)
    actors: ActorConfig = field(default_factory=ActorConfig)
    inference: InferenceConfig = field(default_factory=InferenceConfig)
    # multi-tenant serving tier (off = single-tenant server, bitwise
    # the pre-tier path); enable with --set serving.multi_tenant=true
    serving: ServingConfig = field(default_factory=ServingConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    comm: CommConfig = field(default_factory=CommConfig)
    # observability (ape_x_dqn_tpu/obs): off by default; enable with
    # --set obs.enabled=true [--set obs.trace_path=trace.json ...]
    obs: ObsConfig = field(default_factory=ObsConfig)
    # fleet remediation plane (runtime/remediation.py): off by default;
    # dry-run with --set remediation.mode=observe, close the loop with
    # --set remediation.mode=enforce
    remediation: RemediationConfig = field(
        default_factory=RemediationConfig)
    eval_every_steps: int = 10_000
    eval_episodes: int = 10
    eval_eps: float = 0.001
    # Per-episode frame cap for the periodic/final eval. The Atari
    # protocol's 108k (30 min of game time) is right for real ALE runs;
    # hosts where each eval env-step is expensive (e.g. queries
    # crossing a slow host<->device link) can bound it — an uncapped
    # episode left the rotation unable to finish a single eval while
    # training saturated the device (PERF.md "Live multi-game").
    eval_max_frames: int = 108_000
    # Wall-clock budget for the END-OF-RUN eval backstop (the greedy
    # eval the driver guarantees when a run finishes without a periodic
    # eval having completed). The old hard-coded 60s silently returned
    # no eval on hosts where each eval env-step crosses a slow
    # host<->device link (~30ms/step on this rig's tunnel: 5 episodes x
    # 2000 steps ~ 300s) — a fully-trained suite game then recorded
    # eval=null and was discarded (round-5 suite-learning run).
    final_eval_deadline_s: float = 600.0
    checkpoint_dir: str = ""
    checkpoint_every: int = 50_000
    # Opt-in, SINGLE-HOST driver only (the multihost driver rejects it:
    # its replicated payload gather would multiply the save by dp x
    # capacity): include the device ReplayState (storage + sum-tree +
    # cursors) in checkpoints. Resume then skips the min_fill refill
    # stall and keeps the replay distribution continuous across a
    # preemption (SURVEY.md §5 "and (optionally) replay contents").
    # The flag governs SAVES; restores follow what the checkpoint
    # contains, so toggling it between runs cannot brick resume.
    # Cost scales with capacity — the flagship's 2M-transition
    # frame-ring is ~20GB per save plus a transient on-device copy, so
    # the default stays off; at Pong-scale capacities it is cheap
    # (measured: see PERF.md "Replay-contents checkpointing").
    checkpoint_replay: bool = False
    # JAX profiler capture (SURVEY.md §5 tracing/profiling): when set,
    # the driver traces `profile_steps` learner grad-steps starting at
    # the first dispatch after min-fill into this directory
    # (TensorBoard/Perfetto-readable)
    profile_dir: str = ""
    profile_steps: int = 24
    # Multihost stall watchdog (runtime/multihost_driver.StallWatchdog):
    # seconds of zero round progress before a host-local diagnostic
    # fires naming this process; two consecutive silent windows abort
    # the process so the job restarts from the latest checkpoint
    # instead of hanging in a dead peer's collective. 0 disables.
    # Must exceed the slowest legitimate in-loop operation (first-round
    # XLA compiles when AOT warmup is unavailable, checkpoint gathers
    # over slow links).
    multihost_watchdog_s: float = 300.0

    def replace(self, **kw: Any) -> "RunConfig":
        return dataclasses.replace(self, **kw)


def _preset_cartpole_smoke() -> RunConfig:
    """Config 1: CartPole-v1 single-actor DQN, MLP, uniform replay (CPU smoke)."""
    return RunConfig(
        name="cartpole_smoke",
        total_env_frames=120_000,
        env=EnvConfig(id="CartPole-v1", kind="cartpole"),
        network=NetworkConfig(kind="mlp", mlp_hidden=(256, 256), dueling=False,
                              compute_dtype="float32"),
        replay=ReplayConfig(kind="uniform", capacity=50_000, min_fill=1_000),
        learner=LearnerConfig(batch_size=64, lr=1e-3, n_step=3,
                              target_sync_every=250),
        actors=ActorConfig(num_actors=1, base_eps=1.0),
    )


def _preset_pong() -> RunConfig:
    """Config 2: PongNoFrameskip-v4, Nature-CNN, 8 actors, prioritized replay."""
    return RunConfig(
        name="pong",
        total_env_frames=10_000_000,
        env=EnvConfig(id="PongNoFrameskip-v4", kind="atari"),
        network=NetworkConfig(kind="nature_cnn", dueling=True),
        # 1M transitions rounds to 2^20 in the drivers; as packed
        # frame-ring byte rows that is 9.63GiB + model/opt + ~2GiB
        # transient headroom = ~11.7GiB on one 16GiB chip (verified by
        # compiled memory stats AND a full-capacity bench run — PERF.md
        # "HBM budget"; the driver's check_hbm_fits re-prices it at
        # startup)
        replay=ReplayConfig(kind="prioritized", capacity=1_000_000,
                            min_fill=20_000, storage="frame_ring"),
        # steps_per_frame_cap pins the Ape-X effective replay ratio
        # (Horgan et al. 2018: ~19 grad-steps/s at batch 512 against
        # ~12.5k ingested transitions/s = ~0.78 samples/insert, i.e.
        # ~1.6e-3 grad-steps per ingested env step). Without it the
        # 490/s TPU learner free-runs hundreds of epochs over a slow
        # actor fleet's replay — the pathology PERF.md measured live.
        # sample_chunk=4: K-batch sampling relaxation, +4% on the real
        # chip with learning parity on the catch e2e (PERF.md "K-batch
        # sampling").
        learner=LearnerConfig(batch_size=512, steps_per_frame_cap=1.6e-3,
                              sample_chunk=4),
        actors=ActorConfig(num_actors=8, envs_per_actor=16),
    )


def _preset_atari57_apex() -> RunConfig:
    """Config 3: full Ape-X over the 57-game ALE suite, 256 actors."""
    return RunConfig(
        name="atari57_apex",
        total_env_frames=22_500_000_000,
        env=EnvConfig(id="atari57", kind="atari"),
        network=NetworkConfig(kind="nature_cnn", dueling=True),
        # frame-ring storage: the attested ~2M-transition capacity only
        # fits in HBM as single frames (~10KB/transition vs ~56KB flat)
        replay=ReplayConfig(kind="prioritized", capacity=2_000_000,
                            storage="frame_ring"),
        # replay-ratio pin + vector actors + K-batch sampling: see the
        # pong preset notes (the dist learner implements the same
        # sample_chunk relaxation per shard). 256 actor threads x 16
        # envs = 4096 env slots across the remote actor hosts; each
        # thread ships one 16-item inference query per vector step
        # (runtime/vector_actor.py)
        learner=LearnerConfig(batch_size=512, steps_per_frame_cap=1.6e-3,
                              sample_chunk=4),
        actors=ActorConfig(num_actors=256, envs_per_actor=16),
        parallel=ParallelConfig(dp=4, tp=2),
    )


def _preset_r2d2() -> RunConfig:
    """Config 4: recurrent LSTM Q-net with stored-state sequence replay."""
    return RunConfig(
        name="r2d2",
        total_env_frames=10_000_000_000,
        env=EnvConfig(id="atari57", kind="atari"),
        network=NetworkConfig(kind="lstm_q", dueling=True),
        # frame_ring: sequences store single frames (~0.56MB packed
        # byte-rows each at L=80) instead of per-step stacks (~2.2MB).
        # Capacity is HBM-budgeted (utils/hbm.py): 65536 sequences over
        # dp=4 shards = 16384/shard x 0.59MB = ~9.0GiB per 16GiB chip
        # (~2.6M transitions fleet-wide at overlap 40 — above the
        # attested ~2M-transition replay scale). R2D2-paper 100k+
        # sequences: raise dp to 8 (--set parallel.dp=8) or run
        # 32GiB-HBM chips; the driver's check_hbm_fits prints the
        # budget table if a layout doesn't fit.
        replay=ReplayConfig(kind="sequence", capacity=65_536,  # sequences
                            seq_length=80, seq_overlap=40, burn_in=40,
                            min_fill=5_000, storage="frame_ring"),
        # sample_chunk=4: the K-batch sampling relaxation, adopted for
        # sequences in round 5 — +25% grad-steps/s on the real chip
        # (52.5 -> 66 at these shapes, A/B'd both orders) with learning
        # parity on the masked-CartPole POMDP e2e (K=1 eval 43.2 vs
        # K=4 42.7, both >35 bar); PERF.md "K-batch for sequences"
        learner=LearnerConfig(batch_size=64, n_step=5, value_rescale=True,
                              target_sync_every=2500, lr=1e-4,
                              sample_chunk=4),
        # vectorized recurrent actors: one {obs,c,h} query of 16 envs
        # per vector step (runtime/vector_actor.py:RecurrentVectorActor)
        actors=ActorConfig(num_actors=256, envs_per_actor=16),
        parallel=ParallelConfig(dp=4, tp=2),
    )


def _preset_apex_dpg() -> RunConfig:
    """Config 5: Ape-X DPG continuous control (DM Control humanoid class)."""
    return RunConfig(
        name="apex_dpg",
        total_env_frames=100_000_000,
        env=EnvConfig(id="humanoid_stand", kind="control"),
        network=NetworkConfig(kind="dpg", compute_dtype="float32"),
        replay=ReplayConfig(kind="prioritized", capacity=1_000_000,
                            min_fill=10_000),
        learner=LearnerConfig(batch_size=256, n_step=5, gamma=0.99),
        actors=ActorConfig(num_actors=32),
    )


PRESETS = {
    "cartpole_smoke": _preset_cartpole_smoke,
    "pong": _preset_pong,
    "atari57_apex": _preset_atari57_apex,
    "r2d2": _preset_r2d2,
    "apex_dpg": _preset_apex_dpg,
}


def get_config(name: str, **overrides: Any) -> RunConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown config {name!r}; known: {sorted(PRESETS)}")
    cfg = PRESETS[name]()
    if overrides:
        cfg = cfg.replace(**overrides)
    return cfg
