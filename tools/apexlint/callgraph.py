"""Cross-module call graph for the whole-program checkers.

Stdlib-`ast` only, like everything else in apexlint: modules are
parsed, never imported. The graph indexes every scanned module's
top-level functions, classes (with methods), and import table, then
resolves three call shapes across module boundaries:

- `name(...)`        a module-level function, local or imported via
                     `from x import name [as alias]`
- `self.m(...)`      a method on the enclosing class, walking base
                     classes across modules (SequenceLearner inherits
                     SingleChipLearner from runtime/learner.py)
- `alias.fn(...)`    a function in another module bound by
                     `import x.y as alias` / `from x import y` where
                     y is itself a module

Module identity is the dotted path derived from the file path, and
imports resolve by dotted-suffix match so the graph works both on the
real package (`ape_x_dqn_tpu.runtime.learner`) and on flat fixture
directories (`from learner import X`). Unresolvable calls (third-party
modules, dynamic dispatch) resolve to None — checkers treat those as
opaque, exactly like the module-local v1 did.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from tools.apexlint.common import ModuleSource, dotted_name


@dataclass
class FuncInfo:
    """One function/method definition and where it lives."""
    node: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda
    module: "ModuleInfo"
    cls: "ClassInfo | None" = None

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")


@dataclass
class ClassInfo:
    node: ast.ClassDef
    module: "ModuleInfo"
    methods: dict[str, FuncInfo] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.node.name


class ModuleInfo:
    """One module's symbol tables: functions, classes, imports."""

    def __init__(self, src: ModuleSource):
        self.src = src
        self.path = src.path
        self.dotted = _dotted_from_path(src.path)
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        # local name -> (module dotted name, symbol-or-None); symbol
        # None means the local name is a module alias
        self.imports: dict[str, tuple[str, str | None]] = {}
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = FuncInfo(node, self)
            elif isinstance(node, ast.ClassDef):
                info = ClassInfo(node, self)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        info.methods[item.name] = FuncInfo(item, self,
                                                           info)
                self.classes[node.name] = info
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    # `import a.b` binds `a`; `import a.b as c` binds c
                    target = (alias.name if alias.asname
                              else alias.name.split(".")[0])
                    self.imports[local] = (target, None)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.imports[local] = (node.module, alias.name)


def _dotted_from_path(path: str) -> str:
    norm = os.path.normpath(path)
    if norm.endswith(".py"):
        norm = norm[:-3]
    parts = [p for p in norm.split(os.sep) if p not in ("", ".", "..")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class CallGraph:
    """Whole-program index over a list of ModuleSources."""

    def __init__(self, sources: list[ModuleSource]):
        self.modules: list[ModuleInfo] = [ModuleInfo(s) for s in sources]
        self._by_dotted: dict[str, ModuleInfo] = {}
        for mod in self.modules:
            self._by_dotted[mod.dotted] = mod

    # -- module / symbol resolution -----------------------------------

    def resolve_module(self, dotted: str) -> ModuleInfo | None:
        """Find a scanned module by dotted name, matching the longest
        dotted suffix (so `runtime.learner` and `learner` both hit
        `ape_x_dqn_tpu.runtime.learner` when unambiguous)."""
        if dotted in self._by_dotted:
            return self._by_dotted[dotted]
        tail = "." + dotted
        hits = [m for d, m in self._by_dotted.items() if d.endswith(tail)]
        return hits[0] if len(hits) == 1 else None

    def resolve_symbol(self, module: ModuleInfo, name: str, _depth: int = 0
                       ) -> FuncInfo | ClassInfo | ModuleInfo | None:
        """A name in `module`'s top-level namespace: local function or
        class, or an imported binding followed across modules."""
        if name in module.functions:
            return module.functions[name]
        if name in module.classes:
            return module.classes[name]
        if name in module.imports and _depth < 8:
            target_mod, symbol = module.imports[name]
            if symbol is None:
                return self.resolve_module(target_mod)
            # `from pkg import mod` where mod is a module, not a symbol
            target = self.resolve_module(target_mod)
            if target is None:
                return self.resolve_module(f"{target_mod}.{symbol}")
            resolved = self.resolve_symbol(target, symbol, _depth + 1)
            if resolved is None:
                return self.resolve_module(f"{target_mod}.{symbol}")
            return resolved
        return None

    # -- class hierarchy ----------------------------------------------

    def bases(self, cls: ClassInfo) -> list[ClassInfo]:
        out: list[ClassInfo] = []
        for base in cls.node.bases:
            resolved: FuncInfo | ClassInfo | ModuleInfo | None = None
            if isinstance(base, ast.Name):
                resolved = self.resolve_symbol(cls.module, base.id)
            elif isinstance(base, ast.Attribute):
                name = dotted_name(base)
                if name is not None:
                    head, _, attr = name.rpartition(".")
                    mod = self.resolve_symbol(cls.module, head) \
                        if "." not in head else self.resolve_module(head)
                    if isinstance(mod, ModuleInfo):
                        resolved = self.resolve_symbol(mod, attr)
            if isinstance(resolved, ClassInfo):
                out.append(resolved)
        return out

    def mro(self, cls: ClassInfo) -> list[ClassInfo]:
        """Linearized ancestry (DFS, left-to-right — close enough to C3
        for lint purposes; the package has no diamond method clashes)."""
        out: list[ClassInfo] = []
        seen: set[int] = set()

        def visit(c: ClassInfo) -> None:
            if id(c.node) in seen:
                return
            seen.add(id(c.node))
            out.append(c)
            for b in self.bases(c):
                visit(b)

        visit(cls)
        return out

    def lookup_method(self, cls: ClassInfo, name: str) -> FuncInfo | None:
        for c in self.mro(cls):
            if name in c.methods:
                return c.methods[name]
        return None

    def method_table(self, cls: ClassInfo) -> dict[str, FuncInfo]:
        """Full resolved method surface: own methods shadow inherited."""
        table: dict[str, FuncInfo] = {}
        for c in reversed(self.mro(cls)):
            table.update(c.methods)
        return table

    def is_base_of_any(self, cls: ClassInfo) -> bool:
        return any(cls.node is b.node
                   for m in self.modules for c in m.classes.values()
                   for b in self.bases(c))

    # -- call resolution -----------------------------------------------

    def resolve_call(self, call: ast.Call, module: ModuleInfo,
                     cls: ClassInfo | None) -> FuncInfo | None:
        func = call.func
        if isinstance(func, ast.Name):
            resolved = self.resolve_symbol(module, func.id)
            return resolved if isinstance(resolved, FuncInfo) else None
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name):
                if func.value.id == "self" and cls is not None:
                    return self.lookup_method(cls, func.attr)
                owner = self.resolve_symbol(module, func.value.id)
                if isinstance(owner, ModuleInfo):
                    fn = owner.functions.get(func.attr)
                    return fn
                if isinstance(owner, ClassInfo):
                    return self.lookup_method(owner, func.attr)
            else:
                # a.b.c(...): resolve the dotted receiver as a module
                recv = dotted_name(func.value)
                if recv is not None:
                    owner = self.resolve_module(recv)
                    if isinstance(owner, ModuleInfo):
                        return owner.functions.get(func.attr)
        return None
