"""CLI: `python -m tools.apexlint <package_dir> [options]`.

--format=text|json|sarif   sarif emits SARIF 2.1.0 for code-scanning
                           UIs (one rule per checker).
--changed-only <git-ref>   fast mode: the WHOLE-PROGRAM analysis still
                           runs (cross-module checkers need the full
                           graph), but findings are filtered to files
                           changed vs <git-ref> (plus untracked files)
                           and the exit code reflects only those. CI
                           keeps the full run; this is the pre-push
                           loop.
--self                     dogfood: lint tools/ itself with the
                           structural checkers (package-specific
                           tables — configs, README knobs, obs report
                           — auto-skip when absent). Coverage of
                           tools/chaos/ is asserted, not assumed: the
                           run aborts if the walk found no chaos
                           files, and the JSON summary carries
                           `self_scope` with the per-subtree file
                           counts.

The JSON/SARIF summaries carry per-checker wall-clock (`ms`) so CI can
spot a checker gone slow; SARIF rules expose findings/waivers/ms as
rule properties.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from tools.apexlint import run

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(summary: dict) -> dict:
    per = summary["per_checker"]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "apexlint",
                "informationUri": "tools/apexlint",
                "rules": [{"id": r,
                           "properties": {
                               "findings": per[r]["findings"],
                               "waivers": per[r]["waivers"],
                               "ms": per[r]["ms"],
                           }} for r in sorted(per)],
            }},
            "results": [{
                "ruleId": f["checker"],
                "level": "error",
                "message": {"text": f["message"]},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f["path"]},
                    "region": {"startLine": f["line"]},
                }}],
            } for f in summary["findings"]],
        }],
    }


def changed_files(ref: str) -> set[str]:
    """Files changed vs `ref` plus untracked files, repo-relative and
    normalized for comparison against finding paths."""
    out: set[str] = set()
    for args in (["git", "diff", "--name-only", ref, "--"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(args, capture_output=True, text=True,
                              timeout=60)
        if proc.returncode != 0:
            raise SystemExit(f"apexlint: {' '.join(args)} failed: "
                             f"{proc.stderr.strip()}")
        out.update(os.path.normpath(line)
                   for line in proc.stdout.splitlines() if line.strip())
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.apexlint",
        description="Ape-X project lint: guarded-by, jit-purity, "
                    "wire-protocol, obs-names, retry-annotation, "
                    "remediation-accounting, use-after-donate, "
                    "host-sync, config-coverage, learner-parity, "
                    "thread-lifecycle, resource-lifecycle, "
                    "counter-closure.")
    ap.add_argument("package", nargs="?", default=None,
                    help="package directory to scan (e.g. "
                         "ape_x_dqn_tpu/)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--changed-only", metavar="GIT_REF", default=None,
                    help="filter findings (and the exit code) to files "
                         "changed vs GIT_REF; the analysis itself stays "
                         "whole-program")
    ap.add_argument("--self", action="store_true", dest="self_lint",
                    help="lint tools/ itself (dogfood)")
    args = ap.parse_args(argv)
    if args.package is None:
        if not args.self_lint:
            ap.error("package directory required (or --self)")
        args.package = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    summary = run(args.package)
    if args.self_lint:
        # chaos coverage is asserted, not assumed: the thread/resource
        # checkers exist for exactly the kind of code tools/chaos holds
        from tools.apexlint import package_files
        chaos = os.path.normpath(os.path.join(args.package, "chaos"))
        n_chaos = sum(
            1 for p in package_files(args.package)
            if os.path.normpath(p).startswith(chaos + os.sep))
        if n_chaos == 0:
            raise SystemExit(
                "apexlint --self: tools/chaos/ contributed no files to "
                "the scan — the dogfood run no longer covers the fault "
                "injectors")
        summary["self_scope"] = {"tools/chaos": n_chaos}
    if args.changed_only is not None:
        changed = changed_files(args.changed_only)
        summary["findings"] = [
            f for f in summary["findings"]
            if os.path.normpath(f["path"]) in changed]
        summary["changed_only"] = {"ref": args.changed_only,
                                   "changed_files": len(changed)}
    if args.format == "json":
        print(json.dumps(summary))
    elif args.format == "sarif":
        print(json.dumps(to_sarif(summary), indent=2))
    else:
        for f in summary["findings"]:
            print(f"{f['path']}:{f['line']}: [{f['checker']}] "
                  f"{f['message']}")
        counts = ", ".join(
            f"{k}={v['findings']}/{v['waivers']}w" for k, v in
            sorted(summary["per_checker"].items()))
        scope = (f" [changed vs {args.changed_only}]"
                 if args.changed_only else "")
        print(f"apexlint: {len(summary['findings'])} finding(s), "
              f"{summary['waivers']} waiver(s) across "
              f"{summary['checked_files']} files{scope} ({counts})")
    return 1 if summary["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
