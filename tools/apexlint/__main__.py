"""CLI: `python -m tools.apexlint <package_dir> [--format=json]`."""

from __future__ import annotations

import argparse
import json
import sys

from tools.apexlint import run


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.apexlint",
        description="Ape-X project lint: guarded-by, jit-purity, "
                    "wire-protocol, obs-names.")
    ap.add_argument("package", help="package directory to scan "
                                    "(e.g. ape_x_dqn_tpu/)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    args = ap.parse_args(argv)
    summary = run(args.package)
    if args.format == "json":
        print(json.dumps(summary))
    else:
        for f in summary["findings"]:
            print(f"{f['path']}:{f['line']}: [{f['checker']}] "
                  f"{f['message']}")
        counts = ", ".join(f"{k}={v}" for k, v in
                           sorted(summary["per_checker"].items()))
        print(f"apexlint: {len(summary['findings'])} finding(s), "
              f"{summary['waivers']} waiver(s) across "
              f"{summary['checked_files']} files ({counts})")
    return 1 if summary["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
