"""Guarded-by checker: lock-discipline for annotated attributes.

An attribute whose assignment in `__init__` carries a trailing
`# guarded-by: <lock>` comment may only be written inside a
`with self.<lock>:` block (anywhere else in the class). Writes in
`__init__` itself are construction — no other thread can hold a
reference yet — and are exempt.

The check is lexical: a write inside a helper that is only ever
*called* with the lock held still flags, because nothing enforces that
calling convention. Either inline the write under the `with`, or waive
the line with `# apexlint: unguarded(<why it is safe>)`.

Nested functions (thread targets, closures) defined inside a `with`
block run later, after the lock is released, so the held-lock set is
reset to empty inside them.
"""

from __future__ import annotations

import ast
import re

from tools.apexlint.common import (
    CheckResult, Finding, ModuleSource, attr_on_self,
    self_attr_write_targets)

GUARDED_BY_RE = re.compile(r"guarded-by:\s*(\w+)")

CHECKER = "guarded-by"


def _declared_guards(cls: ast.ClassDef,
                     src: ModuleSource) -> dict[str, str]:
    """attr -> lock-attr from `# guarded-by:` comments in __init__."""
    guards: dict[str, str] = {}
    for stmt in cls.body:
        if (isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                and stmt.name == "__init__"):
            for node in ast.walk(stmt):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                m = GUARDED_BY_RE.search(src.comment(node.lineno))
                if not m:
                    continue
                for attr, _ in self_attr_write_targets(node):
                    guards[attr] = m.group(1)
    return guards


class _WriteScanner:
    """Walk one method body tracking the lexically-held lock set."""

    def __init__(self, src: ModuleSource, guards: dict[str, str],
                 result: CheckResult):
        self.src = src
        self.guards = guards
        self.result = result

    def scan(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        for stmt in fn.body:
            self._visit(stmt, frozenset())

    def _visit(self, node: ast.stmt, held: frozenset[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # closure/thread-target bodies execute later, after the
            # enclosing with-block has released its lock
            for stmt in node.body:
                self._visit(stmt, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set(held)
            for item in node.items:
                attr = attr_on_self(item.context_expr)
                if attr is not None:
                    acquired.add(attr)
            for stmt in node.body:
                self._visit(stmt, frozenset(acquired))
            return
        self._check_stmt(node, held)
        # statements only nest inside statement lists: body/orelse/
        # finalbody of compound statements, except-handler bodies, and
        # match-case bodies (lambdas hold expressions only)
        for _, value in ast.iter_fields(node):
            if isinstance(value, list):
                for child in value:
                    if isinstance(child, ast.stmt):
                        self._visit(child, held)
                    elif isinstance(child, (ast.ExceptHandler,
                                            ast.match_case)):
                        for stmt in child.body:
                            self._visit(stmt, held)

    def _check_stmt(self, node: ast.stmt, held: frozenset[str]) -> None:
        for attr, line in self_attr_write_targets(node):
            lock = self.guards.get(attr)
            if lock is None or lock in held:
                continue
            if self.src.waiver(line, "unguarded") is not None:
                self.result.waivers += 1
                continue
            self.result.findings.append(Finding(
                CHECKER, self.src.path, line,
                f"write to self.{attr} (guarded-by {lock}) outside "
                f"`with self.{lock}:`"))


def check_module(src: ModuleSource) -> CheckResult:
    result = CheckResult()
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guards = _declared_guards(node, src)
        if not guards:
            continue
        scanner = _WriteScanner(src, guards, result)
        for stmt in node.body:
            if (isinstance(stmt, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))
                    and stmt.name != "__init__"):
                scanner.scan(stmt)
    return result


def check_paths(paths: list[str]) -> CheckResult:
    result = CheckResult()
    for path in paths:
        result.merge(check_module(ModuleSource(path)))
    return result
