"""Hidden-host-sync checker: no silent device round-trips on the hot path.

The throughput story (PERF.md's roofline; Horgan 2018's whole claim)
dies by a thousand `.item()`s: every host materialization of a jit
output (`float(m["loss"])`, `np.asarray(device_array)`,
`jax.block_until_ready`, `jax.device_get`) blocks the dispatch queue
and serializes the learner against the device. The measured complement
is the PR 8 device-time plane; this checker is the static half — it
flags sync-shaped calls inside the hot-path modules unless they sit in
an observability window or carry a justification.

Scope — a module is hot when its basename is one of the learner/ingest
files (HOT_BASENAMES) or it carries an `# apexlint-scope: hot-path`
comment (how fixtures opt in). Inside `runtime/driver.py` only the
train-loop functions are hot (DRIVER_HOT_FUNCS): checkpointing,
staging-buffer numpy work, and teardown are host-side by design.

Flagged calls: `.item()`, `np.asarray`/`np.array` on a value,
`float(<name/attr/subscript>)` (a direct device-value fetch —
`float(np.mean(host_list))` stays quiet), `jax.block_until_ready`,
`jax.device_get`.

Allowed windows (lexical containment):
- `with obs.span(...)` / `with obs.stage_window(...)` bodies — the
  measured-sync points the perf plane rides;
- `if <...>.enabled:` / `if windowed:` bodies — obs-gated branches
  that only pay the sync when observability asked for it.

Sanitized values: after `x = jax.device_get(...)` / `x =
jax.block_until_ready(...)` / `x = np.asarray(...)` / `x = float(...)`
the name `x` is host-side, so later `float(x[...])`/`x.item()` reads
are free and stay quiet. A sanitizer inside an allowed window only
covers reads inside that same window (the un-observed branch never ran
it); an unwindowed sanitizer (itself flagged or waived — one explicit
sync covering the batch) sanitizes the rest of the function.

Waive with `# apexlint: host-sync(<why>)` on the call line, or on the
`def` line to waive a whole documented-off-hot-loop function (each
suppressed site still counts toward the waiver total, so creep stays
visible in `secondary.apexlint`).
"""

from __future__ import annotations

import ast
import os

from tools.apexlint.common import (
    CheckResult, Finding, ModuleSource, dotted_name)

CHECKER = "host-sync"

HOT_BASENAMES = {"learner.py", "dist_learner.py", "sequence_learner.py",
                 "dpg_learner.py", "ingest.py"}
DRIVER_HOT_FUNCS = {"_learner_loop", "_learner_loop_inner",
                    "_publish_params", "_ship_staged",
                    "_ship_staged_cold", "_add_block"}
SCOPE_MARK = "apexlint-scope: hot-path"

WINDOW_WITH_ATTRS = {"span", "stage_window"}
WINDOW_IF_NAMES = {"windowed"}
WINDOW_IF_ATTRS = {"enabled"}
SYNC_FULL = {"jax.block_until_ready", "jax.device_get"}


def _base_name(expr: ast.expr) -> str | None:
    """The root Name of a Name/Attribute/Subscript chain."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _sync_kind(call: ast.Call) -> tuple[str, ast.expr | None] | None:
    """(description, synced-value-expr) when `call` is sync-shaped."""
    func = call.func
    name = dotted_name(func)
    if name in SYNC_FULL:
        return (f"{name}() blocks on device completion",
                call.args[0] if call.args else None)
    if name is not None:
        head, _, attr = name.rpartition(".")
        if head in ("np", "numpy") and attr in ("asarray", "array"):
            return (f"{head}.{attr}() pulls a device value to host",
                    call.args[0] if call.args else None)
    if (isinstance(func, ast.Attribute) and func.attr == "item"
            and not call.args and not call.keywords):
        return (".item() blocks on a device->host transfer", func.value)
    if (isinstance(func, ast.Name) and func.id == "float"
            and len(call.args) == 1
            and isinstance(call.args[0],
                           (ast.Name, ast.Attribute, ast.Subscript))):
        return ("float() on a device value blocks on a device->host "
                "transfer", call.args[0])
    return None


def _is_window(node: ast.AST) -> bool:
    if isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
            call = item.context_expr
            if (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in WINDOW_WITH_ATTRS):
                return True
        return False
    if isinstance(node, ast.If):
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Name) and sub.id in WINDOW_IF_NAMES:
                return True
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in WINDOW_IF_ATTRS:
                return True
    return False


def _window_spans(fn: ast.AST) -> list[tuple[int, int]]:
    spans = []
    for node in ast.walk(fn):
        if _is_window(node):
            spans.append((node.lineno, node.end_lineno or node.lineno))
    return spans


def _in_window(line: int, spans: list[tuple[int, int]]) -> bool:
    return any(lo <= line <= hi for lo, hi in spans)


def _hot_functions(src: ModuleSource) -> list[ast.AST]:
    base = os.path.basename(src.path)
    marked = any(SCOPE_MARK in c for c in src.comments.values())
    driver = base == "driver.py"
    if not (marked or base in HOT_BASENAMES or driver):
        return []
    out: list[ast.AST] = []
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if driver and not marked \
                    and node.name not in DRIVER_HOT_FUNCS:
                continue
            out.append(node)
    if driver and not marked:
        return out
    # non-driver hot modules: every function is in scope; drop nested
    # duplicates (ast.walk yields inner defs too — the outer walk of
    # each function already covers them)
    roots, covered = [], set()
    for node in out:
        if id(node) in covered:
            continue
        roots.append(node)
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not node:
                covered.add(id(sub))
    return roots


def _def_waived(src: ModuleSource,
                fn: ast.AST) -> bool:
    line = getattr(fn, "lineno", 0)
    for dec in getattr(fn, "decorator_list", []):
        if src.waiver(dec.lineno, CHECKER) is not None:
            return True
    return src.waiver(line, CHECKER) is not None


def _sanitizers(fn: ast.AST) -> list[tuple[int, str, bool]]:
    """(line, name, unwindowed) for `x = <sync-call>(...)` rebinds."""
    spans = _window_spans(fn)
    out = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        kind = _sync_kind(node.value)
        if kind is None:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out.append((node.lineno, tgt.id,
                            not _in_window(node.lineno, spans)))
    return out


def check_module(src: ModuleSource) -> CheckResult:
    result = CheckResult()
    for fn in _hot_functions(src):
        fn_waived = _def_waived(src, fn)
        spans = _window_spans(fn)
        sanitizers = _sanitizers(fn)
        seen_lines: set[int] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            kind = _sync_kind(node)
            if kind is None or node.lineno in seen_lines:
                continue
            desc, value = kind
            line = node.lineno
            if _in_window(line, spans):
                continue
            root = _base_name(value) if value is not None else None
            # reads inside windows were skipped above, so only the
            # unwindowed (explicitly flagged-or-waived) sanitizers can
            # cover what remains
            if root is not None and any(
                    s_line < line and s_name == root and unwin
                    for s_line, s_name, unwin in sanitizers):
                continue
            seen_lines.add(line)
            if fn_waived or src.waiver(line, CHECKER) is not None:
                result.waivers += 1
                continue
            result.findings.append(Finding(
                CHECKER, src.path, line,
                f"{desc} on the hot path "
                f"({getattr(fn, 'name', '<fn>')}()) — move it inside an "
                f"obs window, batch it through one explicit waived "
                f"fetch, or keep the value on-device"))
    return result


def check_paths(paths: list[str]) -> CheckResult:
    result = CheckResult()
    for path in paths:
        result.merge(check_module(ModuleSource(path)))
    result.findings.sort(key=lambda f: (f.path, f.line))
    return result
