"""counter-closure: conservation laws between counters, enforced
statically at every increment site.

The runtime's accounting invariants (`evicted == stored + dropped`,
`dropped == sum(drop_reasons)`) today hold because tests assert them
after the fact; a new code path that bumps the left-hand side and
forgets the right-hand term leaks silently until a soak disagrees
with its ledger. Declaring the law at the counter-owning class makes
the leak a lint finding at the exact line:

    class Driver:
        # apexlint: closure(_cold_evicted == _cold_stored + _cold_dropped)

The check: every `self.<lhs> += ...` site in the class's methods must
be post-dominated — within its enclosing loop body if it sits in a
loop, else within its function — by EXACTLY ONE bump of a right-hand
term (`self.<term> += ...` or `self.<term>[...] += ...`; a dict or
per-shard-array term counts through its subscript). The analysis is a
small abstract interpreter over the statement suffix: if/else branches
union, loops contribute {0, 1, 2+} passes, try handlers enter from the
boundary before each body statement, and return/raise/break/continue
terminate a path. Any exit where the term count is not exactly 1 is a
finding.

A bump that is deliberately outside the law is waived at its line
with `# apexlint: closure(reason)` — an argument that does not parse
as an `lhs == a + b` equation is a waiver, one that does is a
declaration.

The same declarations feed a debug-mode runtime hook: `declarations()`
returns them machine-readable, and `check_object(obj, decl)` evaluates
the law on a live object (ints, per-shard numpy arrays, and
reason->count dict terms all compare), so bench lanes can assert
dynamically what CI proved statically.
"""

from __future__ import annotations

import ast
import re

from tools.apexlint.callgraph import CallGraph, ClassInfo, ModuleInfo
from tools.apexlint.common import CheckResult, Finding, ModuleSource

CHECKER = "counter-closure"
WAIVER = "closure"

_EQ_RE = re.compile(
    r"^\s*(?P<lhs>\w+)\s*==\s*(?P<rhs>\w+(?:\s*\+\s*\w+)*)\s*$")

_CAP = 2  # count lattice: 0, 1, 2 ("two or more")


def parse_declaration(arg: str) -> tuple[str, tuple[str, ...]] | None:
    m = _EQ_RE.match(arg)
    if not m:
        return None
    terms = tuple(t.strip() for t in m.group("rhs").split("+"))
    return m.group("lhs"), terms


def _aug_attr(stmt: ast.stmt) -> str | None:
    """Attr name for `self.X += ...` / `self.X[...] += ...`."""
    if not isinstance(stmt, ast.AugAssign) or not isinstance(
            stmt.op, ast.Add):
        return None
    t = stmt.target
    while isinstance(t, ast.Subscript):
        t = t.value
    if isinstance(t, ast.Attribute) and isinstance(
            t.value, ast.Name) and t.value.id == "self":
        return t.attr
    return None


def _sim_block(stmts: list[ast.stmt], state: set[int],
               exits: list[int], terms: tuple[str, ...]) -> set[int]:
    """Abstract-interpret a statement list: `state` is the set of
    possible term-bump counts on entry; paths that leave the region
    (return/raise/break/continue) deposit their count into `exits`;
    the returned set is the fall-through counts (empty if none)."""
    for stmt in stmts:
        if not state:
            return state
        attr = _aug_attr(stmt)
        if attr in terms:
            state = {min(c + 1, _CAP) for c in state}
            continue
        if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                             ast.Continue)):
            exits.extend(state)
            return set()
        if isinstance(stmt, ast.If):
            a = _sim_block(stmt.body, set(state), exits, terms)
            b = _sim_block(stmt.orelse, set(state), exits, terms)
            state = a | b
        elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            once = _sim_block(stmt.body, set(state), exits, terms)
            twice = _sim_block(stmt.body, set(once), exits, terms)
            state = state | once | twice
            if stmt.orelse:
                state = _sim_block(stmt.orelse, state, exits, terms)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            state = _sim_block(stmt.body, state, exits, terms)
        elif isinstance(stmt, ast.Try):
            # the handler can enter from the boundary BEFORE each body
            # statement (an exception interrupts the statement, not the
            # space after the last one) — so `try: op(); stored += 1
            # except: dropped += 1` counts exactly 1, not {1, 2}
            cur = set(state)
            entries: set[int] = set()
            for s in stmt.body:
                entries |= cur
                cur = _sim_block([s], cur, exits, terms)
                if not cur:
                    break
            body = cur
            after = set(body)
            for h in stmt.handlers:
                after |= _sim_block(h.body, set(entries), exits, terms)
            if stmt.orelse:
                after = (after - body) | _sim_block(
                    stmt.orelse, set(body), exits, terms)
            if stmt.finalbody:
                after = _sim_block(stmt.finalbody, after, exits, terms)
            state = after
        # plain statements (Expr/Assign/nested defs/...) don't bump
    return state


def _chain_to(func_node: ast.AST, bump: ast.stmt
              ) -> list[tuple[list[ast.stmt], int, ast.AST]] | None:
    """Path of (block, index, block_owner) from the function body down
    to the block directly holding `bump`."""
    def search(owner: ast.AST) -> list | None:
        for field in ("body", "orelse", "finalbody"):
            block = getattr(owner, field, None)
            if not isinstance(block, list):
                continue
            for i, s in enumerate(block):
                if s is bump:
                    return [(block, i, owner)]
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                sub = search(s)
                if sub is not None:
                    return [(block, i, owner)] + sub
        for h in getattr(owner, "handlers", []) or []:
            sub = search(h)
            if sub is not None:
                return sub
        return None
    return search(func_node)


def _bump_exit_counts(func_node: ast.AST, bump: ast.stmt,
                      terms: tuple[str, ...]) -> list[int] | None:
    """Possible term-bump counts at every exit of the bump's
    post-dominance region (enclosing loop body, else the function)."""
    chain = _chain_to(func_node, bump)
    if chain is None:
        return None
    # region root: the innermost enclosing loop's body
    start = 0
    for i, (_, _, owner) in enumerate(chain):
        if isinstance(owner, (ast.For, ast.AsyncFor, ast.While)):
            start = i
    exits: list[int] = []
    state: set[int] = {0}
    for block, idx, _ in reversed(chain[start:]):
        state = _sim_block(block[idx + 1:], state, exits, terms)
        if not state:
            break
    exits.extend(state)  # fall off the region root
    return exits


def _class_span(cls: ast.ClassDef) -> tuple[int, int]:
    return cls.lineno, getattr(cls, "end_lineno", cls.lineno)


def _owning_class(mod: ModuleInfo, line: int) -> ClassInfo | None:
    best: ClassInfo | None = None
    for cls in mod.classes.values():
        lo, hi = _class_span(cls.node)
        if lo <= line <= hi:
            if best is None or _class_span(best.node)[0] < lo:
                best = cls
    return best


def check_paths(paths: list[str]) -> CheckResult:
    res = CheckResult()
    sources = []
    for p in paths:
        try:
            sources.append(ModuleSource(p))
        except (SyntaxError, OSError):
            continue
    graph = CallGraph(sources)
    for mod in graph.modules:
        _check_module(graph, mod, res)
    return res


def _declarations_in(mod: ModuleInfo) -> list[dict]:
    out = []
    for line, arg in sorted(mod.src.waivers_of_kind(WAIVER).items()):
        parsed = parse_declaration(arg)
        if parsed is None:
            continue  # a waiver, consumed at its bump site
        lhs, terms = parsed
        cls = _owning_class(mod, line)
        out.append({"path": mod.path, "module": mod.dotted, "line": line,
                    "class": cls.name if cls else None,
                    "lhs": lhs, "terms": list(terms),
                    "expr": f"{lhs} == {' + '.join(terms)}",
                    "_cls": cls})
    return out


def declarations(paths: list[str]) -> list[dict]:
    """Machine-readable closure declarations (the runtime-hook feed):
    [{path, module, line, class, lhs, terms, expr}, ...]. Entries may
    be .py files or package directories (expanded like the CLI scan) —
    a directory silently yielding [] was too easy a footgun."""
    import os

    from tools.apexlint import package_files  # lazy: avoids import cycle

    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(package_files(p))
        else:
            files.append(p)
    out = []
    for p in files:
        try:
            mod = ModuleInfo(ModuleSource(p))
        except (SyntaxError, OSError):
            continue
        for d in _declarations_in(mod):
            d.pop("_cls")
            out.append(d)
    return out


def check_object(obj, decl: dict) -> None:
    """Debug-mode runtime assertion: evaluate a declaration on a live
    object. Terms/LHS may be ints, numpy arrays (compared
    elementwise), or reason->count mappings (summed). Raises
    AssertionError with both sides on violation."""
    def value(name):
        v = getattr(obj, name)
        if hasattr(v, "values") and callable(v.values):
            return sum(v.values())
        return v
    lhs = value(decl["lhs"])
    rhs = None
    for t in decl["terms"]:
        v = value(t)
        rhs = v if rhs is None else rhs + v
    ok = lhs == rhs
    if hasattr(ok, "all"):
        ok = bool(ok.all())
    if not ok:
        raise AssertionError(
            f"closure violated on {type(obj).__name__}: "
            f"{decl['expr']} (lhs={lhs!r}, rhs={rhs!r})")


def _check_module(graph: CallGraph, mod: ModuleInfo,
                  res: CheckResult) -> None:
    src = mod.src
    decls = _declarations_in(mod)
    for d in decls:
        cls = d.pop("_cls")
        if cls is None:
            res.findings.append(Finding(
                CHECKER, src.path, d["line"],
                f"closure declaration '{d['expr']}' sits outside any "
                "class body — it must live at the counter-owning "
                "class"))
            continue
        lhs, terms = d["lhs"], tuple(d["terms"])
        for meth in graph.method_table(cls).values():
            for stmt in ast.walk(meth.node):
                if not isinstance(stmt, ast.stmt) or \
                        _aug_attr(stmt) != lhs:
                    continue
                waiver = meth.module.src.waiver(stmt.lineno, WAIVER)
                if waiver is not None and \
                        parse_declaration(waiver) is None:
                    res.waivers += 1
                    continue
                counts = _bump_exit_counts(meth.node, stmt, terms)
                if counts is None:
                    continue
                bad = sorted(set(c for c in counts if c != 1))
                if bad:
                    shapes = ", ".join(
                        "a path leaks (0 term bumps)" if c == 0 else
                        "a path double-counts (2+ term bumps)"
                        for c in bad)
                    res.findings.append(Finding(
                        CHECKER, meth.module.src.path, stmt.lineno,
                        f"increment of self.{lhs} is not post-"
                        f"dominated by exactly one bump of "
                        f"{' / '.join(terms)}: {shapes} — breaks the "
                        f"declared closure '{d['expr']}'; waive with "
                        "# apexlint: closure(reason)"))
