"""Use-after-donate checker: no reads of a donated buffer after dispatch.

Every learner/replay jit donates its state (`donate_argnums=1` with
`static_argnums=0` on methods, `jax.jit(fn, donate_argnums=(0,))` on
module-level wrappers). After such a call the donated argument's device
buffers are DELETED — any later read (attribute access, re-pass to
another dispatch, host fetch) raises "Array has been deleted" on real
TPUs while often *appearing* to work on CPU test runs, which is exactly
the class of bug that only fires in production.

The checker collects every donating callable across the scanned
modules (decorated `@partial(jax.jit, ..., donate_argnums=...)`
functions/methods and `name = jax.jit(fn, donate_argnums=...)`
bindings), maps donated indices to call-site argument positions
(methods burn index 0 on `self`), then scans every function body for
calls to those names. Call sites are matched by callable name AND
call-site arity — `seen.add(x)` does not match the replay's
3-argument `add(state, batch, pris)` — a deliberately coarse match
that errs quiet on dynamic dispatch.

For each matched call the donated argument expression is rooted
(`state`, `self.state`, or `X._replace(...)` which donates X's
buffers), then the enclosing function is scanned line-forward:

- a REBIND of the root (including at the call statement itself —
  `self.state = self.learner.add(self.state, ...)`) makes the path
  safe and ends the scan;
- a READ of the root before any rebind is a finding at the read line.

Audited deliberate patterns (the driver's eviction swap reads the jit
*outputs*, never the donated input, so it is naturally clean; a true
read-after-donate that is provably safe on this backend) carry
`# apexlint: donated-ok(<why>)` on the read line or the call line.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from tools.apexlint.callgraph import CallGraph, ClassInfo, FuncInfo
from tools.apexlint.common import (
    CheckResult, Finding, ModuleSource, dotted_name)
from tools.apexlint.jit_purity import jit_decorator

CHECKER = "use-after-donate"


@dataclass
class Donor:
    """One donating callable: name, donated call-site positions, and
    the call-site arity window used to disambiguate name collisions."""
    name: str
    positions: tuple[int, ...]
    min_arity: int
    max_arity: int


def _int_tuple(node: ast.expr | None) -> tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _jit_kwargs(call: ast.Call) -> dict[str, ast.expr]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _fn_arity(fn: ast.FunctionDef | ast.AsyncFunctionDef,
              drop_self: bool) -> tuple[int, int]:
    args = fn.args
    pos = list(args.posonlyargs) + list(args.args)
    n = len(pos) - (1 if drop_self and pos
                    and pos[0].arg in ("self", "cls") else 0)
    return n - len(args.defaults), n


def collect_donors(graph: CallGraph) -> list[Donor]:
    donors: list[Donor] = []

    def from_decorated(fn: FuncInfo, is_method: bool) -> None:
        dec = jit_decorator(fn.node)
        if not isinstance(dec, ast.Call):
            return
        donated = _int_tuple(_jit_kwargs(dec).get("donate_argnums"))
        if not donated:
            return
        shift = 1 if is_method else 0
        positions = tuple(sorted(d - shift for d in donated
                                 if d - shift >= 0))
        lo, hi = _fn_arity(fn.node, drop_self=is_method)
        donors.append(Donor(fn.name, positions, lo, hi))

    for mod in graph.modules:
        for fn in mod.functions.values():
            from_decorated(fn, is_method=False)
        for cls in mod.classes.values():
            for fn in cls.methods.values():
                from_decorated(fn, is_method=True)
        # name = jax.jit(fn, donate_argnums=...) bindings
        for node in ast.walk(mod.src.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and dotted_name(node.value.func) in ("jax.jit", "jit")):
                continue
            donated = _int_tuple(
                _jit_kwargs(node.value).get("donate_argnums"))
            if not donated or not node.value.args:
                continue
            wrapped = node.value.args[0]
            lo, hi = 0, 64
            if isinstance(wrapped, ast.Name):
                target = mod.functions.get(wrapped.id)
                if target is not None:
                    lo, hi = _fn_arity(target.node, drop_self=False)
            for tgt in node.targets:
                name = tgt.id if isinstance(tgt, ast.Name) else (
                    tgt.attr if isinstance(tgt, ast.Attribute) else None)
                if name:
                    donors.append(Donor(name, tuple(sorted(donated)),
                                        lo, hi))
    return donors


# -- donated-expression rooting ---------------------------------------

def _root(expr: ast.expr) -> tuple[str, ...] | None:
    """('state',) for `state`, ('self', 'state') for `self.state`;
    `X._replace(...)` / `X.replace(...)` roots to X (a functional
    update still hands X's buffers to the donating dispatch)."""
    if (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute)
            and expr.func.attr in ("_replace", "replace")):
        return _root(expr.func.value)
    if isinstance(expr, ast.Name):
        return (expr.id,)
    if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return ("self", expr.attr)
    return None


def _matches_root(expr: ast.expr, root: tuple[str, ...]) -> bool:
    if len(root) == 1:
        return isinstance(expr, ast.Name) and expr.id == root[0]
    return (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self" and expr.attr == root[1])


def _assigned_roots(node: ast.AST) -> list[tuple[tuple[str, ...], int]]:
    """Roots rebound by an assignment-like node (tuple targets too)."""
    targets: list[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        targets = [node.target]
    elif isinstance(node, ast.withitem) and node.optional_vars:
        targets = [node.optional_vars]
    out: list[tuple[tuple[str, ...], int]] = []

    def visit(t: ast.expr) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                visit(e)
            return
        if isinstance(t, ast.Starred):
            visit(t.value)
            return
        r = _root(t)
        if r is not None:
            out.append((r, t.lineno))

    for t in targets:
        visit(t)
    return out


def _check_function(fn: FuncInfo, donors_by_name: dict[str, list[Donor]],
                    result: CheckResult) -> None:
    src = fn.module.src
    body = fn.node
    # all rebind and read events for the whole function, by line: the
    # scan is linear-by-line, which matches the straight-line dispatch
    # sequences this package writes (loops re-enter at the call line,
    # where the rebind-at-call rule already covers them)
    events: list[tuple[int, str, tuple[str, ...], ast.AST]] = []
    for node in ast.walk(body):
        for root, line in _assigned_roots(node):
            events.append((line, "store", root, node))
        if isinstance(node, (ast.Name, ast.Attribute)) \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            r = _root(node)
            if r is not None and not (isinstance(node, ast.Name)
                                      and r == ("self",)):
                events.append((node.lineno, "load", r, node))
    events.sort(key=lambda e: e[0])

    for node in ast.walk(body):
        if not isinstance(node, ast.Call):
            continue
        callee = None
        if isinstance(node.func, ast.Name):
            callee = node.func.id
        elif isinstance(node.func, ast.Attribute):
            callee = node.func.attr
        if callee not in donors_by_name:
            continue
        arity = len(node.args) + len(node.keywords)
        donor = next((d for d in donors_by_name[callee]
                      if d.min_arity <= arity <= d.max_arity), None)
        if donor is None:
            continue
        for pos in donor.positions:
            if pos >= len(node.args):
                continue  # passed by keyword / defaulted: out of scope
            root = _root(node.args[pos])
            if root is None:
                continue
            call_line = node.lineno
            # rebind at the call statement itself is the safe idiom
            rebound = any(e_line == call_line and kind == "store"
                          and e_root == root
                          for e_line, kind, e_root, _ in events)
            if rebound:
                continue
            flagged = False
            for e_line, kind, e_root, e_node in events:
                if e_line <= call_line or e_root != root:
                    continue
                if kind == "store":
                    break
                if src.waiver(e_line, "donated-ok") is not None \
                        or src.waiver(call_line, "donated-ok") is not None:
                    result.waivers += 1
                    flagged = True
                    break
                result.findings.append(Finding(
                    CHECKER, src.path, e_line,
                    f"reads {'.'.join(root)} after it was donated to "
                    f"{callee}() at line {call_line} — the buffers are "
                    f"deleted on dispatch; rebind the result or copy "
                    f"before donating"))
                flagged = True
                break
            if flagged:
                continue


def check_graph(graph: CallGraph) -> CheckResult:
    result = CheckResult()
    donors = collect_donors(graph)
    by_name: dict[str, list[Donor]] = {}
    for d in donors:
        by_name.setdefault(d.name, []).append(d)
    for mod in graph.modules:
        fns: list[FuncInfo] = list(mod.functions.values())
        for cls in mod.classes.values():
            fns.extend(cls.methods.values())
        for fn in fns:
            _check_function(fn, by_name, result)
    result.findings.sort(key=lambda f: (f.path, f.line))
    return result


def check_paths(paths: list[str]) -> CheckResult:
    return check_graph(CallGraph([ModuleSource(p) for p in paths]))
