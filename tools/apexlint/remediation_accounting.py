"""Remediation-accounting: every actuator call site must be counted.

The remediation plane's trust story (ISSUE 14) is that NO automated
fleet action is invisible: wherever code invokes one of the bounded
actuators — the callables behind `runtime/remediation.Actuators` plus
the serving tier's `force_backpressure` — the enclosing function must
also bump a `remediation_*` obs counter, or the call must carry an
explicit waiver naming where the accounting lives:

    self.serving.force_backpressure(on)  # apexlint: unaccounted(counted centrally in RemediationEngine._apply)

The counter does not have to be on the same line (an actuator that
raises is counted on the failure path), but it must be in the same
function scope — accounting a restart from a different module is how
actions go missing from the run JSONL when the call site is
refactored. Waivers are counted so accounting-by-reference creep
stays visible in the bench trajectory.

Scope: modules under `/runtime/` — the engine itself, the driver's
actuator wrappers, and the actor host's watchdogs.
"""

from __future__ import annotations

import ast

from tools.apexlint.common import CheckResult, Finding, ModuleSource

CHECKER = "remediation-accounting"

SCOPE_SEGMENTS = ("/runtime/",)

# the attribute names an actuator invocation goes through: the six
# Actuators fields plus the serving tier's direct latch override
ACTUATOR_NAMES = {
    "restart_actor", "quarantine_peer", "pause_actor", "resume_actor",
    "set_backpressure", "set_priority", "force_backpressure",
}


def _scopes(tree: ast.Module):
    """(scope node, nodes owned by that scope) for the module and
    every function — nested function bodies belong to the nested
    function, not the enclosing one (a callback defined inline does
    its own accounting)."""
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for scope in [tree, *funcs]:
        owned: list[ast.AST] = []
        stack = list(scope.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
                continue
            owned.append(node)
            stack.extend(ast.iter_child_nodes(node))
        yield scope, owned


def _counts_remediation(nodes: list[ast.AST]) -> bool:
    """True when the scope bumps a remediation_* counter: a call to a
    method named `count` whose first argument is a string literal
    starting with "remediation_"."""
    for node in nodes:
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "count" and node.args):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value.startswith("remediation_"):
            return True
    return False


def check_module(src: ModuleSource) -> CheckResult:
    result = CheckResult()
    norm = src.path.replace("\\", "/")
    if not any(seg in norm for seg in SCOPE_SEGMENTS):
        return result
    for _scope, owned in _scopes(src.tree):
        calls = [n for n in owned
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Attribute)
                 and n.func.attr in ACTUATOR_NAMES]
        if not calls:
            continue
        if _counts_remediation(owned):
            continue
        for call in calls:
            if src.waiver(call.lineno, "unaccounted") is not None:
                result.waivers += 1
                continue
            result.findings.append(Finding(
                CHECKER, src.path, call.lineno,
                f"{call.func.attr}() actuator call without a "
                f"remediation_* counter bump in the enclosing "
                f"function — count the action or waive with "
                f"`# apexlint: unaccounted(where it is counted)`"))
    return result


def check_paths(paths: list[str]) -> CheckResult:
    result = CheckResult()
    for path in paths:
        result.merge(check_module(ModuleSource(path)))
    return result
