"""Obs-name drift: emitted instruments <-> report table, both ways.

Every metric name emitted through the Obs facade or the registry —
`.observe("name", ...)`, `.observe_many`, `.gauge`, `.count`,
`.histogram("name", edges)`, `.counter` with a string-literal first
argument — must have a row in `obs/report.py`'s `INSTRUMENTS` table
(which also carries the healthy-range bounds the report warns on),
and every table row must correspond to a name the code can actually
emit. Drift in either direction is a finding:

- emitted-but-unlisted: the report silently drops the signal a PR
  just added (waive the emission line with
  `# apexlint: unlisted(<why>)` for deliberate scratch metrics);
- listed-but-unemitted: a dead row that documents an instrument no
  code path produces (waive the table row with
  `# apexlint: unemitted(<why>)`, e.g. emitted by an external tool).

A kind mismatch (emitted as a gauge, listed as a counter) is also a
finding: the report would look for it under the wrong `gauge/`-vs-
`ctr/` JSONL prefix and never print it.
"""

from __future__ import annotations

import ast
import re

from tools.apexlint.common import CheckResult, Finding, ModuleSource

CHECKER = "obs-names"

# method name -> instrument kind, as MetricRegistry.publish prefixes
# them in the JSONL stream (ctr/ gauge/ hist/)
EMIT_KINDS = {
    "observe": "hist",
    "observe_many": "hist",
    "histogram": "hist",
    "gauge": "gauge",
    "count": "ctr",
    "counter": "ctr",
}
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def collect_emissions(paths: list[str]) -> tuple[
        dict[str, tuple[str, str, int]], "CheckResult"]:
    """name -> (kind, path, line) across `paths`; waived emissions are
    counted but excluded from the cross-reference."""
    emissions: dict[str, tuple[str, str, int]] = {}
    result = CheckResult()
    for path in paths:
        src = ModuleSource(path)
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            kind = EMIT_KINDS.get(node.func.attr)
            if kind is None or not node.args:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            name = arg.value
            if not NAME_RE.match(name):
                continue  # e.g. str.count(",") on a plain string
            if src.waiver(node.lineno, "unlisted") is not None:
                result.waivers += 1
                continue
            prev = emissions.get(name)
            if prev is not None and prev[0] != kind:
                result.findings.append(Finding(
                    CHECKER, path, node.lineno,
                    f"instrument {name!r} emitted as {kind} here but "
                    f"as {prev[0]} at {prev[1]}:{prev[2]}"))
                continue
            emissions.setdefault(name, (kind, path, node.lineno))
    return emissions, result


def _table(report_src: ModuleSource) -> dict[str, tuple[str, int]]:
    """name -> (kind, line) from the INSTRUMENTS dict literal."""
    table: dict[str, tuple[str, int]] = {}
    for node in report_src.tree.body:
        if not (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "INSTRUMENTS"
                and isinstance(node.value, ast.Dict)):
            continue
        for key, value in zip(node.value.keys, node.value.values):
            if not (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)):
                continue
            kind = None
            if isinstance(value, ast.Dict):
                for k, v in zip(value.keys, value.values):
                    if (isinstance(k, ast.Constant)
                            and k.value == "kind"
                            and isinstance(v, ast.Constant)):
                        kind = v.value
            table[key.value] = (kind or "?", key.lineno)
    return table


def check(paths: list[str], report_path: str) -> CheckResult:
    emissions, result = collect_emissions(
        [p for p in paths if not p.endswith("obs/report.py")])
    report_src = ModuleSource(report_path)
    table = _table(report_src)
    for name, (kind, path, line) in sorted(emissions.items()):
        row = table.get(name)
        if row is None:
            result.findings.append(Finding(
                CHECKER, path, line,
                f"emitted instrument {name!r} ({kind}) has no row in "
                f"{report_path}'s INSTRUMENTS table"))
        elif row[0] != kind:
            result.findings.append(Finding(
                CHECKER, report_path, row[1],
                f"instrument {name!r} listed as {row[0]} but emitted "
                f"as {kind} at {path}:{line}"))
    for name, (kind, line) in sorted(table.items()):
        if name in emissions:
            continue
        if report_src.waiver(line, "unemitted") is not None:
            result.waivers += 1
            continue
        result.findings.append(Finding(
            CHECKER, report_path, line,
            f"INSTRUMENTS row {name!r} ({kind}) is emitted nowhere "
            f"in the scanned package"))
    return result
