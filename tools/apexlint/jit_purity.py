"""Jit-purity checker: no host effects reachable from a jit boundary.

A function is a *jit root* when it is decorated `@jax.jit` /
`@partial(jax.jit, ...)`, or passed to `jax.jit(fn)` as a module-local
function, same-class method (`jax.jit(self._step)`), or inline lambda.
From each root the checker walks the module-local call graph (calls to
module-level functions and to `self.<method>` within the same class)
and flags host-effect calls anywhere in the reachable bodies:

- wall-clock reads / sleeps (`time.time`, `time.monotonic`, ...)
- `print(...)` (use `jax.debug.print` inside traced code)
- `.item()` — a blocking device->host transfer that also leaks tracers
- `np.asarray` / `np.array` / `np.frombuffer` on traced values
- metric/trace emission (`obs.observe/gauge/count/...`,
  `metrics.log/write`, `trace.span`) — host I/O that silently turns
  into a tracer leak or a retrace

Inside jit these either fail loudly (tracer leak), or worse, succeed
once at trace time and then never run again — a metric that reports
the compile-time value forever. Waive a deliberate trace-time effect
with `# apexlint: host-effect(<why>)`.

The call graph is module-local by design: cross-module helpers called
from jit are checked when their own module is scanned (every module
with a jit callsite is in the scan set).
"""

from __future__ import annotations

import ast

from tools.apexlint.common import (
    CheckResult, Finding, ModuleSource, dotted_name)

CHECKER = "jit-purity"

TIME_EFFECTS = {"time", "monotonic", "perf_counter", "perf_counter_ns",
                "time_ns", "sleep"}
NUMPY_EFFECTS = {"asarray", "array", "frombuffer", "copyto", "save"}
# emission methods flagged only on obs/metrics/registry-ish receivers,
# so `q.count(x)` on a plain container does not false-positive
EMIT_METHODS = {"observe", "observe_many", "gauge", "count",
                "counter", "histogram", "span", "publish", "log",
                "write", "beat"}
EMIT_RECEIVERS = {"obs", "obs_", "_obs", "metrics", "_metrics",
                  "registry", "_reg", "_registry", "tracer", "_tracer",
                  "trace", "heartbeat", "_heartbeats"}


def _is_jax_jit(node: ast.expr) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def _jit_decorated(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for dec in fn.decorator_list:
        if _is_jax_jit(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func):
                return True
            # @partial(jax.jit, ...)
            if (dotted_name(dec.func) in ("partial", "functools.partial")
                    and dec.args and _is_jax_jit(dec.args[0])):
                return True
    return False


class _ModuleIndex:
    """Module-level functions and per-class methods, by name."""

    def __init__(self, tree: ast.Module):
        self.functions: dict[str, ast.FunctionDef] = {}
        self.methods: dict[str, dict[str, ast.FunctionDef]] = {}
        self.owner: dict[int, str | None] = {}  # id(fn-node) -> class
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
                self.owner[id(node)] = None
            elif isinstance(node, ast.ClassDef):
                table: dict[str, ast.FunctionDef] = {}
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        table[item.name] = item
                        self.owner[id(item)] = node.name
                self.methods[node.name] = table

    def resolve(self, call: ast.Call,
                cls: str | None) -> ast.FunctionDef | None:
        func = call.func
        if isinstance(func, ast.Name):
            return self.functions.get(func.id)
        if (cls is not None and isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"):
            return self.methods.get(cls, {}).get(func.attr)
        return None


def _jit_roots(index: _ModuleIndex,
               tree: ast.Module) -> list[tuple[ast.AST, str | None]]:
    """(function-or-lambda node, owning-class) for every jit boundary."""
    roots: list[tuple[ast.AST, str | None]] = []
    for name, fn in index.functions.items():
        if _jit_decorated(fn):
            roots.append((fn, None))
    for cls, table in index.methods.items():
        for name, fn in table.items():
            if _jit_decorated(fn):
                roots.append((fn, cls))

    # jax.jit(<arg>) callsites anywhere in the module
    def walk(node: ast.AST, cls: str | None) -> None:
        if isinstance(node, ast.ClassDef):
            for child in ast.iter_child_nodes(node):
                walk(child, node.name)
            return
        if isinstance(node, ast.Call) and _is_jax_jit(node.func):
            if node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name):
                    target = index.functions.get(arg.id)
                    if target is not None:
                        roots.append((target, None))
                elif isinstance(arg, ast.Lambda):
                    roots.append((arg, cls))
                elif (isinstance(arg, ast.Attribute)
                      and isinstance(arg.value, ast.Name)
                      and arg.value.id == "self" and cls is not None):
                    target = index.methods.get(cls, {}).get(arg.attr)
                    if target is not None:
                        roots.append((target, cls))
        for child in ast.iter_child_nodes(node):
            walk(child, cls)

    walk(tree, None)
    return roots


def _reachable(index: _ModuleIndex,
               roots: list[tuple[ast.AST, str | None]]
               ) -> list[tuple[ast.AST, str | None]]:
    seen: set[int] = set()
    out: list[tuple[ast.AST, str | None]] = []
    work = list(roots)
    while work:
        fn, cls = work.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        out.append((fn, cls))
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                target = index.resolve(node, cls)
                if target is not None and id(target) not in seen:
                    work.append((target, index.owner.get(id(target))))
    return out


def _host_effect(call: ast.Call) -> str | None:
    """Describe the host effect of a call, or None when pure."""
    func = call.func
    if isinstance(func, ast.Name) and func.id == "print":
        return "print() (use jax.debug.print in traced code)"
    if not isinstance(func, ast.Attribute):
        return None
    name = dotted_name(func)
    if name is not None:
        head, _, attr = name.rpartition(".")
        if head == "time" and attr in TIME_EFFECTS:
            return f"time.{attr}() reads the host clock"
        if head in ("np", "numpy") and attr in NUMPY_EFFECTS:
            return (f"{head}.{attr}() forces a host round-trip on a "
                    f"traced value")
    if func.attr == "item" and not call.args and not call.keywords:
        return ".item() blocks on a device->host transfer"
    if func.attr in EMIT_METHODS:
        recv = func.value
        last = None
        if isinstance(recv, ast.Name):
            last = recv.id
        elif isinstance(recv, ast.Attribute):
            last = recv.attr
        if last in EMIT_RECEIVERS:
            return (f"metric/trace emission .{func.attr}() is host I/O "
                    f"inside a traced function")
    return None


def check_module(src: ModuleSource) -> CheckResult:
    result = CheckResult()
    index = _ModuleIndex(src.tree)
    roots = _jit_roots(index, src.tree)
    seen_lines: set[int] = set()
    for fn, _cls in _reachable(index, roots):
        fn_name = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            effect = _host_effect(node)
            if effect is None or node.lineno in seen_lines:
                continue
            seen_lines.add(node.lineno)
            if src.waiver(node.lineno, "host-effect") is not None:
                result.waivers += 1
                continue
            result.findings.append(Finding(
                CHECKER, src.path, node.lineno,
                f"{effect} — reachable from a jax.jit boundary via "
                f"{fn_name}()"))
    return result


def check_paths(paths: list[str]) -> CheckResult:
    result = CheckResult()
    for path in paths:
        result.merge(check_module(ModuleSource(path)))
    return result
