"""Jit-purity checker: no host effects reachable from a jit boundary.

A function is a *jit root* when it is decorated `@jax.jit` /
`@partial(jax.jit, ...)`, or passed to `jax.jit(fn)` as a module-level
function, same-class method (`jax.jit(self._step)`), or inline lambda.
From each root the checker walks the PACKAGE-WIDE call graph
(tools/apexlint/callgraph.py): calls to module-level functions (local
or imported via `from x import y`), to `self.<method>` including
methods inherited from base classes in other modules, and to
`alias.fn` on known module aliases (`from ape_x_dqn_tpu.obs import
learning as learn_obs` — the jits call `learn_obs.sgd_diag` and the
checker follows it into obs/learning.py). Host-effect calls anywhere
in the reachable bodies are flagged:

- wall-clock reads / sleeps (`time.time`, `time.monotonic`, ...)
- `print(...)` (use `jax.debug.print` inside traced code)
- `.item()` — a blocking device->host transfer that also leaks tracers
- `np.asarray` / `np.array` / `np.frombuffer` on traced values
- metric/trace emission (`obs.observe/gauge/count/...`,
  `metrics.log/write`, `trace.span`) — host I/O that silently turns
  into a tracer leak or a retrace

Inside jit these either fail loudly (tracer leak), or worse, succeed
once at trace time and then never run again — a metric that reports
the compile-time value forever. Waive a deliberate trace-time effect
with `# apexlint: host-effect(<why>)` on the effect's line (the line
in the module where the effect lives, which may not be the module
with the jit boundary).

`check_paths([one_file])` degenerates to the v1 module-local pass —
imports that leave the scan set resolve to None and stay opaque.
"""

from __future__ import annotations

import ast

from tools.apexlint.callgraph import CallGraph, ClassInfo, FuncInfo
from tools.apexlint.common import (
    CheckResult, Finding, ModuleSource, dotted_name)

CHECKER = "jit-purity"

TIME_EFFECTS = {"time", "monotonic", "perf_counter", "perf_counter_ns",
                "time_ns", "sleep"}
NUMPY_EFFECTS = {"asarray", "array", "frombuffer", "copyto", "save"}
# emission methods flagged only on obs/metrics/registry-ish receivers,
# so `q.count(x)` on a plain container does not false-positive
EMIT_METHODS = {"observe", "observe_many", "gauge", "count",
                "counter", "histogram", "span", "publish", "log",
                "write", "beat"}
EMIT_RECEIVERS = {"obs", "obs_", "_obs", "metrics", "_metrics",
                  "registry", "_reg", "_registry", "tracer", "_tracer",
                  "trace", "heartbeat", "_heartbeats"}


def _is_jax_jit(node: ast.expr) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def jit_decorator(fn: ast.FunctionDef | ast.AsyncFunctionDef
                  ) -> ast.expr | None:
    """The decorator expression that makes `fn` a jit, or None."""
    for dec in fn.decorator_list:
        if _is_jax_jit(dec):
            return dec
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func):
                return dec
            # @partial(jax.jit, ...)
            if (dotted_name(dec.func) in ("partial", "functools.partial")
                    and dec.args and _is_jax_jit(dec.args[0])):
                return dec
    return None


def _jit_decorated(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    return jit_decorator(fn) is not None


def _jit_roots(graph: CallGraph) -> list[FuncInfo]:
    """Every jit boundary across the scanned modules."""
    roots: list[FuncInfo] = []
    for mod in graph.modules:
        for fn in mod.functions.values():
            if _jit_decorated(fn.node):
                roots.append(fn)
        for cls in mod.classes.values():
            for fn in cls.methods.values():
                if _jit_decorated(fn.node):
                    roots.append(fn)

        # jax.jit(<arg>) callsites anywhere in the module
        def walk(node: ast.AST, cls: ClassInfo | None) -> None:
            if isinstance(node, ast.ClassDef):
                owner = mod.classes.get(node.name)
                for child in ast.iter_child_nodes(node):
                    walk(child, owner)
                return
            if isinstance(node, ast.Call) and _is_jax_jit(node.func):
                if node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name):
                        target = mod.functions.get(arg.id)
                        if target is not None:
                            roots.append(target)
                    elif isinstance(arg, ast.Lambda):
                        roots.append(FuncInfo(arg, mod, cls))
                    elif (isinstance(arg, ast.Attribute)
                          and isinstance(arg.value, ast.Name)
                          and arg.value.id == "self" and cls is not None):
                        target = graph.lookup_method(cls, arg.attr)
                        if target is not None:
                            roots.append(target)
            for child in ast.iter_child_nodes(node):
                walk(child, cls)

        walk(mod.src.tree, None)
    return roots


def _reachable(graph: CallGraph, roots: list[FuncInfo]) -> list[FuncInfo]:
    seen: set[int] = set()
    out: list[FuncInfo] = []
    work = list(roots)
    while work:
        fn = work.pop()
        if id(fn.node) in seen:
            continue
        seen.add(id(fn.node))
        out.append(fn)
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                target = graph.resolve_call(node, fn.module, fn.cls)
                if target is not None and id(target.node) not in seen:
                    work.append(target)
    return out


def _host_effect(call: ast.Call) -> str | None:
    """Describe the host effect of a call, or None when pure."""
    func = call.func
    if isinstance(func, ast.Name) and func.id == "print":
        return "print() (use jax.debug.print in traced code)"
    if not isinstance(func, ast.Attribute):
        return None
    name = dotted_name(func)
    if name is not None:
        head, _, attr = name.rpartition(".")
        if head == "time" and attr in TIME_EFFECTS:
            return f"time.{attr}() reads the host clock"
        if head in ("np", "numpy") and attr in NUMPY_EFFECTS:
            return (f"{head}.{attr}() forces a host round-trip on a "
                    f"traced value")
    if func.attr == "item" and not call.args and not call.keywords:
        return ".item() blocks on a device->host transfer"
    if func.attr in EMIT_METHODS:
        recv = func.value
        last = None
        if isinstance(recv, ast.Name):
            last = recv.id
        elif isinstance(recv, ast.Attribute):
            last = recv.attr
        if last in EMIT_RECEIVERS:
            return (f"metric/trace emission .{func.attr}() is host I/O "
                    f"inside a traced function")
    return None


def check_graph(graph: CallGraph) -> CheckResult:
    result = CheckResult()
    roots = _jit_roots(graph)
    seen_sites: set[tuple[str, int]] = set()
    for fn in _reachable(graph, roots):
        src = fn.module.src
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            effect = _host_effect(node)
            site = (src.path, node.lineno)
            if effect is None or site in seen_sites:
                continue
            seen_sites.add(site)
            if src.waiver(node.lineno, "host-effect") is not None:
                result.waivers += 1
                continue
            result.findings.append(Finding(
                CHECKER, src.path, node.lineno,
                f"{effect} — reachable from a jax.jit boundary via "
                f"{fn.name}()"))
    result.findings.sort(key=lambda f: (f.path, f.line))
    return result


def check_paths(paths: list[str]) -> CheckResult:
    return check_graph(CallGraph([ModuleSource(p) for p in paths]))
