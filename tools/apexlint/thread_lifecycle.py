"""thread-lifecycle: every thread must be stoppable, owned, and joined.

The fleet runs ~21 `threading.Thread` sites across 9 modules; PR 7's
wedged-thread drain hang was exactly a thread nobody could join on
teardown. The contract, per construction site:

- **retained**: the Thread object lands in an attribute, a registry
  (`self._slots[i] = t`, `self._threads.append(t)`), or a local that
  the same function later joins. A bare
  `threading.Thread(...).start()` is fire-and-forget — nothing can
  ever join it.
- **stoppable**: the resolved target function consults a stop signal
  (an `Event.is_set()`/`.wait()`, a stop-ish flag read, or an
  `is None` queue sentinel). A loop only the process's death can end
  is a wedge waiting for a watchdog.
- **joined, bounded**: somewhere in the owning scope (the class's
  methods for attribute retention, the enclosing function for locals)
  the thread is joined; every thread-shaped `.join()` must carry
  `timeout=` — an unbounded join converts a wedged worker into a
  wedged teardown (the PR 7 bug class).

Waive a deliberately detached thread at the construction (or join)
line — or on the comment line directly above it — with
`# apexlint: detached(reason)`; e.g. per-connection reader threads
that exit when their socket dies.

Heuristic edges, chosen to stay quiet on the real package: a thread
returned from a factory escapes ownership analysis (opaque); a target
that cannot be resolved through the call graph is not accused of
missing a stop signal; `"sep".join(parts)` is distinguished from
thread joins by call shape (thread joins take no positional args).
"""

from __future__ import annotations

import ast

from tools.apexlint.callgraph import CallGraph, ClassInfo, ModuleInfo
from tools.apexlint.common import (CheckResult, Finding, ModuleSource,
                                   dotted_name)

CHECKER = "thread-lifecycle"
WAIVER = "detached"

# identifier substrings that read as a shutdown flag consult
_STOP_HINTS = ("stop", "done", "shutdown", "halt", "closed", "quit",
               "retire", "drain", "exit", "running", "alive")


def _is_thread_ctor(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name in ("threading.Thread", "Thread")


def _kwarg(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_daemon(call: ast.Call) -> bool:
    v = _kwarg(call, "daemon")
    return isinstance(v, ast.Constant) and bool(v.value)


def _span_waived(src: ModuleSource, node: ast.AST) -> bool:
    # the line above the node counts too: Thread(...) constructions
    # rarely leave room for a trailing justification
    for line in range(node.lineno - 1,
                      (getattr(node, "end_lineno", None)
                       or node.lineno) + 1):
        if src.waiver(line, WAIVER) is not None:
            return True
    return False


def _base_attr(node: ast.expr) -> tuple[str | None, str | None]:
    """For a (possibly subscripted) store target: ('self', attr) for
    `self.X` / `self.X[i]`, (name, None) for `n` / `n[i]`, else
    (None, None)."""
    while isinstance(node, (ast.Subscript, ast.Starred)):
        node = node.value
    if isinstance(node, ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            return "self", node.attr
        return None, None
    if isinstance(node, ast.Name):
        return node.id, None
    return None, None


def _contains(node: ast.AST, target: ast.AST) -> bool:
    return any(n is target for n in ast.walk(node))


def _binding(stmt: ast.stmt, call: ast.Call
             ) -> tuple[str, str] | None:
    """How the constructed Thread is retained:
    ('attr', X)    stored on self (incl. registries self.X[i] = t)
    ('local', n)   bound to / appended onto a local name
    ('escape', '') returned or yielded: ownership leaves this scope
    None           not retained at all (fire-and-forget)
    """
    if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                base, attr = _base_attr(e)
                if base == "self" and attr:
                    return ("attr", attr)
                if base and base != "self":
                    return ("local", base)
        return None
    if isinstance(stmt, (ast.Return, ast.Expr)) and isinstance(
            getattr(stmt, "value", None), ast.Yield):
        return ("escape", "")
    if isinstance(stmt, ast.Return):
        return ("escape", "")
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        outer = stmt.value
        # self.X.append(t) / registry.add(t): retained in the receiver
        if (isinstance(outer.func, ast.Attribute)
                and outer.func.attr in ("append", "add", "insert")
                and any(_contains(a, call) for a in outer.args)):
            base, attr = _base_attr(outer.func.value)
            if base == "self" and attr:
                return ("attr", attr)
            if base and base != "self":
                return ("local", base)
        # threading.Thread(...).start(): the classic fire-and-forget
        return None
    return None


def _local_escape(fnode: ast.AST, name: str) -> str | None:
    """Where a local thread escapes its function: an attr name when it
    is stored on self (`self.X = t`, `self.X[i] = t`,
    `self.X.append(t)`), '<return>' when returned, else None."""
    def is_name(e: ast.expr) -> bool:
        return isinstance(e, ast.Name) and e.id == name
    returned = False
    for n in ast.walk(fnode):
        if isinstance(n, ast.Assign) and is_name(n.value):
            for t in n.targets:
                base, attr = _base_attr(t)
                if base == "self" and attr:
                    return attr  # retention beats a convenience return
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("append", "add", "insert") \
                and any(is_name(a) for a in n.args):
            base, attr = _base_attr(n.func.value)
            if base == "self" and attr:
                return attr
        if isinstance(n, ast.Return) and n.value is not None and (
                is_name(n.value) or (
                    isinstance(n.value, (ast.Tuple, ast.List))
                    and any(is_name(e) for e in n.value.elts))):
            returned = True
    return "<return>" if returned else None


def _shallow_walk(root: ast.AST):
    """Walk `root` without descending into nested function/class
    bodies — the per-function view `_functions_with_context` already
    yields those as their own entries."""
    stack = [root]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            yield child
            stack.append(child)


def _thread_joins(scope: ast.AST) -> list[ast.Call]:
    """Thread-shaped `.join(...)` calls in a scope: no positional args
    (string joins always pass the iterable positionally)."""
    out = []
    for n in ast.walk(scope):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join" and not n.args):
            out.append(n)
    return out


def _consults_stop(fnode: ast.AST, graph: CallGraph | None = None,
                   cls: ClassInfo | None = None, depth: int = 0) -> bool:
    saw_none_check = saw_break = False
    for n in ast.walk(fnode):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("is_set", "wait")):
            return True
        ident = None
        if isinstance(n, ast.Name):
            ident = n.id
        elif isinstance(n, ast.Attribute):
            ident = n.attr
        if ident and any(h in ident.lower() for h in _STOP_HINTS):
            return True
        if isinstance(n, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops) \
                and any(isinstance(c, ast.Constant) and c.value is None
                        for c in [n.left] + list(n.comparators)):
            saw_none_check = True
        if isinstance(n, (ast.Break, ast.Return)):
            saw_break = True
    if saw_none_check and saw_break:  # `if item is None: break` sentinel
        return True
    # thin wrappers (`def _loop(self): self._loop_inner()`) delegate
    # the consult one call down — follow self-method calls two hops
    if graph is not None and cls is not None and depth < 2:
        for n in ast.walk(fnode):
            if isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) and isinstance(
                    n.func.value, ast.Name) and n.func.value.id == "self":
                m = graph.lookup_method(cls, n.func.attr)
                if m is not None and _consults_stop(
                        m.node, graph, cls, depth + 1):
                    return True
    return False


def _resolve_target(graph: CallGraph, mod: ModuleInfo,
                    cls: ClassInfo | None, func_node: ast.AST,
                    target: ast.expr) -> tuple[str, ast.AST | None]:
    """(display name, resolved function node or None if opaque)."""
    if isinstance(target, ast.Lambda):
        return "<lambda>", target
    if isinstance(target, ast.Name):
        for n in ast.walk(func_node):  # nested worker defs first
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n.name == target.id:
                return target.id, n
        resolved = graph.resolve_symbol(mod, target.id)
        node = getattr(resolved, "node", None)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return target.id, node
        return target.id, None
    name = dotted_name(target)
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self" and cls is not None):
        m = graph.lookup_method(cls, target.attr)
        return name or target.attr, (m.node if m else None)
    return name or "<dynamic>", None


def _enclosing_stmt(func_node: ast.AST, call: ast.Call) -> ast.stmt | None:
    """Innermost statement inside `func_node` containing `call`."""
    best: ast.stmt | None = None
    for n in ast.walk(func_node):
        if isinstance(n, ast.stmt) and _contains(n, call):
            if best is None or _contains(best, n):
                best = n
    return best


def _class_info(mod: ModuleInfo, cls_node: ast.ClassDef | None
                ) -> ClassInfo | None:
    if cls_node is None:
        return None
    return mod.classes.get(cls_node.name)


def check_paths(paths: list[str]) -> CheckResult:
    res = CheckResult()
    sources = []
    for p in paths:
        try:
            sources.append(ModuleSource(p))
        except (SyntaxError, OSError):
            continue
    graph = CallGraph(sources)
    for mod in graph.modules:
        _check_module(graph, mod, res)
    return res


def _functions_with_context(tree: ast.Module):
    """Yield (func_node, enclosing ClassDef | None) for every function,
    attributing nested defs to their outermost enclosing function's
    class."""
    def visit(node: ast.AST, cls: ast.ClassDef | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from visit(child, child)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                yield child, cls
                yield from visit(child, cls)
            else:
                yield from visit(child, cls)
    yield from visit(tree, None)


def _check_module(graph: CallGraph, mod: ModuleInfo,
                  res: CheckResult) -> None:
    src = mod.src
    funcs = list(_functions_with_context(src.tree))

    # -- unbounded thread joins, anywhere ------------------------------
    # (one flat walk of the module tree sees each join exactly once)
    for jn in _thread_joins(src.tree):
        if _kwarg(jn, "timeout") is not None:
            continue
        if _span_waived(src, jn):
            res.waivers += 1
            continue
        res.findings.append(Finding(
            CHECKER, src.path, jn.lineno,
            "unbounded .join() — a wedged thread turns this into a "
            "wedged teardown; use join(timeout=...) or waive with "
            "# apexlint: detached(reason)"))

    # -- construction sites --------------------------------------------
    # the shallow walk attributes each call to exactly its innermost
    # enclosing function (nested defs are separate `funcs` entries)
    for fnode, cls_node in funcs:
        cls = _class_info(mod, cls_node)
        for call in _shallow_walk(fnode):
            if not (isinstance(call, ast.Call) and _is_thread_ctor(call)):
                continue
            if _span_waived(src, call):
                res.waivers += 1
                continue

            target = _kwarg(call, "target") or (
                call.args[0] if call.args else None)
            tname, tnode = ("<none>", None) if target is None else \
                _resolve_target(graph, mod, cls, fnode, target)
            if tnode is not None and not _consults_stop(tnode, graph,
                                                        cls):
                res.findings.append(Finding(
                    CHECKER, src.path, call.lineno,
                    f"thread target '{tname}' never consults a stop "
                    "signal (Event.is_set()/.wait(), a stop-ish flag, "
                    "or an `is None` sentinel) — the owner cannot shut "
                    "it down; waive with # apexlint: detached(reason)"))

            stmt = _enclosing_stmt(fnode, call)
            bind = _binding(stmt, call) if stmt is not None else None
            if bind is None:
                kind = "daemon" if _is_daemon(call) else "NON-daemon"
                res.findings.append(Finding(
                    CHECKER, src.path, call.lineno,
                    f"fire-and-forget {kind} thread: never retained in "
                    "an attribute, registry, or joined local — nothing "
                    "can join it on teardown; waive with "
                    "# apexlint: detached(reason)"))
                continue
            how, name = bind
            if how == "local":
                # a local that lands in a self registry afterwards
                # (`t = Thread(...); self._slots[i] = t`) is class-
                # retained; one that is returned escapes to the caller
                escaped_attr = _local_escape(fnode, name)
                if escaped_attr == "<return>":
                    how = "escape"
                elif escaped_attr is not None:
                    how, name = "attr", escaped_attr
            if how == "escape":
                continue  # factory hands ownership to the caller
            if how == "attr":
                scope_nodes = ([m.node for m in
                                graph.method_table(cls).values()]
                               if cls is not None else [fnode])
                where = (f"any method of {cls.name}" if cls is not None
                         else "the enclosing scope")
            else:
                scope_nodes = [fnode]
                where = f"function '{getattr(fnode, 'name', '?')}'"
            joins = [j for s in scope_nodes for j in _thread_joins(s)]
            if not joins:
                held = (f"self.{name}" if how == "attr" else
                        f"local '{name}'")
                res.findings.append(Finding(
                    CHECKER, src.path, call.lineno,
                    f"thread retained in {held} is never joined in "
                    f"{where} — teardown (close/stop/shutdown/retire) "
                    "must reach a bounded join(timeout=...); waive "
                    "with # apexlint: detached(reason)"))
