"""resource-lifecycle: OS resources must be released from teardown,
in the declared order.

PR 18 shipped (and fixed by hand) the whole bug family this checker
exists for: a SharedMemory segment closed before it was unlinked pins
the /dev/shm name forever; a probe segment on an error path leaks the
name; a bounded queue dropped on the floor strands the memoryviews
parked in it. The contract, per `self.X = <acquire>` site:

- the owning class must define a teardown method (one of
  close/stop/shutdown/retire/destroy/__exit__/__del__);
- from some teardown root, walking self-method calls, a release of
  `self.X` must be reachable:
    shm     -> .close() or .unlink()
    file    -> .close()
    socket  -> .close() or .shutdown()
    queue   -> any reference (drain loop, `put(None)` sentinel, .join)
- an ordering declared at the acquire site with
  `# apexlint: releases(X, unlink<close)` is verified against every
  teardown root's linearized body (self-calls inlined): within one
  root, `X.close()` must not precede `X.unlink()`.

Acquire kinds are recognized structurally: `SharedMemory(...)`,
`open(...)`, `socket.socket(...)` / `create_connection(...)`, and
bounded `queue.Queue(maxsize=...)`. Factory indirection is followed
through the call graph for shm/socket (e.g. `self._sock =
self._connect()` where _connect returns a create_connection result) —
but not for files/queues, where helpers routinely open-and-close
internally.

A `releases(...)` comment whose argument carries no `<` ordering is an
out-of-band waiver ("caller owns teardown"), counted like any other
waiver; ordering declarations are contracts, verified and not counted.
"""

from __future__ import annotations

import ast

from tools.apexlint.callgraph import (CallGraph, ClassInfo, FuncInfo,
                                      ModuleInfo)
from tools.apexlint.common import (CheckResult, Finding, ModuleSource,
                                   dotted_name)

CHECKER = "resource-lifecycle"
WAIVER = "releases"

TEARDOWN_NAMES = ("close", "stop", "shutdown", "retire", "destroy",
                  "__exit__", "__del__")

_RELEASE_OPS = {
    "shm": ("close", "unlink"),
    "file": ("close",),
    "socket": ("close", "shutdown"),
    # queue: any reference in teardown counts (drain / sentinel / join)
    "queue": (),
}


def _direct_kind(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name is None:
        return None
    if name.endswith("SharedMemory"):
        return "shm"
    if name in ("open", "io.open"):
        return "file"
    if name in ("socket.socket", "socket.create_connection",
                "create_connection"):
        return "socket"
    if name in ("queue.Queue", "Queue") and (
            call.args or any(kw.arg == "maxsize" for kw in call.keywords)):
        return "queue"
    return None


def _acquire_kind(graph: CallGraph, mod: ModuleInfo,
                  cls: ClassInfo | None, call: ast.Call,
                  depth: int = 0) -> str | None:
    kind = _direct_kind(call)
    if kind is not None:
        return kind
    if depth >= 3:
        return None
    resolved = graph.resolve_call(call, mod, cls)
    if not isinstance(resolved, FuncInfo):
        return None
    # factory indirection: only connection-shaped kinds (shm/socket);
    # file/queue helpers routinely acquire-and-release internally
    for n in ast.walk(resolved.node):
        if isinstance(n, ast.Call):
            k = _direct_kind(n)
            if k in ("shm", "socket"):
                return k
    for n in ast.walk(resolved.node):
        if isinstance(n, ast.Call):
            k = _acquire_kind(graph, resolved.module, resolved.cls, n,
                              depth + 1)
            if k in ("shm", "socket"):
                return k
    return None


def _self_attr_base(node: ast.expr) -> str | None:
    """'X' when node is a (possibly chained) `self.X...` expression."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id == "self":
            return node.attr
        node = node.value
    return None


def _releases_annotation(src: ModuleSource, node: ast.AST
                         ) -> tuple[str | None, list[tuple[str, str]]]:
    """(waiver_text, [(first_op, second_op), ...]) from a
    `# apexlint: releases(...)` on the acquire statement's lines.
    Orderings (`a<b`) make it a verified declaration; anything else
    makes it an out-of-band waiver."""
    # the line directly above the acquire counts too — multi-line
    # constructor calls rarely leave room for a trailing annotation
    for line in range(node.lineno - 1,
                      (getattr(node, "end_lineno", None)
                       or node.lineno) + 1):
        arg = src.waiver(line, WAIVER)
        if arg is None:
            continue
        orders = []
        free = []
        for part in arg.split(","):
            part = part.strip()
            if "<" in part:
                a, b = part.split("<", 1)
                orders.append((a.strip(), b.strip()))
            elif part:
                free.append(part)
        if orders:
            return None, orders  # declaration (the leading name is doc)
        return (arg or "waived"), []
    return None, []


class _Acquire:
    def __init__(self, cls: ClassInfo, attr: str, kind: str, line: int,
                 orders: list[tuple[str, str]]):
        self.cls = cls
        self.attr = attr
        self.kind = kind
        self.line = line
        self.orders = orders


def _method_calls_on(body: ast.AST, attr: str) -> list[tuple[str, int]]:
    """(op, line) for every `self.<attr>....op(...)` call in a scope,
    including one level of local aliasing: a local bound from an
    expression that mentions self.<attr> (`s = self._sock`,
    `for s in (self._sock, self._psock):`) carries the attr, so
    `s.close()` counts as a release of it — the canonical teardown
    shape `for s in (...): s.close()`."""
    aliases: set[str] = set()
    for n in ast.walk(body):
        src_expr = None
        tgt = None
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            src_expr, tgt = n.value, n.targets[0]
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            src_expr, tgt = n.iter, n.target
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            src_expr, tgt = n.context_expr, n.optional_vars
        if src_expr is None or not isinstance(tgt, ast.Name):
            continue
        if any(isinstance(m, ast.Attribute) and isinstance(
                m.value, ast.Name) and m.value.id == "self"
                and m.attr == attr for m in ast.walk(src_expr)):
            aliases.add(tgt.id)
    out = []
    for n in ast.walk(body):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            recv = n.func.value
            if _self_attr_base(recv) == attr or (
                    isinstance(recv, ast.Name) and recv.id in aliases):
                out.append((n.func.attr, n.lineno))
    return out


def _mentions_attr(body: ast.AST, attr: str) -> bool:
    for n in ast.walk(body):
        if isinstance(n, ast.Attribute) and isinstance(
                n.value, ast.Name) and n.value.id == "self" \
                and n.attr == attr:
            return True
    return False


def _reachable_from_teardown(graph: CallGraph, cls: ClassInfo
                             ) -> dict[str, FuncInfo]:
    table = graph.method_table(cls)
    roots = [n for n in TEARDOWN_NAMES if n in table]
    seen: dict[str, FuncInfo] = {}
    work = list(roots)
    while work:
        name = work.pop()
        if name in seen or name not in table:
            continue
        seen[name] = table[name]
        for n in ast.walk(table[name].node):
            if isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) and isinstance(
                    n.func.value, ast.Name) and n.func.value.id == "self":
                work.append(n.func.attr)
    return seen


def _linearized_ops(graph: CallGraph, cls: ClassInfo, root: FuncInfo,
                    attr: str) -> list[str]:
    """Ops on self.<attr> in source order through `root`, with
    self-method calls inlined (depth-bounded, cycle-guarded)."""
    table = graph.method_table(cls)
    out: list[str] = []

    def visit(node: ast.AST, stack: tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                if isinstance(child.func, ast.Attribute):
                    recv = child.func.value
                    if _self_attr_base(recv) == attr:
                        out.append(child.func.attr)
                    elif (isinstance(recv, ast.Name)
                          and recv.id == "self"
                          and child.func.attr in table
                          and child.func.attr not in stack
                          and len(stack) < 6):
                        callee = table[child.func.attr]
                        visit(callee.node,
                              stack + (child.func.attr,))
            visit(child, stack)

    visit(root.node, (root.name,))
    return out


def check_paths(paths: list[str]) -> CheckResult:
    res = CheckResult()
    sources = []
    for p in paths:
        try:
            sources.append(ModuleSource(p))
        except (SyntaxError, OSError):
            continue
    graph = CallGraph(sources)
    for mod in graph.modules:
        for cls in mod.classes.values():
            _check_class(graph, mod, cls, res)
    return res


def _check_class(graph: CallGraph, mod: ModuleInfo, cls: ClassInfo,
                 res: CheckResult) -> None:
    src = mod.src
    acquires: list[_Acquire] = []
    for meth in cls.methods.values():
        for stmt in ast.walk(meth.node):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)) \
                    or not isinstance(
                        getattr(stmt, "value", None), ast.Call):
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            attrs = [t.attr for t in targets
                     if isinstance(t, ast.Attribute)
                     and isinstance(t.value, ast.Name)
                     and t.value.id == "self"]
            if not attrs:
                continue
            kind = _acquire_kind(graph, mod, cls, stmt.value)
            if kind is None:
                continue
            waived, orders = _releases_annotation(src, stmt)
            if waived is not None:
                res.waivers += 1
                continue
            acquires.append(_Acquire(cls, attrs[0], kind, stmt.lineno,
                                     orders))
    if not acquires:
        return

    reachable = _reachable_from_teardown(graph, cls)
    table = graph.method_table(cls)
    roots = [table[n] for n in TEARDOWN_NAMES if n in table]

    for acq in acquires:
        if not roots:
            res.findings.append(Finding(
                CHECKER, src.path, acq.line,
                f"{cls.name} holds a {acq.kind} in self.{acq.attr} but "
                f"defines no teardown method "
                f"({'/'.join(TEARDOWN_NAMES[:5])}) — the resource "
                "leaks by construction; add one or waive with "
                "# apexlint: releases(reason)"))
            continue
        release_ops = _RELEASE_OPS[acq.kind]
        released = False
        for meth in reachable.values():
            if release_ops:
                if any(op in release_ops for op, _ in
                       _method_calls_on(meth.node, acq.attr)):
                    released = True
                    break
            elif _mentions_attr(meth.node, acq.attr):
                released = True  # queue: drained / sentineled / joined
                break
        if not released:
            want = ("/".join(release_ops) if release_ops
                    else "a drain or sentinel")
            res.findings.append(Finding(
                CHECKER, src.path, acq.line,
                f"self.{acq.attr} ({acq.kind}) has no release ({want}) "
                f"reachable from any teardown method of {cls.name} "
                f"({', '.join(sorted(reachable))}) — released objects "
                "stranded at shutdown; waive with "
                "# apexlint: releases(reason)"))
            continue
        for first, second in acq.orders:
            for root in roots:
                ops = _linearized_ops(graph, cls, root, acq.attr)
                if first in ops and second in ops and \
                        ops.index(second) < ops.index(first):
                    res.findings.append(Finding(
                        CHECKER, src.path, acq.line,
                        f"teardown '{root.name}' releases "
                        f"self.{acq.attr} out of declared order: "
                        f"{second}() runs before {first}() (declared "
                        f"releases({acq.attr}, {first}<{second})) — "
                        "the PR 18 close-pins-mapping class"))
