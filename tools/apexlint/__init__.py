"""apexlint: project-native static analysis for the Ape-X runtime.

Thirteen stdlib-only AST checkers over the package source (no imports
of the code under analysis, no third-party deps). The v1 five are
single-file passes; v2 added a shared cross-module call graph
(callgraph.py) and whole-program dataflow checkers; v3 adds
thread/resource lifecycle analysis and statically-enforced accounting
closures on the same graph:

- guarded-by       lock discipline for `# guarded-by: <lock>` attrs
- jit-purity       no host effects reachable from jax.jit boundaries
                   (package-wide reachability through imports, module
                   aliases, and cross-module inheritance)
- wire-protocol    every MSG_* handled in every dispatch chain
- obs-names        emitted instruments <-> obs/report.py table
- retry-annotation swallowed socket errors in comm/runtime must emit
                   an accounting bump or carry `# apexlint: lossy(...)`
- remediation-accounting
                   every fleet-actuator call site in runtime/ bumps a
                   remediation_* counter or carries
                   `# apexlint: unaccounted(...)`
- use-after-donate no reads of a buffer after it was donated to a
                   `donate_argnums` jit without an intervening rebind
- host-sync        no hidden `.item()`/`np.asarray`/`float()`/
                   `block_until_ready` device syncs in the hot-path
                   modules outside obs windows
- config-coverage  every configs.py dataclass field is read somewhere;
                   every README `replay./comm./obs./actors.` knob exists
- learner-parity   the four learner variants' jitted endpoint surfaces
                   (names, donation pattern, metrics["diag"] threading)
                   stay in lockstep (ROADMAP item 5's enforcement)
- thread-lifecycle every threading.Thread is retained, its target
                   consults a stop signal, and teardown reaches a
                   bounded join(timeout=...) — unbounded joins and
                   fire-and-forget threads are findings
                   (`# apexlint: detached(reason)` waives)
- resource-lifecycle
                   SharedMemory / file / socket / bounded-queue
                   acquires stored on self have a release reachable
                   from teardown, with declarable ordering
                   (`# apexlint: releases(_seg, unlink<close)` —
                   the PR 18 close-pins-mapping class)
- counter-closure  conservation laws declared at the counter-owning
                   class (`# apexlint: closure(evicted == stored +
                   dropped)`) verified at every LHS increment site by
                   suffix post-dominance; declarations double as a
                   debug-mode runtime assertion feed
                   (counter_closure.check_object)

CLI: `python -m tools.apexlint ape_x_dqn_tpu/ [--format=json|sarif]
[--changed-only <git-ref>] [--self]` exits 0 only with zero unwaived
findings; tests/test_apexlint.py runs it over the package as a tier-1
gate, and `--self` dogfoods the structural checkers on tools/ itself.
The dynamic companion (the lock-order witness) lives in
ape_x_dqn_tpu/obs/health.py, enabled under APEX_LOCK_WITNESS=1 by
tests/conftest.py.
"""

from __future__ import annotations

import os
import time

from tools.apexlint import (
    config_coverage, counter_closure, guarded_by, host_sync, jit_purity,
    learner_parity, obs_names, remediation_accounting, resource_lifecycle,
    retry_annotation, thread_lifecycle, use_after_donate, wire_protocol)
from tools.apexlint.common import CheckResult, Finding, ModuleSource

__all__ = ["CheckResult", "Finding", "ModuleSource", "run",
           "package_files"]


def package_files(package_dir: str) -> list[str]:
    out: list[str] = []
    for root, dirs, files in os.walk(package_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for name in sorted(files):
            if name.endswith(".py"):
                out.append(os.path.join(root, name))
    return out


def run(package_dir: str,
        report_path: str | None = None,
        readme_path: str | None = None) -> dict:
    """Run all checkers over a package tree; returns the JSON-shaped
    summary the CLI, tests, and bench.py all consume.

    per_checker maps each checker to {"findings": n, "waivers": n,
    "ms": wall-clock} so waiver creep AND a checker gone slow are both
    attributable per rule in the bench artifact trail
    (`secondary.apexlint`); top-level `findings`/`waivers` stay the
    aggregate view. `closures` lists the counter-closure declarations
    the static pass verified — the debug-mode runtime hook
    (counter_closure.check_object) asserts the same laws on live
    objects in bench lanes.
    """
    paths = package_files(package_dir)
    total = CheckResult()
    per_checker: dict[str, dict[str, float]] = {}

    def fold(name: str, check) -> None:
        t0 = time.perf_counter()
        res = check()
        per_checker[name] = {"findings": len(res.findings),
                             "waivers": res.waivers,
                             "ms": round(
                                 (time.perf_counter() - t0) * 1e3, 2)}
        total.merge(res)

    fold("guarded-by", lambda: guarded_by.check_paths(paths))
    fold("jit-purity", lambda: jit_purity.check_paths(paths))
    fold("wire-protocol", lambda: wire_protocol.check_paths(paths))
    fold("retry-annotation",
         lambda: retry_annotation.check_paths(paths))
    fold("remediation-accounting",
         lambda: remediation_accounting.check_paths(paths))
    fold("use-after-donate",
         lambda: use_after_donate.check_paths(paths))
    fold("host-sync", lambda: host_sync.check_paths(paths))
    fold("learner-parity", lambda: learner_parity.check_paths(paths))
    fold("thread-lifecycle",
         lambda: thread_lifecycle.check_paths(paths))
    fold("resource-lifecycle",
         lambda: resource_lifecycle.check_paths(paths))
    fold("counter-closure",
         lambda: counter_closure.check_paths(paths))
    if readme_path is None:
        candidate = os.path.join(
            os.path.dirname(os.path.abspath(package_dir.rstrip(os.sep))),
            "README.md")
        readme_path = candidate if os.path.exists(candidate) else None
    fold("config-coverage",
         lambda: config_coverage.check(paths, readme_path=readme_path))
    if report_path is None:
        candidate = os.path.join(package_dir, "obs", "report.py")
        report_path = candidate if os.path.exists(candidate) else None
    if report_path is not None:
        fold("obs-names", lambda: obs_names.check(paths, report_path))
    return {
        "findings": [f.as_dict() for f in total.findings],
        "waivers": total.waivers,
        "per_checker": per_checker,
        "checked_files": len(paths),
        "closures": counter_closure.declarations(paths),
    }
