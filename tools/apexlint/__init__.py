"""apexlint: project-native static analysis for the Ape-X runtime.

Five stdlib-only AST checkers over the package source (no imports of
the code under analysis, no third-party deps):

- guarded-by   lock discipline for `# guarded-by: <lock>` attributes
- jit-purity   no host effects reachable from jax.jit boundaries
- wire-protocol every MSG_* handled in every dispatch chain
- obs-names    emitted instruments <-> obs/report.py table, both ways
- retry-annotation swallowed socket errors in comm/runtime must emit
  an obs counter/accounting bump or carry `# apexlint: lossy(reason)`

CLI: `python -m tools.apexlint ape_x_dqn_tpu/ [--format=json]`
exits 0 only with zero unwaived findings; tests/test_apexlint.py runs
it over the package as a tier-1 gate. The dynamic companion (the
lock-order witness) lives in ape_x_dqn_tpu/obs/health.py, enabled
under APEX_LOCK_WITNESS=1 by tests/conftest.py.
"""

from __future__ import annotations

import os

from tools.apexlint import (
    guarded_by, jit_purity, obs_names, retry_annotation, wire_protocol)
from tools.apexlint.common import CheckResult, Finding, ModuleSource

__all__ = ["CheckResult", "Finding", "ModuleSource", "run",
           "package_files"]


def package_files(package_dir: str) -> list[str]:
    out: list[str] = []
    for root, dirs, files in os.walk(package_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for name in sorted(files):
            if name.endswith(".py"):
                out.append(os.path.join(root, name))
    return out


def run(package_dir: str,
        report_path: str | None = None) -> dict:
    """Run all checkers over a package tree; returns the JSON-shaped
    summary the CLI, tests, and bench.py all consume."""
    paths = package_files(package_dir)
    total = CheckResult()
    per_checker: dict[str, int] = {}

    def fold(name: str, res: CheckResult) -> None:
        per_checker[name] = len(res.findings)
        total.merge(res)

    fold("guarded-by", guarded_by.check_paths(paths))
    fold("jit-purity", jit_purity.check_paths(paths))
    fold("wire-protocol", wire_protocol.check_paths(paths))
    fold("retry-annotation", retry_annotation.check_paths(paths))
    if report_path is None:
        candidate = os.path.join(package_dir, "obs", "report.py")
        report_path = candidate if os.path.exists(candidate) else None
    if report_path is not None:
        fold("obs-names", obs_names.check(paths, report_path))
    return {
        "findings": [f.as_dict() for f in total.findings],
        "waivers": total.waivers,
        "per_checker": per_checker,
        "checked_files": len(paths),
    }
