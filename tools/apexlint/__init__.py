"""apexlint: project-native static analysis for the Ape-X runtime.

Ten stdlib-only AST checkers over the package source (no imports of
the code under analysis, no third-party deps). The v1 five are
single-file passes; v2 added a shared cross-module call graph
(callgraph.py) and four whole-program dataflow checkers:

- guarded-by       lock discipline for `# guarded-by: <lock>` attrs
- jit-purity       no host effects reachable from jax.jit boundaries
                   (package-wide reachability through imports, module
                   aliases, and cross-module inheritance)
- wire-protocol    every MSG_* handled in every dispatch chain
- obs-names        emitted instruments <-> obs/report.py table
- retry-annotation swallowed socket errors in comm/runtime must emit
                   an accounting bump or carry `# apexlint: lossy(...)`
- remediation-accounting
                   every fleet-actuator call site in runtime/ bumps a
                   remediation_* counter or carries
                   `# apexlint: unaccounted(...)`
- use-after-donate no reads of a buffer after it was donated to a
                   `donate_argnums` jit without an intervening rebind
- host-sync        no hidden `.item()`/`np.asarray`/`float()`/
                   `block_until_ready` device syncs in the hot-path
                   modules outside obs windows
- config-coverage  every configs.py dataclass field is read somewhere;
                   every README `replay./comm./obs./actors.` knob exists
- learner-parity   the four learner variants' jitted endpoint surfaces
                   (names, donation pattern, metrics["diag"] threading)
                   stay in lockstep (ROADMAP item 5's enforcement)

CLI: `python -m tools.apexlint ape_x_dqn_tpu/ [--format=json|sarif]
[--changed-only <git-ref>] [--self]` exits 0 only with zero unwaived
findings; tests/test_apexlint.py runs it over the package as a tier-1
gate, and `--self` dogfoods the structural checkers on tools/ itself.
The dynamic companion (the lock-order witness) lives in
ape_x_dqn_tpu/obs/health.py, enabled under APEX_LOCK_WITNESS=1 by
tests/conftest.py.
"""

from __future__ import annotations

import os

from tools.apexlint import (
    config_coverage, guarded_by, host_sync, jit_purity, learner_parity,
    obs_names, remediation_accounting, retry_annotation,
    use_after_donate, wire_protocol)
from tools.apexlint.common import CheckResult, Finding, ModuleSource

__all__ = ["CheckResult", "Finding", "ModuleSource", "run",
           "package_files"]


def package_files(package_dir: str) -> list[str]:
    out: list[str] = []
    for root, dirs, files in os.walk(package_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for name in sorted(files):
            if name.endswith(".py"):
                out.append(os.path.join(root, name))
    return out


def run(package_dir: str,
        report_path: str | None = None,
        readme_path: str | None = None) -> dict:
    """Run all checkers over a package tree; returns the JSON-shaped
    summary the CLI, tests, and bench.py all consume.

    per_checker maps each checker to {"findings": n, "waivers": n} so
    waiver creep is attributable per rule in the bench artifact trail
    (`secondary.apexlint`); top-level `findings`/`waivers` stay the
    aggregate view.
    """
    paths = package_files(package_dir)
    total = CheckResult()
    per_checker: dict[str, dict[str, int]] = {}

    def fold(name: str, res: CheckResult) -> None:
        per_checker[name] = {"findings": len(res.findings),
                             "waivers": res.waivers}
        total.merge(res)

    fold("guarded-by", guarded_by.check_paths(paths))
    fold("jit-purity", jit_purity.check_paths(paths))
    fold("wire-protocol", wire_protocol.check_paths(paths))
    fold("retry-annotation", retry_annotation.check_paths(paths))
    fold("remediation-accounting",
         remediation_accounting.check_paths(paths))
    fold("use-after-donate", use_after_donate.check_paths(paths))
    fold("host-sync", host_sync.check_paths(paths))
    fold("learner-parity", learner_parity.check_paths(paths))
    if readme_path is None:
        candidate = os.path.join(
            os.path.dirname(os.path.abspath(package_dir.rstrip(os.sep))),
            "README.md")
        readme_path = candidate if os.path.exists(candidate) else None
    fold("config-coverage",
         config_coverage.check(paths, readme_path=readme_path))
    if report_path is None:
        candidate = os.path.join(package_dir, "obs", "report.py")
        report_path = candidate if os.path.exists(candidate) else None
    if report_path is not None:
        fold("obs-names", obs_names.check(paths, report_path))
    return {
        "findings": [f.as_dict() for f in total.findings],
        "waivers": total.waivers,
        "per_checker": per_checker,
        "checked_files": len(paths),
    }
