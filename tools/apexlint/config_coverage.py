"""Config-knob coverage checker: no dead knobs, no phantom docs.

Both directions of config/doc drift:

1. Every field declared in a `configs.py` dataclass must be READ
   somewhere in the package (an `x.<field>` attribute load or a
   `getattr(x, "<field>", ...)` outside configs.py itself). A field
   nobody reads is a knob the operator turns that does nothing — the
   worst kind of config bug, because the run silently ignores the
   intent (this checker's first catch: `actors.param_pull_every`,
   documented as the pull cadence and wired to nothing). Waive a
   deliberately-dormant field with `# apexlint: unread(<why>)` on its
   declaration line.

2. Every `replay.` / `comm.` / `obs.` / `actors.` / `serving.` knob
   mentioned in README must exist as a field on the matching dataclass
   (ReplayConfig / CommConfig / ObsConfig / ActorConfig /
   ServingConfig). Mentions
   that name a package MODULE instead of a knob (`obs.health`,
   `obs.report` — `ape_x_dqn_tpu/obs/health.py` exists) are skipped.

Reads are detected purely syntactically (any attribute load with the
field's name counts, whatever the receiver) — the checker errs quiet:
a false "read" hides a dead knob, a false "unread" would block CI on
working code. Dynamic access through the `--set dotted.key=value`
override machinery deliberately does NOT count as a read: being
settable is not being honored.
"""

from __future__ import annotations

import ast
import os
import re

from tools.apexlint.common import CheckResult, Finding, ModuleSource

CHECKER = "config-coverage"

PREFIX_TO_CLASS = {"replay": "ReplayConfig", "comm": "CommConfig",
                   "obs": "ObsConfig", "actors": "ActorConfig",
                   "serving": "ServingConfig",
                   "remediation": "RemediationConfig"}
KNOB_RE = re.compile(
    r"\b(replay|comm|obs|actors|serving|remediation)"
    r"\.([a-z_][a-z0-9_]*)")


def _is_dataclass(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = target.attr if isinstance(target, ast.Attribute) else (
            target.id if isinstance(target, ast.Name) else "")
        if name == "dataclass":
            return True
    return False


def dataclass_fields(src: ModuleSource) -> dict[str, dict[str, int]]:
    """{class name: {field name: declaration line}}."""
    out: dict[str, dict[str, int]] = {}
    for node in src.tree.body:
        if not (isinstance(node, ast.ClassDef) and _is_dataclass(node)):
            continue
        fields: dict[str, int] = {}
        for item in node.body:
            if isinstance(item, ast.AnnAssign) \
                    and isinstance(item.target, ast.Name):
                fields[item.target.id] = item.lineno
        out[node.name] = fields
    return out


def _attribute_reads(paths: list[str], skip: str) -> set[str]:
    reads: set[str] = set()
    for path in paths:
        if os.path.abspath(path) == skip:
            continue
        src = ModuleSource(path)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                reads.add(node.attr)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Name)
                  and node.func.id in ("getattr", "hasattr")
                  and len(node.args) >= 2
                  and isinstance(node.args[1], ast.Constant)
                  and isinstance(node.args[1].value, str)):
                reads.add(node.args[1].value)
    return reads


def _module_exists(paths: list[str], prefix: str, attr: str) -> bool:
    tail = os.path.join(prefix, f"{attr}.py")
    return any(os.path.normpath(p).endswith(tail) for p in paths)


def check(paths: list[str], configs_path: str | None = None,
          readme_path: str | None = None) -> CheckResult:
    result = CheckResult()
    if configs_path is None:
        configs_path = next(
            (p for p in paths
             if os.path.basename(p) == "configs.py"), None)
    if configs_path is None:
        return result
    configs_src = ModuleSource(configs_path)
    classes = dataclass_fields(configs_src)

    # direction 1: declared but never read
    reads = _attribute_reads(paths, os.path.abspath(configs_path))
    for cls_name, fields in classes.items():
        for field, line in fields.items():
            if field in reads:
                continue
            if configs_src.waiver(line, "unread") is not None:
                result.waivers += 1
                continue
            result.findings.append(Finding(
                CHECKER, configs_src.path, line,
                f"{cls_name}.{field} is declared (and settable via "
                f"--set) but read nowhere in the package — a knob "
                f"that does nothing; wire it or drop it"))

    # direction 2: README knobs that don't exist
    if readme_path and os.path.exists(readme_path):
        with open(readme_path, encoding="utf-8") as fh:
            for lineno, text in enumerate(fh, start=1):
                for m in KNOB_RE.finditer(text):
                    prefix, attr = m.group(1), m.group(2)
                    if attr == "py":
                        continue  # `remediation.py` is a filename
                    cls_name = PREFIX_TO_CLASS[prefix]
                    fields = classes.get(cls_name)
                    if fields is None or attr in fields:
                        continue
                    if _module_exists(paths, prefix, attr):
                        continue  # `obs.health` names a module, not a knob
                    result.findings.append(Finding(
                        CHECKER, readme_path, lineno,
                        f"README names knob {prefix}.{attr} but "
                        f"{cls_name} has no field `{attr}` — stale "
                        f"doc or missing config"))
    result.findings.sort(key=lambda f: (f.path, f.line))
    return result
