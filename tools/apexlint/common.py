"""Shared plumbing for the apexlint checkers.

Everything here is stdlib-only on purpose: the lint gate must run in
any environment that can run the tests (and in bench.py's subprocess),
with no dependency on jax/numpy being importable — the checkers parse
source, they never import the code under analysis.

A "waiver" is a trailing comment that acknowledges a finding and
suppresses it with a justification:

    self._dropped += 1  # apexlint: unguarded(single-writer stat)
    t0 = time.time()    # apexlint: host-effect(outside trace, timing arg)
    # apexlint: unhandled(MSG_LEGACY)          (wire-protocol checker)
    obs.gauge("scratch", v)  # apexlint: unlisted(debug-only gauge)

Waivers are counted and reported so creep is visible in the bench
trajectory (`secondary.apexlint.waivers`).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field

WAIVER_RE = re.compile(
    r"apexlint:\s*(?P<kind>[a-z-]+)\((?P<arg>[^)]*)\)")


@dataclass
class Finding:
    checker: str
    path: str
    line: int
    message: str

    def as_dict(self) -> dict:
        return {"checker": self.checker, "path": self.path,
                "line": self.line, "message": self.message}

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


@dataclass
class CheckResult:
    findings: list[Finding] = field(default_factory=list)
    waivers: int = 0

    def merge(self, other: "CheckResult") -> "CheckResult":
        self.findings.extend(other.findings)
        self.waivers += other.waivers
        return self


class ModuleSource:
    """One parsed module: AST plus a line -> comment-text map.

    `ast` drops comments, so annotations (`# guarded-by: _lock`) and
    waivers are recovered with `tokenize` and joined to AST nodes by
    line number.
    """

    def __init__(self, path: str, text: str | None = None):
        self.path = path
        if text is None:
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass  # torn file: AST parsed, comments best-effort

    def comment(self, line: int) -> str:
        return self.comments.get(line, "")

    def waiver(self, line: int, kind: str) -> str | None:
        """Return the waiver argument if `line` carries an
        `# apexlint: <kind>(...)` comment, else None."""
        m = WAIVER_RE.search(self.comment(line))
        if m and m.group("kind") == kind:
            return m.group("arg")
        return None

    def waivers_of_kind(self, kind: str) -> dict[int, str]:
        out = {}
        for line, text in self.comments.items():
            m = WAIVER_RE.search(text)
            if m and m.group("kind") == kind:
                out[line] = m.group("arg")
        return out


def attr_on_self(node: ast.expr) -> str | None:
    """'X' when node is `self.X`, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def self_attr_write_targets(node: ast.stmt) -> list[tuple[str, int]]:
    """(attr, line) for every `self.X ... =`-shaped write in a
    statement: plain/aug/ann assigns, tuple unpacks, and subscript
    stores (`self.X[i] = v` mutates the object self.X guards)."""
    targets: list[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    out: list[tuple[str, int]] = []

    def visit_target(t: ast.expr) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                visit_target(e)
            return
        if isinstance(t, ast.Starred):
            visit_target(t.value)
            return
        base = t
        while isinstance(base, ast.Subscript):
            base = base.value
        attr = attr_on_self(base)
        if attr is not None:
            out.append((attr, t.lineno))

    for t in targets:
        visit_target(t)
    return out


def dotted_name(node: ast.expr) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
