"""Retry-annotation: swallowed socket errors must be observable.

The elastic-fleet contract (PR 7) is that EVERY dropped send, failed
pull, or torn connection surfaces somewhere an operator can see —
never a bare `except OSError: pass`. PR 16 extends the same contract
to `replay/`: the disk spill rung does real file IO off the ingest
thread, and a swallowed OSError there is a silently lost replay
segment — exactly the loss class this checker exists to surface.
PR 18's `comm/shm_transport.py` sits in the same scope: attaching or
unlinking a /dev/shm segment is file IO, and a swallowed failure
there silently downgrades a granted shm connection to TCP — that
downgrade must be counted (shm_fallbacks) or carry a lossy waiver
naming why the loss is benign. In
`comm/`, `runtime/`, and `replay/` modules, any except handler typed
on a socket-ish/IO error class
(OSError, ConnectionError and its subclasses, socket.error,
socket.timeout, TimeoutError, BrokenPipeError, InterruptedError) that
*swallows* the exception (no `raise` anywhere in the handler body)
must do at least one of:

- emit an obs signal: call a method named `count` / `inc` / `log` /
  `warning` / `error` / `exception` inside the handler, or
- bump an accounting attribute: `+=` onto a name containing `drop`,
  `error`, `disconnect`, or `fail`, or
- carry an explicit lossy waiver on the `except` line or on its
  first statement:

      except OSError:  # apexlint: lossy(close-path best effort)
          pass

The waiver text is the justification; waivers are counted so silent-
loss creep stays visible in the bench trajectory. Handlers that
re-raise (even conditionally) are exempt — they don't swallow.
"""

from __future__ import annotations

import ast

from tools.apexlint.common import CheckResult, Finding, ModuleSource

CHECKER = "retry-annotation"

# paths under these package segments are in scope: the transport and
# the runtime are where a swallowed socket error means silent data
# loss, and the replay tier (disk spill rung, PR 16) is where a
# swallowed file-IO error means a silently lost segment
SCOPE_SEGMENTS = ("/comm/", "/runtime/", "/replay/")

SOCKET_ERROR_NAMES = {
    "OSError", "IOError", "ConnectionError", "ConnectionResetError",
    "ConnectionRefusedError", "ConnectionAbortedError",
    "BrokenPipeError", "TimeoutError", "InterruptedError",
    "socket.error", "socket.timeout", "socket_mod.error",
    "socket_mod.timeout",
}

OBS_CALL_NAMES = {"count", "inc", "log", "warning", "error",
                  "exception"}

ACCOUNTING_SUBSTRINGS = ("drop", "error", "disconnect", "fail")


def _exc_names(node: ast.expr | None) -> list[str]:
    """Dotted names of the exception types an `except` clause catches
    (a Tuple catches several)."""
    if node is None:
        return []
    if isinstance(node, ast.Tuple):
        out: list[str] = []
        for e in node.elts:
            out.extend(_exc_names(e))
        return out
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return [".".join(reversed(parts))]
    return []


def _swallows(handler: ast.ExceptHandler) -> bool:
    """True when no `raise` is reachable anywhere in the handler body
    (nested function bodies don't count: a callback defined inside the
    handler doesn't re-raise on this path)."""
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Raise):
                return False
    return True


def _accounts(handler: ast.ExceptHandler) -> bool:
    """True when the handler emits an obs signal or bumps an
    accounting attribute."""
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                fn = node.func
                name = (fn.attr if isinstance(fn, ast.Attribute)
                        else fn.id if isinstance(fn, ast.Name)
                        else None)
                if name in OBS_CALL_NAMES:
                    return True
            if isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, ast.Add):
                target = node.target
                attr = (target.attr if isinstance(target, ast.Attribute)
                        else target.id if isinstance(target, ast.Name)
                        else "")
                if any(s in attr.lower()
                       for s in ACCOUNTING_SUBSTRINGS):
                    return True
            # handler delegates to a self._note_*/self._on_* helper:
            # the accounting lives one call down (the transport's
            # _note_send_failure pattern) — accept the delegation
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr.startswith(("_note_", "_on_")):
                return True
    return False


def check_module(src: ModuleSource) -> CheckResult:
    result = CheckResult()
    norm = src.path.replace("\\", "/")
    if not any(seg in norm for seg in SCOPE_SEGMENTS):
        return result
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        caught = _exc_names(node.type)
        if not any(n in SOCKET_ERROR_NAMES for n in caught):
            continue
        if not _swallows(node):
            continue
        if _accounts(node):
            continue
        # the waiver may sit on the `except` line or on the handler's
        # first statement (`pass  # apexlint: lossy(...)`)
        waiver_lines = [node.lineno]
        if node.body:
            waiver_lines.append(node.body[0].lineno)
        if any(src.waiver(ln, "lossy") is not None
               for ln in waiver_lines):
            result.waivers += 1
            continue
        result.findings.append(Finding(
            CHECKER, src.path, node.lineno,
            f"except {'/'.join(caught)} swallows a socket error "
            f"without emitting an obs counter or accounting bump — "
            f"count the loss or waive with "
            f"`# apexlint: lossy(reason)`"))
    return result


def check_paths(paths: list[str]) -> CheckResult:
    result = CheckResult()
    for path in paths:
        result.merge(check_module(ModuleSource(path)))
    return result
