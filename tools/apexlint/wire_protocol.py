"""Wire-protocol exhaustiveness: every MSG_* handled on both ends.

`comm/socket_transport.py` defines the protocol as module-level
`MSG_*` integer constants. A "dispatch chain" is any class whose body
references at least three distinct MSG_* names — in practice the
ingest server's reader loop and the client transport. Each MSG_*
constant must be referenced in *every* dispatch chain, or carry an
explicit module-level waiver:

    # apexlint: unhandled(MSG_LEGACY)

so a new message type added to one end cannot ship half-wired (the
PR-4 codec negotiation added MSG_EXPERIENCE_C to both ends by hand;
this makes the next one a lint failure instead of a runtime stall).
"""

from __future__ import annotations

import ast
import re

from tools.apexlint.common import CheckResult, Finding, ModuleSource

CHECKER = "wire-protocol"

MSG_NAME_RE = re.compile(r"^MSG_[A-Z0-9_]+$")
DISPATCH_MIN_REFS = 3


def _module_constants(tree: ast.Module) -> dict[str, int]:
    consts: dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (isinstance(target, ast.Name)
                    and MSG_NAME_RE.match(target.id)
                    and isinstance(node.value, ast.Constant)):
                consts[target.id] = node.value.value
    return consts


def _class_refs(cls: ast.ClassDef, names: set[str]) -> set[str]:
    refs: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Name) and node.id in names:
            refs.add(node.id)
    return refs


def check_module(src: ModuleSource) -> CheckResult:
    result = CheckResult()
    consts = _module_constants(src.tree)
    if not consts:
        return result
    names = set(consts)
    waived = {arg.strip() for arg in
              src.waivers_of_kind("unhandled").values()}
    chains = []
    for node in src.tree.body:
        if isinstance(node, ast.ClassDef):
            refs = _class_refs(node, names)
            if len(refs) >= DISPATCH_MIN_REFS:
                chains.append((node, refs))
    for cls, refs in chains:
        for name in sorted(names - refs):
            if name in waived:
                result.waivers += 1
                continue
            result.findings.append(Finding(
                CHECKER, src.path, cls.lineno,
                f"{name} is not handled in dispatch chain "
                f"{cls.name!r} (reference it or waive with "
                f"`# apexlint: unhandled({name})`)"))
    return result


def check_paths(paths: list[str]) -> CheckResult:
    result = CheckResult()
    for path in paths:
        result.merge(check_module(ModuleSource(path)))
    return result
