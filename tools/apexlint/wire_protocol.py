"""Wire-protocol exhaustiveness: every MSG_* handled on both ends.

`comm/socket_transport.py` defines the protocol as module-level
`MSG_*` integer constants. A "dispatch chain" is any class whose body
references at least three distinct MSG_* names — in practice the
ingest server's reader loop and the client transport. Each MSG_*
constant must be referenced in *every* dispatch chain, or carry an
explicit module-level waiver:

    # apexlint: unhandled(MSG_LEGACY)

so a new message type added to one end cannot ship half-wired (the
PR-4 codec negotiation added MSG_EXPERIENCE_C to both ends by hand;
this makes the next one a lint failure instead of a runtime stall).

ISSUE 19 adds a second protocol family one level down: the param
payload TAG. A MSG_PARAMS/MSG_PARAMS_PUSH body is sniffed by its
leading magic (`PARAMS_HDR_MAGIC` 'APXV' raw-versioned vs
`PARAMS_CODEC_MAGIC` 'APXC' delta-coded), so a parser that dispatches
on one tag but not the other is exactly the half-wired state the
MSG_* rule exists to catch — except it stalls only for peers that
negotiated the missing shape. Any class that references ONE
`PARAMS_*MAGIC` tag (threshold 1, not 3: the family is two members
and a single-tag parser IS the bug) must reference every tag declared
OR imported in its module, or waive it the same way:

    # apexlint: unhandled(PARAMS_HDR_MAGIC)

Imported tags count because the tags live in param_codec.py while the
client parser dispatching on them lives in socket_transport.py.
"""

from __future__ import annotations

import ast
import re

from tools.apexlint.common import CheckResult, Finding, ModuleSource

CHECKER = "wire-protocol"

MSG_NAME_RE = re.compile(r"^MSG_[A-Z0-9_]+$")
TAG_NAME_RE = re.compile(r"^PARAMS_[A-Z0-9_]*MAGIC$")
DISPATCH_MIN_REFS = 3
TAG_MIN_REFS = 1


def _module_constants(tree: ast.Module) -> dict[str, int]:
    consts: dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (isinstance(target, ast.Name)
                    and MSG_NAME_RE.match(target.id)
                    and isinstance(node.value, ast.Constant)):
                consts[target.id] = node.value.value
    return consts


def _module_tags(tree: ast.Module) -> set[str]:
    """Param payload-tag names assigned OR imported at module level."""
    tags: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if (isinstance(target, ast.Name)
                        and TAG_NAME_RE.match(target.id)):
                    tags.add(target.id)
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                name = alias.asname or alias.name
                if TAG_NAME_RE.match(name):
                    tags.add(name)
    return tags


def _class_refs(cls: ast.ClassDef, names: set[str]) -> set[str]:
    refs: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Name) and node.id in names:
            refs.add(node.id)
    return refs


def check_module(src: ModuleSource) -> CheckResult:
    result = CheckResult()
    waived = {arg.strip() for arg in
              src.waivers_of_kind("unhandled").values()}
    families = []
    consts = _module_constants(src.tree)
    if consts:
        families.append((set(consts), DISPATCH_MIN_REFS,
                         "dispatch chain"))
    tags = _module_tags(src.tree)
    if len(tags) > 1:
        # a module holding a single tag name has nothing to dispatch
        # between; the family check starts when a second shape exists
        families.append((tags, TAG_MIN_REFS, "payload-tag parser"))
    for names, min_refs, kind in families:
        chains = []
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                refs = _class_refs(node, names)
                if len(refs) >= min_refs:
                    chains.append((node, refs))
        for cls, refs in chains:
            for name in sorted(names - refs):
                if name in waived:
                    result.waivers += 1
                    continue
                result.findings.append(Finding(
                    CHECKER, src.path, cls.lineno,
                    f"{name} is not handled in {kind} "
                    f"{cls.name!r} (reference it or waive with "
                    f"`# apexlint: unhandled({name})`)"))
    return result


def check_paths(paths: list[str]) -> CheckResult:
    result = CheckResult()
    for path in paths:
        result.merge(check_module(ModuleSource(path)))
    return result
