"""Learner-parity checker: the four learner variants stay in lockstep.

`runtime/learner.py`, `parallel/dist_learner.py`,
`runtime/sequence_learner.py`, and `runtime/dpg_learner.py` each
re-implement the sample→loss→optimize→write-back cycle, so every
cross-cutting change must land four times (ROADMAP item 5 — PR 10
threaded the in-graph diagnostics through all four jits by hand).
Until the unification refactor collapses them, this checker is the
enforcement: it statically compares the learners' jitted entry-point
surfaces and flags drift.

Discovery — a "learner" is any class whose resolved method table
(own + inherited, across modules via the call graph: SequenceLearner
inherits SingleChipLearner from another file) contains a jit-decorated
`train_step` with `donate_argnums`. Only LEAF classes compare (a base
like SingleChipLearner is represented by its subclasses).

Compared per learner:
- endpoint NAMES: every jitted endpoint present on any learner must be
  present on all (or waived);
- DONATION/STATIC pattern: a shared endpoint whose
  `donate_argnums`/`static_argnums` differ from the modal signature is
  drift — donation asymmetry is exactly how a driver written against
  one learner corrupts state under another;
- `metrics["diag"]` threading: if any learner threads the in-graph
  diagnostics (a `"diag"` key anywhere in its method bodies), all must.

Waivers are deliberate-asymmetry declarations on the CLASS def line:
`# apexlint: parity(<text>)` — a finding is waived only when the
waiver text NAMES the endpoint (or `diag`) it excuses, so a blanket
waiver cannot silently absorb future drift on other endpoints.
"""

from __future__ import annotations

import ast
from collections import Counter

from tools.apexlint.callgraph import CallGraph, ClassInfo
from tools.apexlint.common import CheckResult, Finding, ModuleSource
from tools.apexlint.jit_purity import jit_decorator

CHECKER = "learner-parity"


def _int_tuple(node: ast.expr | None) -> tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


def _jit_signature(fn: ast.FunctionDef | ast.AsyncFunctionDef
                   ) -> tuple[tuple[int, ...], tuple[int, ...]] | None:
    """(donate_argnums, static_argnums) for a jit-decorated method."""
    dec = jit_decorator(fn)
    if dec is None:
        return None
    if not isinstance(dec, ast.Call):
        return ((), ())
    kwargs = {kw.arg: kw.value for kw in dec.keywords if kw.arg}
    return (tuple(sorted(_int_tuple(kwargs.get("donate_argnums")))),
            tuple(sorted(_int_tuple(kwargs.get("static_argnums")))))


def _surface(graph: CallGraph, cls: ClassInfo
             ) -> dict[str, tuple[tuple[int, ...], tuple[int, ...]]]:
    out = {}
    for name, fn in graph.method_table(cls).items():
        sig = _jit_signature(fn.node)
        if sig is not None:
            out[name] = sig
    return out


def _threads_diag(graph: CallGraph, cls: ClassInfo) -> bool:
    for fn in graph.method_table(cls).values():
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Constant) and node.value == "diag":
                return True
    return False


def _class_waiver(cls: ClassInfo) -> str | None:
    return cls.module.src.waiver(cls.node.lineno, "parity")


def _fmt_sig(sig: tuple[tuple[int, ...], tuple[int, ...]]) -> str:
    return f"donate={list(sig[0])}, static={list(sig[1])}"


def check_graph(graph: CallGraph) -> CheckResult:
    result = CheckResult()
    learners: list[ClassInfo] = []
    for mod in graph.modules:
        for cls in mod.classes.values():
            fn = graph.lookup_method(cls, "train_step")
            if fn is None:
                continue
            sig = _jit_signature(fn.node)
            if sig is not None and sig[0]:
                learners.append(cls)
    leaves = [c for c in learners if not graph.is_base_of_any(c)]
    if len(leaves) < 2:
        return result

    surfaces = {c.name: _surface(graph, c) for c in leaves}
    all_endpoints = sorted(set().union(*surfaces.values()))
    any_diag = any(_threads_diag(graph, c) for c in leaves)

    def emit(cls: ClassInfo, token: str, message: str) -> None:
        waiver = _class_waiver(cls)
        if waiver is not None and token in waiver:
            result.waivers += 1
            return
        result.findings.append(Finding(
            CHECKER, cls.module.src.path, cls.node.lineno, message))

    for cls in leaves:
        surface = surfaces[cls.name]
        others = [c.name for c in leaves if c.name != cls.name]
        for ep in all_endpoints:
            if ep not in surface:
                have = [n for n in others if ep in surfaces[n]]
                emit(cls, ep,
                     f"learner {cls.name} is missing jitted endpoint "
                     f"{ep}() (present on {', '.join(have)}) — the "
                     f"variants must stay in lockstep (ROADMAP item 5) "
                     f"or declare the asymmetry in a parity waiver")
                continue
            sigs = Counter(surfaces[n][ep] for n in surfaces
                           if ep in surfaces[n])
            modal, count = sigs.most_common(1)[0]
            if surface[ep] != modal and count > 1:
                emit(cls, ep,
                     f"learner {cls.name}.{ep}() has jit signature "
                     f"{_fmt_sig(surface[ep])} but the other learners "
                     f"use {_fmt_sig(modal)} — donation-pattern drift "
                     f"corrupts state for callers written against the "
                     f"majority contract")
        if any_diag and not _threads_diag(graph, cls):
            emit(cls, "diag",
                 f"learner {cls.name} does not thread "
                 f"metrics[\"diag\"] while the other learners do — "
                 f"the learning-health plane (PR 10) goes blind for "
                 f"this variant")
    result.findings.sort(key=lambda f: (f.path, f.line))
    return result


def check_paths(paths: list[str]) -> CheckResult:
    return check_graph(CallGraph([ModuleSource(p) for p in paths]))
